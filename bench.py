"""Benchmark harness — all five BASELINE.json configs.

Headline metric (unchanged since round 1): examples/sec/chip on the
Recommendation (ALS) template at MovieLens-25M scale (25M ratings,
162,541 users, 59,047 items). One "example" = one rating edge processed
through one full ALS iteration (both half-steps). The reference publishes
no numbers (BASELINE.md), so ``vs_baseline`` is measured against our own
single-host XLA-CPU run of the same program — the "Spark-free CPU ALS
reference anchor" from SURVEY.md §6.

``p50_predict_ms`` is measured THROUGH A LIVE QUERY SERVER: the trained
headline model is persisted to the real storage stack, deployed behind
``create_query_server``, and timed over HTTP ``POST /queries.json`` —
JSON binding, plugin hooks, serving.serve and the device scorer all
included. ``p50_inproc_ms`` keeps the round-1 in-process number for
continuity.

``phases`` decomposes the headline run (one extra profiled train, phases
serialized): host pack seconds, wire bytes + host→device seconds, pure
device-compute seconds, the device-only examples/sec that the tunneled
link hides, and achieved GFLOP/s (normal-equation build term).

``serving`` measures the live query server under load: sequential p50,
then 16 concurrent clients (qps/p50/p95), then the same with the
micro-batching aggregator coalescing concurrent queries into batched
device dispatches (PIO_TPU_SERVE_MICROBATCH_US).

``secondary`` covers the remaining BASELINE.json configs — each as
{value, cpu_anchor, vs_baseline} with the headline's own-CPU-anchor
discipline (same program, XLA-CPU device, subsampled workload):
  - classification      LogReg SGD (treeAggregate → psum all-reduce)
  - similarproduct      implicit ALS (MLlib trainImplicit analog)
  - textclassification  Pallas embedding-bag vs plain-XLA lowering
  - twotower            contrastive two-tower retrieval training
plus ``als_rank_sweep`` (rank 16/64/128 MXU scaling),
``eventserver_events_per_sec`` (HTTP ingest into sqlite + native
eventlog backends) and ``ingest.partitioned`` (the hash-partitioned
replicated log at N=1/2/4 partitions, with a replicated pass recording
``repl_lag_p95_ms`` from the send-to-ack histogram).

Output contract (round 5 — the driver records only the LAST 2000 chars
of stdout, and round 4's single fat JSON line was truncated FRONT-first,
losing the headline; see VERDICT r4 weak #1): the full detail blob
    {"metric": ..., "value": N, ..., "phases": {...},
     "serving": {...}, "secondary": {...}}
is written to ``BENCH_FULL.json`` next to this file, and stdout carries
exactly ONE compact summary line (≤1900 chars, built by
``build_summary``) with the headline value/vs_baseline, link probe,
device rate, pack_s, p50s, concurrent/pool QPS and per-config ratios.

Env knobs (for smoke runs): PIO_TPU_BENCH_EDGES, PIO_TPU_BENCH_ITERS,
PIO_TPU_BENCH_RANK, PIO_TPU_BENCH_CPU_EDGES, PIO_TPU_BENCH_QUERIES,
PIO_TPU_BENCH_SECONDARY=0 (skip the secondary block),
PIO_TPU_BENCH_RANKSWEEP=0 (skip the rank sweep),
PIO_TPU_BENCH_SCALE (0<s≤1 scales every secondary workload).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

# MovieLens-25M shape (ratings, users, movies)
ML25M_EDGES = 25_000_000
ML25M_USERS = 162_541
ML25M_ITEMS = 59_047


def _synth_ratings(n_edges: int, n_users: int, n_items: int, seed: int = 0):
    """Synthetic MovieLens-like COO ratings (zipf-ish item popularity)."""
    rng = np.random.default_rng(seed)
    user_idx = rng.integers(0, n_users, size=n_edges).astype(np.int32)
    # popularity-skewed items: square a uniform to bias toward low ids
    item_idx = (rng.random(n_edges) ** 2 * n_items).astype(np.int32)
    rating = (rng.integers(1, 11, size=n_edges) * 0.5).astype(np.float32)
    return user_idx, item_idx, rating


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _best_of(fn, repeats=3):
    """Best-of-``repeats`` wrapper over _timed_runs — used by stages
    where min time is the stable throughput estimate. Returns
    (seconds, last result)."""
    times, out = _timed_runs(fn, repeats)
    return times[0], out


def _timed_runs(fn, repeats=3):
    """Warmup/compile once, then ``repeats`` timed runs. Returns
    (sorted seconds list, last result) — callers report the MEDIAN as
    the headline (robust to the tunnel's bandwidth swings in either
    direction, where min overstates and mean understates) and may quote
    the best alongside."""
    fn()  # warmup/compile
    times, out = [], None
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn()
        times.append(time.perf_counter() - t0)
    return sorted(times), out


def _probe_link_mb_s(n_bytes: int = 32 << 20) -> float:
    """Same-session host→device bandwidth probe, so every recorded
    headline carries the link speed it was measured under (the tunnel
    swings ~2.5x run to run). Two gotchas measured on the axon tunnel:
    the buffer must be INCOMPRESSIBLE (a zeros put moved at "1.4 GB/s"),
    and ``device_put`` ACKS EARLY from a client-side send buffer — a
    device-side reduction over the data forces the upload to actually
    complete before the clock stops. 32 MB amortizes dispatch latency."""
    import jax
    import jax.numpy as jnp

    buf = np.random.default_rng(0).integers(
        0, 256, n_bytes, dtype=np.uint8
    )
    reduce = jax.jit(lambda x: jnp.max(x))

    def once():
        return float(jax.block_until_ready(reduce(jax.device_put(buf))))

    once()  # warm path + compile
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        once()
        best = min(best, time.perf_counter() - t0)
    return n_bytes / best / 1e6


def _probe_link_d2h_mb_s(n_bytes: int = 16 << 20) -> float:
    """Device→host companion probe for READBACK-bound stages (the
    upload probe measures the other direction, which an asymmetric
    tunnel can decouple). The probed array must be a COMPUTATION
    OUTPUT: ``device_get`` of a host-originated ``device_put`` array
    returns jax's retained host copy without touching the wire
    (measured "1.5 TB/s" on a ~20 MB/s tunnel). XOR with a nonzero
    scalar keeps the bytes incompressible; ``device_get`` is
    synchronous, so the upload probe's early-ack trap doesn't apply."""
    import jax
    import jax.numpy as jnp

    buf_dev = jax.device_put(np.random.default_rng(1).integers(
        0, 256, n_bytes, dtype=np.uint8
    ))
    scramble = jax.jit(lambda x, s: jnp.bitwise_xor(x, s))

    def fresh(k):
        # a NEW device-only result each time: jax caches the host copy
        # on an Array after its first pull, so re-getting one array
        # measures that cache, not the wire
        dev = scramble(buf_dev, jnp.uint8(k))
        jax.block_until_ready(dev)
        return dev

    jax.device_get(fresh(0))  # warm compile + path
    best = float("inf")
    for k in (1, 2):
        dev = fresh(k)
        t0 = time.perf_counter()
        jax.device_get(dev)
        best = min(best, time.perf_counter() - t0)
    return n_bytes / best / 1e6


def _link_meta(active: bool, d2h: bool = False) -> dict:
    """Same-moment link metadata for a wire-bound stage — empty on the
    CPU-anchor side, where a link probe is meaningless. One helper so
    the probe/round/attach sequence cannot drift between stages."""
    if not active:
        return {}
    if d2h:
        return {"link_d2h_mb_s": round(_probe_link_d2h_mb_s(), 1)}
    return {"link_mb_s": round(_probe_link_mb_s(), 1)}


# --------------------------------------------------------------- headline
def _time_train(ctx, u, i, r, n_users, n_items, cfg, repeats=5):
    """repeats=5 on the headline: the tunneled link's bandwidth swings
    ~2.5× between runs and the edge shipment is the dominant term. The
    caller reports the MEDIAN (tunnel-robust methodology) with the best
    alongside. Returns (sorted seconds, factors)."""
    from pio_tpu.models.als import train_als

    return _timed_runs(
        lambda: train_als(ctx, u, i, r, n_users, n_items, cfg), repeats
    )


def _predict_p50_inproc_ms(factors, n_users: int, n_queries: int) -> float:
    """Round-1 continuity metric: the serving math in-process (no HTTP).
    Uses the same adaptive scorer the server uses."""
    from pio_tpu.ops.topn import DeviceTopNScorer

    scorer = DeviceTopNScorer(
        factors.user_factors, factors.item_factors, warmup=True
    )
    lat = []
    for q in range(n_queries):
        user = np.asarray([(q * 7919) % n_users], np.int32)
        t0 = time.perf_counter()
        scorer.top_n_batch(user, 10)
        lat.append(time.perf_counter() - t0)
    return float(np.percentile(np.array(lat) * 1000.0, 50))


# ------------------------------------------------- through-server serving
def _bench_server_p50(factors, n_users: int, n_items: int,
                      n_queries: int) -> dict:
    """Deploy the trained factors behind a real query server (storage
    round trip included) and measure HTTP ``POST /queries.json``:

    - sequential p50 (single client — the round-1/2 continuity metric)
    - concurrent load: 16 client threads → ``serving_qps`` + p50/p95
    - the same concurrent load with the micro-batching aggregator on
      (``PIO_TPU_SERVE_MICROBATCH_US``) — concurrent queries coalesce
      into one batched device dispatch (``algo.batch_predict``)
    """
    from pio_tpu.controller import (
        Algorithm, DataSource, Engine, FirstServing, IdentityPreparator,
        register_engine,
    )
    from pio_tpu.controller.engine import EngineParams
    from pio_tpu.controller.params import EmptyParams
    from pio_tpu.data.bimap import BiMap
    from pio_tpu.templates.recommendation import ALSModel, Query
    from pio_tpu.workflow.core_workflow import run_train
    from pio_tpu.workflow.engine_json import variant_from_dict

    class BenchDataSource(DataSource):
        def read_training(self, ctx):
            return None

    class BenchServeAlgorithm(Algorithm):
        """Serves the pre-trained headline factors (train wraps, not fits —
        the server benchmark measures serving, not a second training)."""

        query_class = Query

        def train(self, ctx, pd):
            return ALSModel(
                factors,
                BiMap({f"u{i}": i for i in range(n_users)}),
                BiMap({f"i{i}": i for i in range(n_items)}),
            )

        def predict(self, model, query):
            from pio_tpu.templates.recommendation import predict_user_topn

            return predict_user_topn(
                model, query, model.user_index, model.item_index
            )

        def batch_predict(self, model, indexed_queries):
            from pio_tpu.templates.recommendation import batched_user_topn

            return batched_user_topn(
                self, model, indexed_queries, model.user_index,
                model.item_index, model.scorer,
            )

        def warmup_query(self, model):
            return Query(user="u0")

        def prepare_for_serving(self, model):
            model.scorer(warmup=True)
            return model

    register_engine("bench.recommendation")(
        lambda: Engine(
            BenchDataSource, IdentityPreparator,
            {"als": BenchServeAlgorithm}, FirstServing,
        )
    )
    variant = variant_from_dict({
        "id": "bench-recommendation",
        "version": "1",
        "engineFactory": "bench.recommendation",
        "algorithms": [{"name": "als", "params": {}}],
    })
    engine_params = EngineParams(
        algorithm_params_list=(("als", EmptyParams()),)
    )
    from pio_tpu.workflow.engine_json import build_engine

    engine, _ = build_engine(variant)
    run_train(engine, engine_params, variant)

    out = {}
    server, _service, post = _serve_single(variant, 0)
    out["time_to_ready_s"] = server.time_to_ready_s
    try:
        got = post({"user": "u1", "num": 10})  # warm (compile + route)
        assert got.get("itemScores"), got
        lat = []
        for q in range(n_queries):
            body = {"user": f"u{(q * 7919) % n_users}", "num": 10}
            t0 = time.perf_counter()
            post(body)
            lat.append(time.perf_counter() - t0)
        out["p50_ms"] = round(
            float(np.percentile(np.array(lat) * 1000.0, 50)), 3
        )
        out["concurrent"] = _with_metrics_delta(
            server.port, lambda: _concurrent_stage(server.port, n_users)
        )
        # per-stage latency budget of everything served above: where the
        # e2e milliseconds went (accept→…→write), and how much of the
        # average the stage spans actually attribute (the residual is the
        # instrumentation's blind spot — the acceptance bar is ≥95%)
        import urllib.request as _ur

        with _ur.urlopen(
            f"http://127.0.0.1:{server.port}/debug/hotpath.json",
            timeout=10,
        ) as resp:
            out["latency_budget"] = json.loads(resp.read().decode("utf-8"))
    finally:
        post.close()
        server.stop()

    try:
        server, service, post = _serve_single(variant, microbatch_us=1500)
        try:
            # warm until the adaptive probe settles (or caps out) so the
            # timed stage measures the POST-decision steady state
            post({"user": "u1", "num": 10})
            _drive_until_decided(server.port, service, n_users)
            out["concurrent_microbatch"] = _with_metrics_delta(
                server.port,
                lambda: _concurrent_stage(server.port, n_users),
            )
            out["concurrent_microbatch"]["time_to_ready_s"] = (
                server.time_to_ready_s
            )
            mb = service._batcher.to_dict()
            out["concurrent_microbatch"]["mode"] = mb["mode"]
            out["concurrent_microbatch"]["mode_by_bucket"] = mb.get(
                "modeByBucket", {}
            )
            out["concurrent_microbatch"]["probe"] = mb["probe"]
            out["concurrent_microbatch"]["avg_batch"] = round(
                mb["batchedQueries"] / max(1, mb["batches"]), 2
            )
            out["concurrent_microbatch"]["max_batch"] = mb["maxBatch"]
            # shape-bucket accounting: per-bucket dispatch counts, the
            # retrace counter (steady state should be flat — every count
            # beyond the warmup sweep is a lost compile on the hot path)
            # and the cache's own view (generation, warmed ladder)
            eng = service.variant.engine_id
            out["concurrent_microbatch"]["bucket_dispatches"] = {
                str(b): int(
                    service._bucket_dispatch_total.labels(eng, str(b)).value
                )
                for b in service._buckets.buckets
            }
            out["concurrent_microbatch"]["bucket_retraces"] = int(
                service._bucket_retrace_total.labels(eng).value
            )
            out["concurrent_microbatch"]["buckets"] = (
                service._buckets.to_dict()
            )
        finally:
            post.close()
            server.stop()
    except Exception as exc:
        print(f"# microbatch serving stage failed: {exc}", file=sys.stderr)

    try:
        out["overload"] = _bench_overload(
            variant, n_users, out["concurrent"]["qps"]
        )
    except Exception as exc:
        print(f"# overload serving stage failed: {exc}", file=sys.stderr)
    return out


def _bench_overload(variant, n_users: int, base_qps: float) -> dict:
    """Overload stage (ISSUE 3): re-serve the same engine with admission
    control capped at roughly HALF the measured concurrent capacity and
    drive the full 16-thread load against it — about 2× saturation. The
    interesting numbers are the control plane's, not the data plane's:
    what fraction was shed (429/503 + Retry-After), the p99 of the
    requests that WERE admitted (shedding exists to protect exactly
    this), and what fraction the stale cache answered instead
    (``X-Pio-Degraded: stale-cache``)."""
    import urllib.request

    from pio_tpu.server.query_server import create_query_server

    # budget: half the measured capacity with a token-thin burst (a deep
    # burst would absorb the whole stage); stale cache smaller than the
    # hot key space so the artifact shows all three outcomes — admitted,
    # degraded (cache hit), shed (cache miss)
    rps = max(base_qps / 2.0, 20.0)
    spec = f"rps={rps:.0f},burst=8,cache=32"
    server, _service = create_query_server(
        variant, host="127.0.0.1", port=0, qos=spec
    )
    server.start()
    _wait_readyz(server.port)
    try:
        warm = _KeepAliveClient(server.port)
        try:
            # warm pass: compile/route warmup + seeds the stale cache so
            # degradation is possible from the first shed
            for q in range(min(n_users, 16)):
                warm({"user": f"u{q}", "num": 10})
        finally:
            warm.close()
        got = _overload_stage(server.port, n_users)
        got["qos_spec"] = spec
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/qos.json", timeout=5.0
        ) as r:
            snap = json.loads(r.read().decode("utf-8"))
        got["server_shed"] = snap.get("shed")
        got["server_degraded"] = snap.get("degraded")
        got["server_admitted"] = snap.get("admitted")
        return got
    finally:
        server.stop()


def _bench_resident_serving(n_queries: int) -> dict:
    """Device-resident classification serving (ISSUE 8): the same
    trained engine served through the resident scorer on BOTH feature
    wires — int8 and float32 — over an identical steady window. The
    artifact records per-request host→device bytes on each wire and
    their ratio (the acceptance bar is ≥3×, i.e. the int8 wire ships at
    most a third of the float32 bytes), the steady-state donation hit
    rate (bar: ≥0.95), retraces over the window (bar: zero — the warmup
    sweep owns every compile), and wire parity (fraction of label
    disagreements between the wires; bar: ≤0.001). In-process, no HTTP:
    this stage isolates the wire + dispatch path from socket churn."""
    import datetime as dtm

    import pio_tpu.templates  # noqa: F401  (registers engine factories)
    from pio_tpu.controller import ComputeContext
    from pio_tpu.data import Event
    from pio_tpu.server.query_server import QueryServerService
    from pio_tpu.storage import Storage
    from pio_tpu.storage.records import App
    from pio_tpu.templates.classification import Query
    from pio_tpu.workflow.core_workflow import run_train
    from pio_tpu.workflow.engine_json import build_engine, variant_from_dict

    home = os.environ["PIO_TPU_HOME"]
    saved = {
        k: os.environ.get(k)
        for k in (
            "PIO_TPU_DEVICE_RESIDENT", "PIO_TPU_SERVE_WIRE",
            "PIO_TPU_BATCH_BUCKETS", "PIO_TPU_BUCKET_WARMUP",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE",
            "PIO_STORAGE_SOURCES_RESIDENT_TYPE",
            "PIO_STORAGE_SOURCES_RESIDENT_PATH",
        )
    }
    os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "RESIDENT"
    os.environ["PIO_STORAGE_SOURCES_RESIDENT_TYPE"] = "sqlite"
    os.environ["PIO_STORAGE_SOURCES_RESIDENT_PATH"] = os.path.join(
        home, "resident_bench"
    )
    # force residency on regardless of backend: the stage measures the
    # wire, and the CPU smoke run must exercise the same code path the
    # accelerator run does
    os.environ["PIO_TPU_DEVICE_RESIDENT"] = "1"
    os.environ["PIO_TPU_BATCH_BUCKETS"] = "1,2,4,8"
    os.environ["PIO_TPU_BUCKET_WARMUP"] = "1"
    Storage.reset()
    try:
        app_id = Storage.get_meta_data_apps().insert(
            App(0, "bench-resident")
        )
        # three linearly separable plans over three attrs — the smoke
        # engine's toy, big enough to train and assert parity on
        le = Storage.get_levents()
        t0 = dtm.datetime(2026, 3, 1, tzinfo=dtm.timezone.utc)
        rng = np.random.default_rng(7)
        n = 0
        for plan, hot in (("basic", 0), ("premium", 1), ("pro", 2)):
            for _ in range(8):
                attrs = rng.integers(0, 3, size=3)
                attrs[hot] += 6
                props = {f"attr{j}": int(attrs[j]) for j in range(3)}
                props["plan"] = plan
                le.insert(
                    Event("$set", "user", f"u{n}", properties=props,
                          event_time=t0 + dtm.timedelta(minutes=n)),
                    app_id,
                )
                n += 1
        variant = variant_from_dict({
            "id": "bench-resident",
            "engineFactory": "templates.classification",
            "datasource": {"params": {"app_name": "bench-resident"}},
            "algorithms": [{"name": "logreg", "params": {}}],
        })
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        run_train(engine, ep, variant, ctx=ctx)

        proto = np.array([9.0, 1.0, 1.0], np.float32)
        queries = [
            Query(attrs=tuple(float(v) for v in np.roll(proto, q % 3)))
            for q in range(n_queries)
        ]

        def one_wire(wire: str) -> tuple:
            os.environ["PIO_TPU_SERVE_WIRE"] = wire
            svc = QueryServerService(variant, ctx=ctx)
            if not svc._resident:
                raise RuntimeError("no resident scorer placed")
            sc = svc._resident[0]
            # snapshot AFTER the warmup sweep so the window's deltas are
            # pure steady state (the sweep's dispatches are deploy cost)
            h0, hit0, miss0 = (
                sc.h2d_bytes, sc.donation_hits, sc.donation_misses
            )
            r0 = svc._buckets.retraces
            labels = [svc._predict_one(q).label for q in queries]
            hits = sc.donation_hits - hit0
            misses = sc.donation_misses - miss0
            # device digest (ISSUE 17): the window is steady state, so
            # the watch's compile total must equal the warmup sweep's —
            # a live dispatch that compiled would show up here
            dp = svc.devwatch.payload()
            stats = {
                "wire": sc.wire,
                "h2d_bytes_per_request": round(
                    (sc.h2d_bytes - h0) / max(1, len(queries)), 1
                ),
                "donation_hit_rate": round(
                    hits / max(1, hits + misses), 4
                ),
                "retraces": svc._buckets.retraces - r0,
                "param_bytes": sc.placed_bytes,
                "device": {
                    "mode": dp.get("mode"),
                    "peak_bytes": max(
                        (d.get("peakBytes") or 0
                         for d in dp.get("devices") or []),
                        default=0,
                    ),
                    "compiles": (dp.get("compiles") or {}).get("total", 0),
                    "compile_seconds": round(sum(
                        float(r.get("seconds") or 0.0) for r in
                        ((dp.get("compiles") or {}).get("sites") or {})
                        .values()
                    ), 4),
                },
            }
            return labels, stats

        labels_i8, i8 = one_wire("int8")
        labels_f32, f32 = one_wire("float32")
        disagree = sum(
            1 for a, b in zip(labels_i8, labels_f32) if a != b
        )
        return {
            "queries": n_queries,
            "int8": i8,
            "float32": f32,
            "device": i8.get("device"),
            "h2d_ratio_f32_over_i8": round(
                f32["h2d_bytes_per_request"]
                / max(1e-9, i8["h2d_bytes_per_request"]), 2
            ),
            "donation_hit_rate": i8["donation_hit_rate"],
            "parity_delta": round(disagree / max(1, n_queries), 6),
        }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        Storage.reset()


def _bench_evfront(n_queries: int) -> dict:
    """Event-loop HTTP front vs the threaded baseline (ISSUE 13): the
    same trained classification engine served over HTTP behind both
    fronts (``PIO_TPU_HTTP_FRONT``), each driven by a serial raw-socket
    keep-alive client so every request's wall time is a clean e2e
    sample. The threaded front serves the JSON wire; the evloop front
    serves the packed int8 wire — the deployment the tentpole ships.
    Records per-front qps / p50 / admit+parse+serialize share of e2e,
    the evloop attributedFraction, and the speedup. Acceptance bar:
    evloop-packed >= 1.5x threaded-json qps with lower p50 and a
    strictly smaller overhead share on the same host."""
    import datetime as dtm
    import socket as socketlib

    import pio_tpu.templates  # noqa: F401  (registers engine factories)
    from pio_tpu.controller import ComputeContext
    from pio_tpu.data import Event
    from pio_tpu.server import create_query_server
    from pio_tpu.server.http import PACKED_QUERY_CONTENT_TYPE
    from pio_tpu.storage import Storage
    from pio_tpu.storage.records import App
    from pio_tpu.workflow.core_workflow import run_train
    from pio_tpu.workflow.engine_json import build_engine, variant_from_dict

    saved = {
        k: os.environ.get(k)
        for k in (
            "PIO_TPU_DEVICE_RESIDENT", "PIO_TPU_SERVE_WIRE",
            "PIO_TPU_BATCH_BUCKETS", "PIO_TPU_BUCKET_WARMUP",
            "PIO_TPU_HTTP_FRONT",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE",
            "PIO_STORAGE_SOURCES_MEM_TYPE",
        )
    }
    # in-memory storage throughout: this stage measures the HTTP front
    # and the wire, not the storage backend — a sqlite-backed store
    # adds a per-request cost that compresses the front-to-front ratio
    os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "MEM"
    os.environ["PIO_STORAGE_REPOSITORIES_METADATA_SOURCE"] = "MEM"
    os.environ["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "MEM"
    os.environ["PIO_STORAGE_SOURCES_MEM_TYPE"] = "memory"
    # packed wire requires a device-resident int8 scorer on both fronts
    os.environ["PIO_TPU_DEVICE_RESIDENT"] = "1"
    os.environ["PIO_TPU_SERVE_WIRE"] = "int8"
    os.environ["PIO_TPU_BATCH_BUCKETS"] = "1,2,4"
    os.environ["PIO_TPU_BUCKET_WARMUP"] = "1"
    Storage.reset()
    try:
        app_id = Storage.get_meta_data_apps().insert(App(0, "bench-evfront"))
        le = Storage.get_levents()
        t0 = dtm.datetime(2026, 3, 1, tzinfo=dtm.timezone.utc)
        rng = np.random.default_rng(7)
        n = 0
        for plan, hot in (("basic", 0), ("premium", 1), ("pro", 2)):
            for _ in range(8):
                attrs = rng.integers(0, 3, size=3)
                attrs[hot] += 6
                props = {f"attr{j}": int(attrs[j]) for j in range(3)}
                props["plan"] = plan
                le.insert(
                    Event("$set", "user", f"u{n}", properties=props,
                          event_time=t0 + dtm.timedelta(minutes=n)),
                    app_id,
                )
                n += 1
        variant = variant_from_dict({
            "id": "bench-evfront",
            "engineFactory": "templates.classification",
            "datasource": {"params": {"app_name": "bench-evfront"}},
            "algorithms": [{"name": "logreg", "params": {}}],
        })
        engine, ep = build_engine(variant)
        # no mesh: a size-1 mesh would pin a per-request explicit
        # device_put (sharded h2d path) on the scorer, burying the
        # front-to-front difference this stage exists to measure
        ctx = ComputeContext.local(seed=0)
        run_train(engine, ep, variant, ctx=ctx)

        body = {"attrs": [9.0, 1.0, 1.0]}
        json_payload = json.dumps(body).encode("utf-8")

        def mk_req(payload, ctype):
            return (b"POST /queries.json HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Type: " + ctype.encode("latin-1") + b"\r\n"
                    b"Content-Length: " + str(len(payload)).encode() +
                    b"\r\n\r\n" + payload)

        def read_one(sock, buf):
            # pop one Content-Length-framed response off the socket
            while True:
                he = buf.find(b"\r\n\r\n")
                if he >= 0:
                    cl = 0
                    for hline in bytes(buf[:he]).lower().split(b"\r\n"):
                        if hline.startswith(b"content-length:"):
                            cl = int(hline.split(b":", 1)[1])
                    if len(buf) >= he + 4 + cl:
                        out = bytes(buf[he + 4:he + 4 + cl])
                        del buf[:he + 4 + cl]
                        return out
                chunk = sock.recv(65536)
                if not chunk:
                    raise RuntimeError("keep-alive connection closed")
                buf += chunk

        def window(port, req, total):
            # ONE keep-alive connection, serial requests: every sample
            # is clean unloaded e2e latency — a concurrent client would
            # fold queueing delay into p50
            s = socketlib.create_connection(("127.0.0.1", port))
            s.setsockopt(socketlib.IPPROTO_TCP, socketlib.TCP_NODELAY, 1)
            buf = bytearray()
            lats = []
            try:
                w0 = time.perf_counter()
                for _ in range(total):
                    q0 = time.perf_counter()
                    s.sendall(req)
                    read_one(s, buf)
                    lats.append(time.perf_counter() - q0)
                took = time.perf_counter() - w0
            finally:
                s.close()
            lats.sort()
            return {
                "qps": round(total / took, 1),
                "p50_ms": round(lats[len(lats) // 2] * 1e3, 3),
            }

        def pooled(port, req, n_conns, total):
            # the ISSUE-13 deployment shape: many keep-alive client
            # connections, one outstanding request each, multiplexed in
            # ONE client thread (a thread-per-connection client would
            # spend more GIL time than either front under test). Each
            # sample is one connection's send→response wall time, so
            # p50 includes the server-side queueing the load creates.
            import selectors as sel_mod

            sel = sel_mod.DefaultSelector()
            socks = []
            for _ in range(n_conns):
                s = socketlib.create_connection(("127.0.0.1", port))
                s.setblocking(False)
                s.setsockopt(
                    socketlib.IPPROTO_TCP, socketlib.TCP_NODELAY, 1
                )
                socks.append(s)
                sel.register(s, sel_mod.EVENT_READ, [bytearray(), 0.0])
            sent = done = 0
            lats = []
            try:
                w0 = time.perf_counter()
                for s in socks:
                    sel.get_key(s).data[1] = time.perf_counter()
                    s.sendall(req)
                    sent += 1
                while done < total:
                    for key, _ in sel.select(10):
                        s, d = key.fileobj, key.data
                        buf = d[0]
                        chunk = s.recv(65536)
                        if not chunk:
                            raise RuntimeError(
                                "keep-alive connection closed"
                            )
                        buf += chunk
                        he = buf.find(b"\r\n\r\n")
                        while he >= 0:
                            cl = 0
                            for hline in bytes(buf[:he]).lower() \
                                    .split(b"\r\n"):
                                if hline.startswith(b"content-length:"):
                                    cl = int(hline.split(b":", 1)[1])
                            if len(buf) < he + 4 + cl:
                                break
                            del buf[:he + 4 + cl]
                            done += 1
                            lats.append(time.perf_counter() - d[1])
                            if sent < total:
                                d[1] = time.perf_counter()
                                s.sendall(req)
                                sent += 1
                            he = buf.find(b"\r\n\r\n")
                took = time.perf_counter() - w0
            finally:
                for s in socks:
                    sel.unregister(s)
                    s.close()
                sel.close()
            lats.sort()
            return {
                "qps": round(total / took, 1),
                "p50_ms": round(lats[len(lats) // 2] * 1e3, 3),
            }

        def get_json(port, path):
            s = socketlib.create_connection(("127.0.0.1", port))
            s.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
            try:
                return json.loads(read_one(s, bytearray()))
            finally:
                s.close()

        servers = {}
        fronts = {}
        try:
            for front, wire in (("threaded", "json"), ("evloop", "packed")):
                os.environ["PIO_TPU_HTTP_FRONT"] = front
                server, svc = create_query_server(
                    variant, host="127.0.0.1", port=0, ctx=ctx
                )
                server.start()
                if wire == "json":
                    req = mk_req(json_payload, "application/json")
                else:
                    req = mk_req(svc.pack_query_body(body),
                                 PACKED_QUERY_CONTENT_TYPE)
                servers[front] = (server, req, wire)
                window(server.port, req, max(32, n_queries // 8))  # settle
            # Phase 1 — interleaved serial windows (best-of-2): clean
            # unloaded e2e latency, and the cumulative traffic the
            # /debug/hotpath.json stage shares are computed over stays
            # pure serial (pooled load would fold queueing into e2e and
            # mechanically shrink every stage's share)
            for _ in range(2):
                for front, (server, req, wire) in servers.items():
                    w = window(server.port, req, n_queries)
                    cur = fronts.setdefault(
                        front,
                        {"wire": wire, "serial_qps": w["qps"],
                         "serial_p50_ms": w["p50_ms"]},
                    )
                    cur["serial_qps"] = max(cur["serial_qps"], w["qps"])
                    cur["serial_p50_ms"] = min(
                        cur["serial_p50_ms"], w["p50_ms"]
                    )
            for front, (server, req, wire) in servers.items():
                hp = get_json(server.port, "/debug/hotpath.json")
                e2e = hp["e2e"]["avgMs"]
                overhead = sum(
                    st["avgMs"] for st in hp.get("stages", ())
                    if st["stage"] in ("admit", "parse", "serialize")
                )
                fronts[front]["overhead_share"] = round(
                    overhead / max(1e-9, e2e), 4
                )
                if front == "evloop":
                    fronts[front]["attributed_fraction"] = hp.get(
                        "attributedFraction"
                    )
            # Phase 2 — interleaved pooled windows (best-of-3): the
            # headline. Both servers stay up and windows alternate front
            # by front, so host scheduling drift on a shared single-core
            # box lands on BOTH sides of the ratio instead of biasing
            # whichever front ran second.
            for _ in range(3):
                for front, (server, req, wire) in servers.items():
                    p = pooled(server.port, req, 16, 2 * n_queries)
                    cur = fronts[front]
                    if p["qps"] > cur.get("qps", 0.0):
                        cur["qps"] = p["qps"]
                        cur["pooled_p50_ms"] = p["p50_ms"]
        finally:
            for server, _, _ in servers.values():
                server.stop()

        ev, th = fronts["evloop"], fronts["threaded"]
        # headline: pooled-load qps, unloaded e2e p50 (the pooled p50
        # is queueing-dominated at saturation and tracks conns/qps, not
        # the front's per-request cost)
        return {
            "qps": ev["qps"],
            "p50_ms": ev["serial_p50_ms"],
            "speedup_x": round(ev["qps"] / max(1e-9, th["qps"]), 2),
            "evloop": ev,
            "threaded": th,
        }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        Storage.reset()


def _overload_stage(port: int, n_users: int, n_threads=16,
                    per_thread=40) -> dict:
    """16 threads at full speed against a rate-limited server; unlike
    ``_concurrent_stage`` the client tolerates 429/503 — those ARE the
    measurement."""
    import concurrent.futures

    # hot key space intentionally larger than the server's stale cache:
    # refused requests split between degraded (cached) and shed (not)
    key_space = min(n_users, 64)

    def worker(t):
        client = _RawIngestClient(port, "/queries.json")
        lats = []
        counts = {"admitted": 0, "degraded": 0, "shed": 0}
        try:
            for q in range(per_thread):
                body = json.dumps({
                    "user":
                        f"u{((t * per_thread + q) * 104729) % key_space}",
                    "num": 10,
                }).encode()
                t0 = time.perf_counter()
                try:
                    status = client.post(body)
                except (ConnectionError, OSError, RuntimeError):
                    client.close()
                    client = _RawIngestClient(port, "/queries.json")
                    continue
                dt = time.perf_counter() - t0
                if status in (429, 503):
                    counts["shed"] += 1
                elif b"x-pio-degraded" in client.last_head.lower():
                    counts["degraded"] += 1
                else:
                    counts["admitted"] += 1
                    lats.append(dt)
        finally:
            client.close()
        return lats, counts

    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(n_threads) as ex:
        results = list(ex.map(worker, range(n_threads)))
    wall = time.perf_counter() - t0
    lat = [l for ls, _ in results for l in ls]
    totals = {"admitted": 0, "degraded": 0, "shed": 0}
    for _, c in results:
        for k in totals:
            totals[k] += c[k]
    offered = sum(totals.values())
    ms = np.array(lat) * 1000.0 if lat else np.array([0.0])
    return {
        "offered": offered,
        "offered_qps": round(offered / wall, 1),
        "shed_rate": round(totals["shed"] / max(offered, 1), 3),
        "degraded_fraction": round(
            totals["degraded"] / max(offered, 1), 3
        ),
        "admitted": totals["admitted"],
        "admitted_p50_ms": round(float(np.percentile(ms, 50)), 3),
        "admitted_p99_ms": round(float(np.percentile(ms, 99)), 3),
    }


class _KeepAliveClient:
    """Persistent-connection query load-gen client (one per thread).
    Real SDKs/load balancers hold connections open — a fresh TCP
    handshake per request would measure the client's socket churn — and
    since round 5 the transport is the same raw-socket machinery as the
    ingest client (``_RawIngestClient``): on the single shared core,
    ``http.client``'s header build/parse cost ~100 µs/request, a third
    of the measured "serving QPS" budget going to the load generator
    itself. The JSON response is still parsed per call (a real SDK
    does)."""

    def __init__(self, port: int, path: str = "/queries.json"):
        self._port, self._path = port, path
        self._c = _RawIngestClient(port, path)

    def __call__(self, body: dict):
        payload = json.dumps(body).encode()
        for attempt in (0, 1):  # one reconnect on a dropped keep-alive
            try:
                status = self._c.post(payload)
                break
            except (ConnectionError, OSError, RuntimeError):
                if attempt:
                    raise
                self._c.close()
                self._c = _RawIngestClient(self._port, self._path)
        got = self._c.last_body
        if status >= 400:
            raise RuntimeError(
                f"{self._path}: HTTP {status} {got[:200]!r}"
            )
        return json.loads(got)

    def close(self):
        self._c.close()


def _wait_readyz(port: int, timeout: float = 30.0) -> float:
    """Poll ``GET /readyz`` until 200 (the orchestrator's view of
    startup); returns seconds waited."""
    import urllib.error
    import urllib.request

    t0 = time.perf_counter()
    deadline = t0 + timeout
    while time.perf_counter() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=2.0
            ) as r:
                if r.status == 200:
                    break
        except (urllib.error.HTTPError, OSError):
            pass
        time.sleep(0.02)
    return time.perf_counter() - t0


def _serve_single(variant, microbatch_us: int):
    from pio_tpu.server.query_server import create_query_server

    prev = os.environ.pop("PIO_TPU_SERVE_MICROBATCH_US", None)
    if microbatch_us:
        os.environ["PIO_TPU_SERVE_MICROBATCH_US"] = str(microbatch_us)
    t_boot = time.perf_counter()
    try:
        server, service = create_query_server(
            variant, host="127.0.0.1", port=0
        )
    finally:
        os.environ.pop("PIO_TPU_SERVE_MICROBATCH_US", None)
        if prev is not None:
            os.environ["PIO_TPU_SERVE_MICROBATCH_US"] = prev
    server.start()
    # time-to-ready: server construction (engine + model load) through
    # the first /readyz 200 — what a rolling deploy actually waits on
    _wait_readyz(server.port)
    server.time_to_ready_s = round(time.perf_counter() - t_boot, 4)
    return server, service, _KeepAliveClient(server.port)


def _scrape_metrics(port: int):
    """One ``GET /metrics`` scrape → ParsedMetrics (obs promparse)."""
    import urllib.request

    from pio_tpu.obs.promparse import parse_prometheus_text

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5.0
    ) as r:
        return parse_prometheus_text(r.read().decode("utf-8"))


def _metrics_delta(before, after) -> dict:
    """Server-side view of a bench stage: request/error counter deltas
    plus per-stage mean latency between two /metrics snapshots. Embedded
    in the artifact so a QPS regression can be localized (queue vs
    execute vs serialize) without re-running under a profiler."""
    fam_sum = lambda pm, name: sum(pm.family(name).values())
    out = {
        "queries": int(
            fam_sum(after, "pio_tpu_queries_total")
            - fam_sum(before, "pio_tpu_queries_total")
        ),
        "errors": int(
            fam_sum(after, "pio_tpu_query_errors_total")
            - fam_sum(before, "pio_tpu_query_errors_total")
        ),
    }
    stages: dict = {}
    for ls, cnt_after in after.family(
        "pio_tpu_query_stage_seconds_count"
    ).items():
        d = dict(ls)
        stage = d.pop("stage", "?")
        d["stage"] = stage
        dn = cnt_after - (
            before.value("pio_tpu_query_stage_seconds_count", **d) or 0.0
        )
        ds = (after.value("pio_tpu_query_stage_seconds_sum", **d) or 0.0) - (
            before.value("pio_tpu_query_stage_seconds_sum", **d) or 0.0
        )
        if dn > 0:  # aggregate across engine_id label values
            prev_n, prev_s = stages.get(stage, (0.0, 0.0))
            stages[stage] = (prev_n + dn, prev_s + ds)
    out["stage_avg_ms"] = {
        s: round(ds / dn * 1e3, 3) for s, (dn, ds) in sorted(stages.items())
    }
    return out


def _with_metrics_delta(port: int, stage_fn):
    """Run ``stage_fn()`` bracketed by /metrics snapshots; attach the
    delta as ``server_metrics`` (best-effort — a scrape failure never
    fails the bench stage)."""
    try:
        m0 = _scrape_metrics(port)
    except Exception:
        m0 = None
    got = stage_fn()
    if m0 is not None:
        try:
            got["server_metrics"] = _metrics_delta(m0, _scrape_metrics(port))
        except Exception as exc:
            print(f"# metrics delta scrape failed: {exc}", file=sys.stderr)
    try:
        got["device"] = _device_block(port)
    except Exception as exc:
        print(f"# device scrape failed: {exc}", file=sys.stderr)
    return got


def _device_block(port: int) -> dict:
    """Compact /device.json digest for a stage record (ISSUE 17): each
    stage runs against a fresh server, so the watch's totals ARE the
    stage's — peak bytes per device plus the compile-site attribution."""
    import urllib.request

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/device.json", timeout=5.0
    ) as r:
        data = json.loads(r.read().decode("utf-8"))
    compiles = data.get("compiles") or {}
    return {
        "mode": data.get("mode"),
        "peak_bytes": {
            str(d.get("device")): d.get("peakBytes")
            for d in data.get("devices") or []
        },
        "compiles": compiles.get("total", 0),
        "compile_seconds": round(sum(
            float(row.get("seconds") or 0.0)
            for row in (compiles.get("sites") or {}).values()
        ), 4),
        "headroom_bytes": data.get("headroomBytes"),
    }


def _concurrent_stage(port: int, n_users: int, n_threads=16,
                      per_thread=40, repeats=2) -> dict:
    """16 keep-alive client threads hammering /queries.json; best of
    ``repeats`` rounds (client and server share cores here, so one round
    can eat a scheduler hiccup)."""
    import concurrent.futures

    def worker(t):
        client = _KeepAliveClient(port)
        lats = []
        try:
            for q in range(per_thread):
                body = {
                    "user": f"u{((t * per_thread + q) * 104729) % n_users}",
                    "num": 10,
                }
                t0 = time.perf_counter()
                client(body)
                lats.append(time.perf_counter() - t0)
        finally:
            client.close()
        return lats

    best = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(n_threads) as ex:
            lat = [
                l for ls in ex.map(worker, range(n_threads)) for l in ls
            ]
        wall = time.perf_counter() - t0
        ms = np.array(lat) * 1000.0
        got = {
            "qps": round(len(lat) / wall, 1),
            "p50_ms": round(float(np.percentile(ms, 50)), 3),
            "p95_ms": round(float(np.percentile(ms, 95)), 3),
        }
        if best is None or got["qps"] > best["qps"]:
            best = got
    return best


def _drive_until_decided(port: int, service, n_users: int,
                         cap: int = 600) -> None:
    """Concurrent warm traffic until the adaptive micro-batcher settles."""
    import concurrent.futures

    def worker(t):
        client = _KeepAliveClient(port)
        try:
            for q in range(cap // 8):
                if service._batcher.mode in ("on", "off"):
                    return
                client({"user": f"u{(t * 131 + q) % n_users}", "num": 10})
        finally:
            client.close()

    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        list(ex.map(worker, range(8)))


_POOL_ENGINE_SRC = '''\
"""Spawn-importable serving engine for the bench worker-pool stage: wraps
pre-trained ALS factors stored beside this module (bench_factors.npz)."""
import os

import numpy as np

from pio_tpu.controller import (
    Algorithm, DataSource, Engine, FirstServing, IdentityPreparator,
)
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.als import ALSFactors
from pio_tpu.templates.recommendation import (
    ALSModel, Query, predict_user_topn,
)

_HERE = os.path.dirname(os.path.abspath(__file__))


class PoolDataSource(DataSource):
    def read_training(self, ctx):
        return None


class PoolServeAlgorithm(Algorithm):
    query_class = Query

    def train(self, ctx, pd):
        z = np.load(os.path.join(_HERE, "bench_factors.npz"))
        uf, itf = z["user_factors"], z["item_factors"]
        return ALSModel(
            ALSFactors(user_factors=uf, item_factors=itf),
            BiMap({f"u{i}": i for i in range(uf.shape[0])}),
            BiMap({f"i{i}": i for i in range(itf.shape[0])}),
        )

    def predict(self, model, query):
        return predict_user_topn(
            model, query, model.user_index, model.item_index
        )

    def prepare_for_serving(self, model):
        model.scorer(warmup=True)
        return model


def engine():
    return Engine(
        PoolDataSource, IdentityPreparator,
        {"als": PoolServeAlgorithm}, FirstServing,
    )
'''


def _bench_pool_serving(factors, n_users: int, n_items: int) -> dict:
    """SO_REUSEPORT worker-pool serving stage. The pool multiplies
    host-path QPS by the worker count ON MULTI-CORE HOSTS; this records
    whatever the current host gives it plus ``host_cores`` so the number
    reads honestly (on a 1-core box the pool pays context-switch tax)."""
    import sys as _sys

    from pio_tpu.server.worker_pool import ServingPool
    from pio_tpu.workflow.core_workflow import run_train
    from pio_tpu.workflow.engine_json import build_engine, variant_from_dict

    home = os.environ["PIO_TPU_HOME"]
    np.savez(
        os.path.join(home, "bench_factors.npz"),
        user_factors=factors.user_factors,
        item_factors=factors.item_factors,
    )
    with open(os.path.join(home, "pio_bench_pool_engine.py"), "w") as f:
        f.write(_POOL_ENGINE_SRC)
    # spawned workers import the factory by dotted path — they need the
    # module on THEIR sys.path (PYTHONPATH propagates; sys.path doesn't)
    if home not in _sys.path:
        _sys.path.insert(0, home)
    os.environ["PYTHONPATH"] = (
        home + os.pathsep + os.environ.get("PYTHONPATH", "")
    )
    variant = variant_from_dict({
        "id": "bench-recommendation-pool",
        "version": "1",
        "engineFactory": "pio_bench_pool_engine:engine",
        "algorithms": [{"name": "als", "params": {}}],
    })
    engine, ep = build_engine(variant)
    run_train(engine, ep, variant)

    cores = len(os.sched_getaffinity(0))
    n_workers = max(2, min(4, cores))
    # no device_worker on the HEADLINE pool number: it measures
    # independent per-worker serving, the fast path on a homogeneous
    # pool — funneling through one lane drainer serializes dispatch.
    # The laned variant is measured separately below as ``laned_qps``
    # so the artifact shows both sides of that trade.
    pool = ServingPool(
        variant, host="127.0.0.1", port=0, n_workers=n_workers
    )
    t_boot = time.perf_counter()
    pool.start()
    try:
        # wait_ready polls /readyz, so this is spawn → first worker READY
        pool.wait_ready(timeout=180)
        time_to_ready_s = round(time.perf_counter() - t_boot, 4)
        warm = _KeepAliveClient(pool.port)
        for _ in range(2 * n_workers):  # hit every worker's first-compile
            warm({"user": "u1", "num": 10})
            warm.close()
            warm = _KeepAliveClient(pool.port)
        warm.close()
        # pool /metrics is pool-wide (shared-memory aggregation), so one
        # scrape on whatever worker answers covers every sibling
        got = _with_metrics_delta(
            pool.port, lambda: _concurrent_stage(pool.port, n_users)
        )
        got["workers"] = n_workers
        got["host_cores"] = cores
        got["time_to_ready_s"] = time_to_ready_s
        # routed pass (ISSUE 18): the SAME live pool fronted by the
        # serving router, so routed_qps vs the direct number above
        # isolates the fabric's relay cost on this host; the overhead
        # metric is the concurrent p50 delta through the extra hop.
        try:
            from pio_tpu.server.routerd import create_router_server

            rs = create_router_server(
                [("pool", f"http://127.0.0.1:{pool.port}")],
                host="127.0.0.1", port=0, interval_s=1.0,
            ).start()
            rs.service.start()
            try:
                _wait_readyz(rs.port)
                rg = _concurrent_stage(rs.port, n_users)
                got["routed_qps"] = rg["qps"]
                got["routed_p50_ms"] = rg.get("p50_ms")
                got["routed_p95_ms"] = rg.get("p95_ms")
                if rg.get("p50_ms") is not None and \
                        got.get("p50_ms") is not None:
                    got["router_overhead_ms"] = round(
                        rg["p50_ms"] - got["p50_ms"], 3
                    )
                # shadow-mirroring pass (ISSUE 19): the same routed hop
                # with a live rollout parked in shadow, mirroring 100%
                # of queries back at the pool. The p50 delta vs the
                # plain routed pass is the mirror's relay-path cost —
                # the contract is fire-and-forget off the hot path, so
                # the delta prices the member's doubled load, not a
                # synchronous mirror hop.
                try:
                    import urllib.request as _ur

                    with _ur.urlopen(
                        f"http://127.0.0.1:{pool.port}/deploy.json",
                        timeout=5,
                    ) as r:
                        iid = json.loads(
                            r.read().decode("utf-8")
                        )["engineInstanceId"]
                    body = json.dumps({
                        "engineInstanceId": iid,
                        "targets": f"127.0.0.1:{pool.port}",
                        "by": "bench", "auto": False,
                        "shadowRate": 1.0, "shadowMinSamples": 1,
                        "shadowHoldSeconds": 3600.0,
                        "judgeIntervalSeconds": 1.0,
                    }).encode("utf-8")
                    req = _ur.Request(
                        f"http://127.0.0.1:{rs.port}/rollout",
                        data=body,
                        headers={"Content-Type": "application/json"},
                    )
                    with _ur.urlopen(req, timeout=30):
                        pass
                    deadline = time.time() + 30
                    while time.time() < deadline:
                        with _ur.urlopen(
                            f"http://127.0.0.1:{rs.port}/rollout.json",
                            timeout=5,
                        ) as r:
                            stage = json.loads(
                                r.read().decode("utf-8")
                            )["stage"]
                        if stage == "shadow":
                            break
                        time.sleep(0.1)
                    sg = _concurrent_stage(rs.port, n_users)
                    got["shadow_qps"] = sg["qps"]
                    got["shadow_p50_ms"] = sg.get("p50_ms")
                    if sg.get("p50_ms") is not None and \
                            rg.get("p50_ms") is not None:
                        got["shadow_overhead_ms"] = round(
                            sg["p50_ms"] - rg["p50_ms"], 3
                        )
                    abort = _ur.Request(
                        f"http://127.0.0.1:{rs.port}/rollout/abort",
                        data=b"{}",
                        headers={"Content-Type": "application/json"},
                    )
                    with _ur.urlopen(abort, timeout=30):
                        pass
                except Exception as exc:
                    print(f"# shadow mirroring stage failed: {exc}",
                          file=sys.stderr)
            finally:
                rs.service.stop()
                rs.stop()
        except Exception as exc:
            print(f"# routed serving stage failed: {exc}",
                  file=sys.stderr)
    finally:
        pool.stop()

    # laned pass: same engine, same worker count, but every worker
    # forwards through the shared-memory batch lane to the designated
    # device worker (one process owns the accelerator; siblings are I/O
    # front-ends). Recorded alongside the headline so pool_qps vs
    # pool_laned_qps quantifies the funnel cost on THIS host.
    try:
        laned = ServingPool(
            variant, host="127.0.0.1", port=0, n_workers=n_workers,
            device_worker=True,
        )
        t_boot = time.perf_counter()
        laned.start()
        try:
            laned.wait_ready(timeout=180)
            got["laned_time_to_ready_s"] = round(
                time.perf_counter() - t_boot, 4
            )
            warm = _KeepAliveClient(laned.port)
            for _ in range(2 * n_workers):
                warm({"user": "u1", "num": 10})
                warm.close()
                warm = _KeepAliveClient(laned.port)
            warm.close()
            lg = _concurrent_stage(laned.port, n_users)
            got["laned_qps"] = lg["qps"]
            got["laned_p50_ms"] = lg.get("p50_ms")
            got["laned_p95_ms"] = lg.get("p95_ms")
        finally:
            laned.stop()
    except Exception as exc:
        print(f"# laned pool stage failed: {exc}", file=sys.stderr)
    return got


def _bench_sharded_serving(factors, n_users: int, n_items: int,
                           baseline_qps=None) -> dict:
    """Mesh-worker pool stage: worker 0 owns the whole device mesh and
    serves with partition-rule-sharded factor tables (ISSUE 10). On a
    host without an accelerator the mesh is 8 simulated CPU devices
    (XLA_FLAGS, inherited by the spawned worker), so the number here
    mostly proves the sharded dispatch path and its retrace behavior;
    ``scaling_x`` is sharded QPS over the single-device laned pool."""
    import sys as _sys
    import urllib.request

    from pio_tpu.server.worker_pool import ServingPool
    from pio_tpu.workflow.core_workflow import run_train
    from pio_tpu.workflow.engine_json import build_engine, variant_from_dict

    home = os.environ["PIO_TPU_HOME"]
    np.savez(
        os.path.join(home, "bench_factors.npz"),
        user_factors=factors.user_factors,
        item_factors=factors.item_factors,
    )
    with open(os.path.join(home, "pio_bench_pool_engine.py"), "w") as f:
        f.write(_POOL_ENGINE_SRC)
    if home not in _sys.path:
        _sys.path.insert(0, home)
    os.environ["PYTHONPATH"] = (
        home + os.pathsep + os.environ.get("PYTHONPATH", "")
    )
    variant = variant_from_dict({
        "id": "bench-recommendation-sharded",
        "version": "1",
        "engineFactory": "pio_bench_pool_engine:engine",
        "algorithms": [{"name": "als", "params": {}}],
    })
    engine, ep = build_engine(variant)
    run_train(engine, ep, variant)

    cores = len(os.sched_getaffinity(0))
    n_workers = max(2, min(4, cores))
    import jax

    n_real = len(jax.devices())
    prev_xla = os.environ.get("XLA_FLAGS")
    if n_real <= 1:
        # no multi-chip hardware: give the spawned mesh worker a
        # simulated 8-device CPU mesh (host-platform device count only
        # affects the CPU backend, so this is a no-op on real TPU hosts)
        os.environ["XLA_FLAGS"] = (
            (prev_xla + " " if prev_xla else "")
            + "--xla_force_host_platform_device_count=8"
        )
    got: dict = {"workers": n_workers, "mesh_devices": max(n_real, 8)}
    try:
        pool = ServingPool(
            variant, host="127.0.0.1", port=0, n_workers=n_workers,
            mesh_worker=True,
        )
        t_boot = time.perf_counter()
        pool.start()
        try:
            pool.wait_ready(timeout=180)
            got["time_to_ready_s"] = round(time.perf_counter() - t_boot, 4)
            warm = _KeepAliveClient(pool.port)
            for _ in range(2 * n_workers):
                warm({"user": "u1", "num": 10})
                warm.close()
                warm = _KeepAliveClient(pool.port)
            warm.close()
            sg = _concurrent_stage(pool.port, n_users)
            got["qps"] = sg["qps"]
            got["p50_ms"] = sg.get("p50_ms")
            got["p95_ms"] = sg.get("p95_ms")
            # the kernel picks which worker answers /stats.json; retry
            # until the mesh owner (the only one with sharding enabled)
            # answers, so the artifact records the actual placement
            for _ in range(16):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{pool.port}/stats.json", timeout=5.0
                ) as r:
                    st = json.loads(r.read().decode("utf-8"))
                sh = st.get("sharding") or {}
                if sh.get("enabled"):
                    got["sharding"] = sh
                    break
        finally:
            pool.stop()
    finally:
        if prev_xla is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = prev_xla
    if baseline_qps is None:
        # no laned pool_qps to compare against (pool stage failed):
        # measure the single-device funnel here
        try:
            base = ServingPool(
                variant, host="127.0.0.1", port=0, n_workers=n_workers,
                device_worker=True,
            )
            base.start()
            try:
                base.wait_ready(timeout=180)
                warm = _KeepAliveClient(base.port)
                for _ in range(2 * n_workers):
                    warm({"user": "u1", "num": 10})
                    warm.close()
                    warm = _KeepAliveClient(base.port)
                warm.close()
                baseline_qps = _concurrent_stage(base.port, n_users)["qps"]
            finally:
                base.stop()
        except Exception as exc:
            print(f"# sharded baseline pool failed: {exc}", file=sys.stderr)
    if baseline_qps:
        got["baseline_qps"] = baseline_qps
        got["scaling_x"] = round(got["qps"] / baseline_qps, 3)
    return got


# ------------------------------------------------------------- secondary
def _bench_classification(ctx, scale: float) -> dict:
    """BASELINE config #2: LogReg (treeAggregate ≡ psum all-reduce).
    examples/sec = rows touched per optimizer iteration × iterations.

    Best-vs-best dtype policy: the accelerator side opts into the int8
    feature wire (quarters the dominant host→device shipment; per-column
    scales fold into the weights on device, so the learned model still
    serves raw floats — the library default stays float32), the CPU
    anchor runs float32 (quantized/bf16 wires only slow a local-RAM CPU
    run, inflating the ratio). Each platform at its best config, with
    ``train_acc`` recorded on BOTH so the ratio is accuracy-honest.

    Variance discipline (round-5): MEDIAN of 5 timed runs on each side —
    the recorded ratio previously swung ~1.7× run-to-run on the
    contended single-core host under best-of-2."""
    import jax

    from pio_tpu.models.logreg import LogRegConfig, train_logreg

    n, d, c = int(100_000 * scale), 256, 10
    iters = 100  # a realistic full-batch training length; also amortizes
    # the one-time [N, D] feature upload like the headline's 10 iterations
    rng = np.random.default_rng(1)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, c))
    y = np.argmax(X @ w_true, axis=1).astype(np.int32)
    plat = (
        list(ctx.mesh.devices.flat)[0].platform
        if ctx is not None and ctx.mesh is not None
        else jax.default_backend()
    )
    cfg = LogRegConfig(
        iterations=iters, learning_rate=0.05,
        input_dtype="float32" if plat == "cpu" else "int8",
    )
    # the stage is h2d-wire-bound on a slow host link (the feature
    # upload): the ratio tracks the link, so every recorded value
    # carries its own same-moment probe
    link = _link_meta(plat != "cpu")
    times, model = _timed_runs(
        lambda: train_logreg(ctx, X, y, c, cfg), repeats=5
    )
    dt = times[len(times) // 2]
    return {
        "value": n * iters / dt,
        "train_acc": round(float((model.predict(X) == y).mean()), 4),
        "wire": cfg.input_dtype,
        "anchor_note": "median-of-5 each side, same program+depth",
        **link,
    }


def _bench_similarproduct(ctx, scale: float) -> dict:
    """BASELINE config #3: implicit ALS (MLlib trainImplicit analog).

    Round-5 discipline: median-of-5 on each side plus a same-moment link
    probe, so a recorded ratio shift is attributable — link swing vs
    real regression (the r3→r4 record showed 5.3×→4.11× with no
    code change on this path)."""
    from pio_tpu.models.als import ALSConfig, train_als

    n_edges = int(5_000_000 * scale)
    n_users, n_items = int(50_000 * scale) + 64, int(20_000 * scale) + 64
    iters = 10  # reference template default depth (see headline note)
    rng = np.random.default_rng(2)
    u = rng.integers(0, n_users, n_edges).astype(np.int32)
    i = (rng.random(n_edges) ** 2 * n_items).astype(np.int32)
    r = np.ones(n_edges, np.float32)
    cfg = ALSConfig(rank=16, iterations=iters, reg=0.1, implicit=True,
                    alpha=40.0)
    link = _link_meta(_on_accelerator(ctx))
    times, _ = _timed_runs(
        lambda: train_als(ctx, u, i, r, n_users, n_items, cfg), repeats=5
    )
    dt = times[len(times) // 2]
    return {
        "value": n_edges * iters / dt,
        "anchor_note": "median-of-5 each side, same program+depth",
        **link,
    }


def _on_accelerator(ctx) -> bool:
    """True when the context's devices are not host-CPU (the link probe
    is meaningless — and wasteful — on the anchor side)."""
    import jax

    if ctx is not None and ctx.mesh is not None:
        return list(ctx.mesh.devices.flat)[0].platform != "cpu"
    return jax.default_backend() != "cpu"


def _bench_textclass(scale: float) -> dict:
    """BASELINE config #4: the embedding-bag hot op — Pallas kernel vs
    the plain-XLA gather+einsum lowering. Beyond raw tokens/sec, this
    stage records the kernel's ACTUAL wins as artifacts:

    - accuracy: max relative error vs a float64 host reference — the
      XLA default contracts in bf16 on the MXU (~2 decimal digits); the
      kernel accumulates f32 on the VPU. ``xla_f32_tokens_per_sec`` is
      the apples-to-apples comparison at equal (f32) accuracy.
    - memory: XLA materializes the gathered [B, L, D] intermediate in
      HBM; the kernel streams rows through an O(depth·D) VMEM ring. The
      large-shape stage runs a bag batch whose XLA intermediate alone
      exceeds v5e HBM — the kernel must survive it, XLA cannot.
    """
    import jax
    import jax.numpy as jnp

    from pio_tpu.ops.embedding import (
        _embedding_bag_pallas, _embedding_bag_xla, _use_pallas,
    )

    V, D = 50_000, 256
    B, L = int(4096 * scale) or 8, 64
    rng = np.random.default_rng(3)
    table_h = rng.normal(size=(V, D)).astype(np.float32)
    ids_h = rng.integers(0, V, (B, L)).astype(np.int32)
    w_h = rng.random((B, L)).astype(np.float32)
    table = jax.device_put(table_h)
    ids = jax.device_put(ids_h)
    w = jax.device_put(w_h)
    tokens = B * L

    K = 8  # chained applications per timed dispatch — amortizes the
    # tunnel RTT and forces real execution (block_until_ready on this
    # tunnel can ack before compute for small async programs; a scalar
    # pulled to host cannot lie)

    def timed(fn):
        def many(t, i, w):
            def body(k, acc):
                # roll by the loop index so no iteration can be hoisted
                out = fn(t, jnp.roll(i, k, axis=0), w)
                return acc + jnp.sum(out)

            return jax.lax.fori_loop(0, K, body, jnp.float32(0))

        jf = jax.jit(many)
        dt, _ = _best_of(
            lambda: float(jf(table, ids, w)), repeats=3
        )
        # accuracy sample from the JITTED op — what the templates run
        # (eager and jitted einsum pick different default precisions)
        return K * tokens / dt, np.asarray(jax.jit(fn)(table, ids, w))

    def xla_unpinned(table, ids, w):
        # the raw default lowering (no pinned precision) — reference
        # point for what the shipped op's HIGHEST pin costs
        rows = table[ids]
        return jnp.einsum(
            "bld,bl->bd", rows.astype(jnp.float32),
            w.astype(jnp.float32),
        )

    xla_rate, xla_out = timed(_embedding_bag_xla)  # shipped path (f32)
    out = {"xla_tokens_per_sec": round(xla_rate, 1)}
    # f64 host reference for the accuracy artifact (sampled rows keep
    # the host cost bounded at full scale)
    sample = np.arange(0, B, max(1, B // 256))
    ref = np.einsum(
        "bld,bl->bd",
        table_h.astype(np.float64)[ids_h[sample]],
        w_h[sample].astype(np.float64),
    )
    denom = max(1e-9, float(np.abs(ref).max()))

    def max_err(got):
        return float(
            np.abs(np.asarray(got)[sample].astype(np.float64) - ref).max()
        ) / denom

    acc = {"xla_max_err": round(max_err(xla_out), 8)}
    unp_rate, unp_out = timed(xla_unpinned)
    out["xla_unpinned_default_tokens_per_sec"] = round(unp_rate, 1)
    acc["xla_unpinned_default_max_err"] = round(max_err(unp_out), 8)
    if _use_pallas(table):
        p_rate, p_out = timed(_embedding_bag_pallas)
        out["pallas_tokens_per_sec"] = round(p_rate, 1)
        out["pallas_speedup_vs_xla"] = round(p_rate / xla_rate, 3)
        acc["pallas_max_err"] = round(max_err(p_out), 8)
    out["accuracy"] = acc
    out["memory_mb"] = {
        # what each path needs beyond inputs + outputs at this shape
        "xla_intermediate": round(B * L * D * 4 / 1e6, 1),
        "pallas_scratch": round(4 * D * 4 / 1e6, 4),
    }

    if _use_pallas(table) and scale >= 0.5:
        # large-shape survival: the gathered [B, L, D] f32 intermediate
        # is ~24 GB > v5e HBM; the kernel's O(B·D) output + VMEM ring
        # fits easily
        Bl, Ll = 16_384, 1_436
        ids_l = jax.device_put(
            rng.integers(0, V, (Bl, Ll)).astype(np.int32)
        )
        w_l = jax.device_put(rng.random((Bl, Ll)).astype(np.float32))
        big = {"B": Bl, "L": Ll,
               "xla_intermediate_gb": round(Bl * Ll * D * 4 / 1e9, 1)}
        try:
            jf = jax.jit(
                lambda t, i, w: jnp.sum(_embedding_bag_pallas(t, i, w))
            )
            dt, _ = _best_of(
                lambda: float(jf(table, ids_l, w_l)), repeats=1
            )
            big["pallas_tokens_per_sec"] = round(Bl * Ll / dt, 1)
        except Exception as exc:
            big["pallas_error"] = str(exc)[:200]
        big["xla"] = "skipped: intermediate alone exceeds v5e HBM"
        out["large_shape"] = big
    return out


#: two-tower bench shape, shared with the achieved-GFLOP/s computation in
#: main() — keep them in one place so a tuned config can't silently
#: desync the published utilization number
_TT_BATCH, _TT_EMBED, _TT_HIDDEN, _TT_OUT = 4096, 64, 128, 64


def _bench_twotower(ctx, scale: float) -> dict:
    """BASELINE config #5: two-tower retrieval training, examples/sec
    (one example = one positive pair through a contrastive step).

    Round-5 finding: training is ONE compiled scan over device-resident
    ids — the e2e cost was ~78% the OUTPUT readback of the full vector
    tables over the tunneled link, not any input feed. The stage opts
    into the bf16 table wire (half those bytes; tables are retrieval
    embeddings) and records the phase split so the achieved-GFLOP/s
    figure carries its real bound."""
    from pio_tpu.models.two_tower import TwoTowerConfig, train_two_tower
    from pio_tpu.parallel.mesh import MeshSpec, build_mesh

    n_pairs = int(500_000 * scale)
    n_users, n_items = int(100_000 * scale) + 64, int(50_000 * scale) + 64
    steps, batch = 200, _TT_BATCH  # fixed transfer costs dominate short runs
    # (measured ~3 ms/step vs ~1.8 s fixed); 200 steps is a realistic
    # retrieval-training depth
    rng = np.random.default_rng(4)
    u = rng.integers(0, n_users, n_pairs).astype(np.int32)
    i = rng.integers(0, n_items, n_pairs).astype(np.int32)
    on_acc = _on_accelerator(ctx)
    cfg = TwoTowerConfig(
        embed_dim=_TT_EMBED, hidden=_TT_HIDDEN, out_dim=_TT_OUT,
        steps=steps, batch_size=batch,
        # bf16 emulation only slows the CPU anchor — each side at its
        # best config, like the classification wire policy
        table_wire="bfloat16" if on_acc else "float32",
    )
    mesh = build_mesh(  # the tower shardings need a model axis too
        MeshSpec(data=-1, model=1), devices=list(ctx.mesh.devices.flat)
    )
    # table-READBACK-bound (see phases): probe the d2h direction, which
    # an asymmetric tunnel can decouple from the upload direction
    link = _link_meta(on_acc, d2h=True)
    times, _ = _timed_runs(
        lambda: train_two_tower(mesh, u, i, n_users, n_items, cfg),
        repeats=5 if on_acc else 3,
    )
    dt = times[len(times) // 2]
    out = {
        "value": steps * batch / dt,
        "table_wire": cfg.table_wire,
        "anchor_note": "median each side, same program+depth",
        **link,
    }
    if on_acc:
        st = {}
        train_two_tower(mesh, u, i, n_users, n_items, cfg, stats=st)
        out["phases"] = {
            k: round(v, 3) if isinstance(v, float) else v
            for k, v in st.items()
        }
    return out


def _bench_train_streamed(ctx, scale: float) -> dict:
    """ISSUE 14: the streamed training feed (parallel/stream.py) —
    examples/sec/chip for a streamed two-tower run on the full mesh,
    the profiled h2d/device phase split, the achieved h2d/compute
    overlap ratio, and the mesh-vs-single-chip scaling factor.

    The overlap ratio comes from a controlled executor-level probe (a
    profiled serialized pass vs an overlapped double-buffered pass over
    the SAME chunk workload) rather than from the e2e trainer, whose
    wall time also carries init/readback and would drown the feed
    phases in noise. record_overlap_ratio publishes the gauge."""
    import jax
    import jax.numpy as jnp

    from pio_tpu.models.two_tower import TwoTowerConfig, train_two_tower
    from pio_tpu.parallel.mesh import MeshSpec, build_mesh
    from pio_tpu.parallel.stream import record_overlap_ratio, stream_feed

    n_pairs = max(4096, int(200_000 * scale))
    n_users, n_items = int(50_000 * scale) + 64, int(20_000 * scale) + 64
    # batch capped so the epoch always has several spans to stream,
    # even at smoke scale (one batch = nothing to overlap)
    steps = 60
    batch = max(256, min(_TT_BATCH, n_pairs // 8))
    rng = np.random.default_rng(14)
    u = rng.integers(0, n_users, n_pairs).astype(np.int32)
    i = rng.integers(0, n_items, n_pairs).astype(np.int32)
    cfg = TwoTowerConfig(
        embed_dim=_TT_EMBED, hidden=_TT_HIDDEN, out_dim=_TT_OUT,
        steps=steps, batch_size=batch, stream="on",
    )
    devices = list(ctx.mesh.devices.flat)
    mesh = build_mesh(MeshSpec(data=-1, model=1), devices=devices)

    times, _ = _timed_runs(
        lambda: train_two_tower(mesh, u, i, n_users, n_items, cfg),
        repeats=3,
    )
    rate = steps * batch / times[len(times) // 2]
    st: dict = {}
    # device accounting for the profiled pass (ISSUE 17): stream-carry
    # ledger + train_step compile attribution land in this watch
    from pio_tpu.obs import devicewatch

    dw = devicewatch.DeviceWatch()
    with devicewatch.watching(dw, sample=False):
        train_two_tower(mesh, u, i, n_users, n_items, cfg, stats=st)
        dw.sample()
    dw_payload = dw.payload()

    # single-chip anchor: same streamed program without collectives
    t_single, _ = _timed_runs(
        lambda: train_two_tower(None, u, i, n_users, n_items, cfg),
        repeats=3,
    )
    rate_single = steps * batch / t_single[len(t_single) // 2]

    # executor-level overlap probe: heavy async chunk programs vs
    # multi-MB puts — the serialized pass measures the phases, the
    # double-buffered pass measures how much of the put time hides
    side = 512 if scale < 1 else 1024
    n_chunks, burn_iters = 6, 4
    host_chunks = [
        rng.normal(size=(side, side)).astype(np.float32) * 0.01
        for _ in range(n_chunks)
    ]

    @jax.jit
    def _burn(carry, dev):
        x = carry
        for _ in range(burn_iters):
            x = jnp.tanh(x @ dev)
        return x

    def _probe(stats=None, lookahead=0):
        from pio_tpu.obs import monotonic_s

        t0 = monotonic_s()
        out = stream_feed(
            list(range(n_chunks)),
            encode=lambda c: host_chunks[c],
            dispatch=lambda carry, dev, _i: _burn(carry, dev),
            init_carry=lambda: jnp.eye(side, dtype=jnp.float32),
            lookahead=lookahead,
            stats=stats,
        )
        jax.block_until_ready(out)
        return monotonic_s() - t0

    pst: dict = {}
    _probe(stats=pst)  # warm compile + serialized phases
    pst = {}
    _probe(stats=pst)
    wall = min(_probe(lookahead=2) for _ in range(3))
    overlap = record_overlap_ratio(pst["h2d_s"], pst["device_s"], wall)

    return {
        "value": rate / max(1, len(devices)),
        "examples_per_sec": round(rate, 1),
        "sharded_scaling_x": round(rate / rate_single, 2),
        "n_devices": len(devices),
        "overlap_ratio": round(overlap, 3),
        "probe_h2d_s": round(pst["h2d_s"], 4),
        "probe_device_s": round(pst["device_s"], 4),
        "probe_wall_s": round(wall, 4),
        "device": {
            "mode": dw_payload.get("mode"),
            "peak_bytes": max(
                (d.get("peakBytes") or 0
                 for d in dw_payload.get("devices") or []),
                default=0,
            ),
            "compiles": (dw_payload.get("compiles") or {}).get("total", 0),
        },
        "phases": {
            k: round(v, 3) if isinstance(v, float) else v
            for k, v in st.items()
        },
    }


#: v5e bf16 peak, GFLOP/s — the roofline anchor for utilization notes
_V5E_BF16_PEAK_GFLOPS = 197_000.0


def _bench_seqrec(ctx, scale: float) -> dict:
    """Sequence-recommender (transformer) train step — the second
    MXU-capable workload (beyond the reference's template set; no
    Spark analog, so no vs_baseline). Reports tokens/sec and achieved
    matmul GFLOP/s from the analytic count (attention projections +
    scores/values + FFN + the vocab-parallel CE logits matmul, ×3 for
    backward; embedding gathers excluded → conservative)."""
    from pio_tpu.models.seqrec import SeqRecConfig, train_seqrec
    from pio_tpu.parallel.mesh import MeshSpec, build_mesh

    n, t = max(8, int(256 * scale)), 128
    d, heads, layers, ffn = 256, 8, 4, 1024
    vocab, steps = 20_000, 30
    rng = np.random.default_rng(6)
    lens = rng.integers(t // 2, t, n)
    seqs = np.zeros((n, t), np.int32)
    for r in range(n):
        seqs[r, : lens[r]] = rng.integers(1, vocab + 1, lens[r])
    cfg = SeqRecConfig(
        d_model=d, n_heads=heads, n_layers=layers, ffn=ffn,
        max_len=t, steps=steps,
    )
    mesh = build_mesh(
        MeshSpec(data=-1, pipe=1, seq=1, model=1),
        devices=list(ctx.mesh.devices.flat),
    )
    dt, _ = _best_of(
        lambda: train_seqrec(mesh, seqs, vocab, cfg), repeats=2
    )
    tokens = n * t * steps
    fwd_per_token = (
        layers * (8 * d * d + 4 * t * d + 4 * d * ffn) + 2 * d * vocab
    )
    gflops = 3 * fwd_per_token * tokens / dt / 1e9
    return {
        "tokens_per_sec": round(tokens / dt, 1),
        "achieved_gflops": round(gflops, 1),
        "roofline_note": (
            f"{gflops / _V5E_BF16_PEAK_GFLOPS:.2%} of v5e bf16 peak — "
            "e2e wall-clock incl. host batch staging; f32 params"
        ),
    }


def _bench_rank_sweep(ctx, scale: float) -> dict:
    """ALS rank scaling {16, 64, 128}: the K²-per-edge normal-equation
    term pushes the MXU where rank 16 is gather/transfer-bound. Reports
    end-to-end + device-phase rates and achieved GFLOP/s (normal-equation
    build term only, 4·K·(K+1) FLOPs per edge per iteration — solves and
    packing excluded, so the figure is conservative)."""
    from pio_tpu.models.als import ALSConfig, train_als

    iters = 4
    out = {}
    # entity counts shrink with rank: the per-entity K×K normal-equation
    # tensor is rank²·4 bytes/entity and the batched-CG solver carries
    # ~3 copies — 80k entities at rank 128 needs >20 GB HBM (measured
    # OOM on 16 GB v5e); 16k keeps the whole sweep resident
    sizes = {16: 80_000, 64: 40_000, 128: 16_000}
    for rank, U0 in sizes.items():
        E = int(8_000_000 * scale)
        U, I = int(U0 * scale) + 64, int(U0 * scale) // 2 + 64
        rng = np.random.default_rng(7)
        u = rng.integers(0, U, E).astype(np.int32)
        i = (rng.random(E) ** 2 * I).astype(np.int32)
        r = (rng.integers(1, 11, E) * 0.5).astype(np.float32)
        cfg = ALSConfig(rank=rank, iterations=iters, reg=0.1)
        try:
            # repeats=1: the sweep is a scaling curve, not the headline —
            # one warm timed run per rank bounds the sweep's wall-clock
            dt, _ = _best_of(
                lambda: train_als(ctx, u, i, r, U, I, cfg), repeats=1
            )
            st = {}
            train_als(ctx, u, i, r, U, I, cfg, stats=st)
        except Exception as exc:  # one rank failing must not kill the curve
            print(f"# rank sweep rank={rank} failed: {exc}",
                  file=sys.stderr)
            continue
        flops = 4 * rank * (rank + 1) * E * iters
        out[f"rank{rank}"] = {
            "examples_per_sec": round(E * iters / dt, 1),
            "device_examples_per_sec": round(
                E * iters / st["device_s"], 1
            ),
            "achieved_gflops": round(flops / st["device_s"] / 1e9, 1),
        }
    return out


class _RawIngestClient:
    """Minimal keep-alive load-gen client: preformatted header template,
    single-pass status/Content-Length response scan. ``http.client``
    costs ~100 µs/request building and parsing MIME headers — on the
    single shared core that was a third of the measured "ingest rate",
    i.e. the load generator throttling the server under test."""

    def __init__(self, port: int, path_qs: str):
        import socket

        self._sock = socket.create_connection(("127.0.0.1", port),
                                              timeout=30)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._tmpl = (
            f"POST {path_qs} HTTP/1.1\r\nHost: x\r\n"
            "Content-Type: application/json\r\n"
            "Content-Length: %d\r\n\r\n"
        )
        self._buf = b""
        self.last_body = b""  # response body of the latest post()
        self.last_head = b""  # response headers of the latest post()

    def post(self, body: bytes) -> int:
        self._sock.sendall((self._tmpl % len(body)).encode() + body)
        while True:
            i = self._buf.find(b"\r\n\r\n")
            if i >= 0:
                head = self._buf[:i]
                clen = int(
                    head.lower().split(b"content-length:")[1]
                    .split(b"\r\n")[0]
                )
                while len(self._buf) < i + 4 + clen:
                    got = self._sock.recv(65536)
                    if not got:  # EOF mid-body must fail, not spin
                        raise RuntimeError(
                            "server closed mid-response"
                        )
                    self._buf += got
                status = int(head.split(b" ", 2)[1])
                self.last_head = head
                self.last_body = self._buf[i + 4:i + 4 + clen]
                self._buf = self._buf[i + 4 + clen:]
                return status
            got = self._sock.recv(65536)
            if not got:
                raise RuntimeError("server closed the connection")
            self._buf += got

    def close(self):
        self._sock.close()


def _bench_event_ingest(scale: float) -> dict:
    """Events/sec through a LIVE Event Server (HTTP POST, auth included):
    single ``/events.json`` posts and ≤50-event ``/batch/events.json``
    batches, against the sqlite event store (quickstart default) and the
    native C++ eventlog backend (the HBase-slot store). Also records the
    IN-PROCESS handler rate (no HTTP) so the artifact shows how the
    measured number decomposes: handler floor (storage commit + parse +
    validate) vs the HTTP/socket layer vs the load client sharing the
    core — see docs/operations.md §"Ingest cost profile"."""
    from pio_tpu.server.event_server import (
        EventServerService,
        create_event_server,
    )
    from pio_tpu.server.http import Request
    from pio_tpu.storage import Storage
    from pio_tpu.storage.records import AccessKey, App

    n_single = max(50, int(3000 * min(scale, 1.0)))
    n_batches = max(4, int(30 * min(scale, 1.0)))
    home = os.environ["PIO_TPU_HOME"]

    def one_backend(backend: str) -> dict:
        saved = {
            k: os.environ.get(k)
            for k in (
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE",
                "PIO_STORAGE_SOURCES_INGEST_TYPE",
                "PIO_STORAGE_SOURCES_INGEST_PATH",
            )
        }
        os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "INGEST"
        os.environ["PIO_STORAGE_SOURCES_INGEST_TYPE"] = backend
        os.environ["PIO_STORAGE_SOURCES_INGEST_PATH"] = os.path.join(
            home, f"ingest_{backend}"
        )
        Storage.reset()
        try:
            app_id = Storage.get_meta_data_apps().insert(
                App(0, f"bench-ingest-{backend}")
            )
            key = Storage.get_meta_data_access_keys().insert(
                AccessKey("", app_id)
            )
            server = create_event_server(
                host="127.0.0.1", port=_free_port()
            )
            server.start()
            # keep-alive connections — the reference SDKs hold one open;
            # a fresh TCP handshake per event would measure the client's
            # socket churn, not the server's ingest path
            single_cli = _RawIngestClient(
                server.port, f"/events.json?accessKey={key}"
            )
            batch_cli = _RawIngestClient(
                server.port, f"/batch/events.json?accessKey={key}"
            )
            try:
                def post(cli, body):
                    status = cli.post(json.dumps(body).encode())
                    if status >= 400:  # a 401/400 must fail the bench,
                        # not get timed as a successful ingest
                        raise RuntimeError(f"ingest: HTTP {status}")
                    return status

                def ev(n):
                    return {
                        "event": "rate",
                        "entityType": "user",
                        "entityId": f"u{n}",
                        "targetEntityType": "item",
                        "targetEntityId": f"i{n % 97}",
                        "properties": {"rating": float(n % 10) / 2.0},
                    }

                # in-process handler floor FIRST (no HTTP, no client;
                # fresh store, before WAL growth/checkpoints from the
                # HTTP phases can stall it): the measured HTTP numbers
                # then read as floor + HTTP layer + load client on the
                # shared core
                service = EventServerService()
                n_inproc = max(200, n_single // 2)

                def inproc_req(n):
                    return Request(
                        method="POST", path="/events.json",
                        params={"accessKey": key}, body=ev(n),
                    )

                status, _b = service.create_event(inproc_req(499_999))
                assert status == 201, status  # warm route + store
                t0 = time.perf_counter()
                for n in range(n_inproc):
                    status, _b = service.create_event(
                        inproc_req(500_000 + n)
                    )
                    assert status == 201, status
                dt_inproc = time.perf_counter() - t0

                post(single_cli, ev(0))  # warm the route + store
                # median-of-3 wall trials + per-request p50: hypervisor
                # STEAL on this 1-core host parks the whole VM for
                # 100-300 ms at random (seen as 0.1% of requests eating
                # ~30% of wall time), so a lone trial swings ~2×. The
                # p50 is the steal-free capability number; the wall
                # median is what a tenant actually gets.
                single_rates = []
                req_lat = []
                for trial in range(3):
                    base = trial * n_single
                    t0 = time.perf_counter()
                    for n in range(n_single):
                        tr = time.perf_counter()
                        post(single_cli, ev(base + n))
                        req_lat.append(time.perf_counter() - tr)
                    single_rates.append(
                        n_single / (time.perf_counter() - t0)
                    )
                single_rates.sort()
                req_lat.sort()
                p50_us = req_lat[len(req_lat) // 2] * 1e6
                t0 = time.perf_counter()
                for b in range(n_batches):
                    post(batch_cli,
                         [ev(b * 50 + j) for j in range(50)])
                dt_batch = time.perf_counter() - t0

                # concurrent single-POSTs (8 keep-alive clients): where
                # the storage layer's group commit earns its keep —
                # contemporaneous inserts coalesce into one WAL commit /
                # log append
                import concurrent.futures

                def conc_worker(t):
                    client = _RawIngestClient(
                        server.port, f"/events.json?accessKey={key}"
                    )
                    try:
                        for n in range(n_single // 4):
                            post(client, ev(100_000 + t * 10_000 + n))
                    finally:
                        client.close()

                t0 = time.perf_counter()
                with concurrent.futures.ThreadPoolExecutor(8) as ex:
                    list(ex.map(conc_worker, range(8)))
                dt_conc = time.perf_counter() - t0
                return {
                    "single_events_per_sec": round(single_rates[1], 1),
                    "single_trials": [round(r, 1) for r in single_rates],
                    "single_p50_us": round(p50_us, 1),
                    "single_p50_events_per_sec": round(1e6 / p50_us, 1),
                    "inproc_events_per_sec": round(
                        n_inproc / dt_inproc, 1
                    ),
                    "concurrent_single_events_per_sec": round(
                        8 * (n_single // 4) / dt_conc, 1
                    ),
                    "batch_events_per_sec": round(
                        n_batches * 50 / dt_batch, 1
                    ),
                    "client": "raw-keepalive",
                }
            finally:
                single_cli.close()
                batch_cli.close()
                server.stop()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            Storage.reset()

    out = {}
    for backend in ("sqlite", "eventlog"):
        try:
            out[backend] = one_backend(backend)
        except Exception as exc:
            print(f"# ingest {backend} failed: {exc}", file=sys.stderr)
    return out


def _bench_partitioned_ingest(scale: float) -> dict:
    """``ingest.partitioned`` (ISSUE 9): concurrent HTTP ingest into the
    hash-partitioned event log at N=1/2/4 partitions through a live
    Event Server. The router spreads contemporaneous inserts over N
    independent group-commit queues, so the N=1 column is the single-log
    baseline and ``ingest_part_x`` (N=4 over N=1) is the concurrency win
    partitioning buys on THIS host. A final replicated pass (N=2, one
    in-process follower, the default ``batch`` durability → async
    replication off the ack path) records the rate with a follower
    attached plus ``repl_lag_p95_ms`` — the p95 of the
    ``pio_tpu_repl_ack_seconds`` send-to-ack histogram — and how long
    the follower took to drain to zero lag after the load stopped."""
    from pio_tpu.server.event_server import create_event_server
    from pio_tpu.storage import Storage
    from pio_tpu.storage.records import AccessKey, App

    n_each = max(40, int(1200 * min(scale, 1.0)))  # per client, 8 clients
    home = os.environ["PIO_TPU_HOME"]
    _ENV_KEYS = (
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE",
        "PIO_STORAGE_SOURCES_PART_TYPE",
        "PIO_STORAGE_SOURCES_PART_PATH",
        "PIO_TPU_PARTLOG_PARTITIONS",
        "PIO_TPU_PARTLOG_REPLICAS",
    )

    def one_pass(n: int, follower=None) -> dict:
        import concurrent.futures

        saved = {k: os.environ.get(k) for k in _ENV_KEYS}
        tag = f"part{n}" + ("r" if follower is not None else "")
        os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "PART"
        os.environ["PIO_STORAGE_SOURCES_PART_TYPE"] = "partlog"
        os.environ["PIO_STORAGE_SOURCES_PART_PATH"] = os.path.join(
            home, f"ingest_{tag}"
        )
        os.environ["PIO_TPU_PARTLOG_PARTITIONS"] = str(n)
        os.environ.pop("PIO_TPU_PARTLOG_REPLICAS", None)
        if follower is not None:
            os.environ["PIO_TPU_PARTLOG_REPLICAS"] = (
                f"127.0.0.1:{follower.port}"
            )
        Storage.reset()
        try:
            app_id = Storage.get_meta_data_apps().insert(
                App(0, f"bench-{tag}")
            )
            key = Storage.get_meta_data_access_keys().insert(
                AccessKey("", app_id)
            )
            server = create_event_server(host="127.0.0.1", port=_free_port())
            server.start()
            try:
                def ev(m):
                    return {
                        "event": "rate",
                        "entityType": "user",
                        "entityId": f"u{m}",
                        "targetEntityType": "item",
                        "targetEntityId": f"i{m % 97}",
                        "properties": {"rating": float(m % 10) / 2.0},
                    }

                def conc_worker(t):
                    client = _RawIngestClient(
                        server.port, f"/events.json?accessKey={key}"
                    )
                    try:
                        for m in range(n_each):
                            status = client.post(
                                json.dumps(ev(t * 100_000 + m)).encode()
                            )
                            if status >= 400:
                                raise RuntimeError(f"ingest: HTTP {status}")
                    finally:
                        client.close()

                warm = _RawIngestClient(
                    server.port, f"/events.json?accessKey={key}"
                )
                try:
                    assert warm.post(json.dumps(ev(999_999)).encode()) < 400
                finally:
                    warm.close()
                t0 = time.perf_counter()
                with concurrent.futures.ThreadPoolExecutor(8) as ex:
                    list(ex.map(conc_worker, range(8)))
                dt = time.perf_counter() - t0
                got = {
                    "concurrent_events_per_sec": round(8 * n_each / dt, 1),
                }
                if follower is not None:
                    # async replication: let the follower drain before
                    # reading the lag/ack artifacts (drain time is itself
                    # the interesting number — the unreplicated window a
                    # crash at batch durability could cost)
                    lev = Storage.get_levents()
                    t0 = time.perf_counter()
                    deadline = t0 + 20.0
                    while time.perf_counter() < deadline:
                        rows = lev._replicator.lag_snapshot()
                        if rows and all(
                            row["acked"].get(str(k), 0) >= lev.committed(k)
                            for row in rows
                            for k in range(n)
                        ):
                            break
                        time.sleep(0.02)
                    got["repl_drain_s"] = round(time.perf_counter() - t0, 3)
                    from pio_tpu.storage.partlog.replication import (
                        _ACK_SECONDS,
                    )

                    # per-partition/per-follower since ISSUE 11; the
                    # family-wide quantile merges cells bucket-wise
                    p95 = _ACK_SECONDS.quantile(0.95)
                    if p95 is not None:
                        got["repl_lag_p95_ms"] = round(p95 * 1e3, 3)
                return got
            finally:
                server.stop()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            Storage.reset()

    # partitioning multiplies COMMIT concurrency; on a 1-core host the
    # passes contend for the same CPU, so record the core count the
    # ratio was measured under (same honesty rule as the pool stage)
    out: dict = {
        "concurrent_events_per_sec": {},
        "host_cores": len(os.sched_getaffinity(0)),
    }
    for n in (1, 2, 4):
        try:
            got = one_pass(n)
            out["concurrent_events_per_sec"][str(n)] = (
                got["concurrent_events_per_sec"]
            )
        except Exception as exc:
            print(f"# partitioned ingest N={n} failed: {exc}",
                  file=sys.stderr)
    r1 = out["concurrent_events_per_sec"].get("1")
    r4 = out["concurrent_events_per_sec"].get("4")
    if r1 and r4:
        out["ingest_part_x"] = round(r4 / r1, 2)
    try:
        from pio_tpu.storage.partlog.replication import FollowerServer

        froot = os.path.join(home, "ingest_follower")
        follower = FollowerServer(froot)
        try:
            rep = one_pass(2, follower=follower)
        finally:
            follower.stop()
        rep["partitions"] = 2
        rep["durability"] = "batch (async replication)"
        out["replicated"] = rep
    except Exception as exc:
        print(f"# replicated ingest pass failed: {exc}", file=sys.stderr)
    return out


#: hard budget for the final stdout line — the driver records only the
#: LAST 2000 characters of output, so the printed summary (plus newline)
#: must always fit; the full result goes to BENCH_FULL.json instead
SUMMARY_CHAR_BUDGET = 1900


def build_summary(full: dict, full_path: str = "BENCH_FULL.json") -> dict:
    """Compact, tail-window-safe summary of a full bench result.

    The round-4 artifact of record was lost because the single JSON line
    outgrew the driver's 2000-char tail window and the FRONT of the line
    (the headline) was truncated away. The contract now: the full detail
    blob is written to ``BENCH_FULL.json`` and stdout carries ONLY this
    summary — headline value/vs_baseline, link probe, device-phase rate,
    pack_s, serving p50s + concurrent/pool QPS, and per-config
    vs_baseline ratios — small enough that the whole line always
    survives the tail window.
    """

    def get(*path, default=None):
        node = full
        for key in path:
            if not isinstance(node, dict) or key not in node:
                return default
            node = node[key]
        return node

    s = {
        "metric": full.get("metric"),
        "value": full.get("value"),
        "unit": full.get("unit"),
        "vs_baseline": full.get("vs_baseline"),
        "value_best_of_5": full.get("value_best_of_5"),
        "link_mb_s": full.get("link_mb_s"),
        "device_examples_per_sec": full.get("device_examples_per_sec"),
        "pack_s": get("phases", "pack_s"),
        "p50_predict_ms": full.get("p50_predict_ms"),
        "p50_inproc_ms": full.get("p50_inproc_ms"),
        "serving_qps": get("serving", "concurrent", "qps"),
        "serving_mb_qps": get("serving", "concurrent_microbatch", "qps"),
        "serving_mb_mode": get("serving", "concurrent_microbatch", "mode"),
        "pool_qps": get("serving", "pool", "qps"),
        "pool_laned_qps": get("serving", "pool", "laned_qps"),
        "routed_qps": get("serving", "pool", "routed_qps"),
        "router_overhead_ms": get("serving", "pool", "router_overhead_ms"),
        "shadow_overhead_ms": get("serving", "pool", "shadow_overhead_ms"),
        "pool_workers": get("serving", "pool", "workers"),
        "host_cores": get("serving", "pool", "host_cores"),
        "sharded_qps": get("serving", "sharded", "qps"),
        "sharded_scaling_x": get("serving", "sharded", "scaling_x"),
        "evfront_qps": get("serving", "evfront", "qps"),
        "evfront_p50_ms": get("serving", "evfront", "p50_ms"),
        "serving_attributed": get(
            "serving", "latency_budget", "attributedFraction"
        ),
    }
    # per-bucket micro-batch decisions replace the single mode string
    # when present (compacted to {bucket: mode} — the p50s live in the
    # full blob)
    mode_map = get("serving", "concurrent_microbatch", "mode_by_bucket")
    if isinstance(mode_map, dict) and mode_map:
        s["serving_mb_mode"] = {
            b: (v.get("mode") if isinstance(v, dict) else v)
            for b, v in sorted(mode_map.items(), key=lambda kv: int(kv[0]))
        }
    res = get("serving", "resident")
    if isinstance(res, dict):
        s["serving_h2d_x"] = res.get("h2d_ratio_f32_over_i8")
        s["serving_donation_hit"] = res.get("donation_hit_rate")
        s["serving_wire_parity_delta"] = res.get("parity_delta")
    sec = full.get("secondary") or {}
    configs: dict = {}
    for short, key in (
        ("classification", "classification_examples_per_sec"),
        ("similarproduct", "similarproduct_examples_per_sec"),
        ("twotower", "twotower_examples_per_sec"),
    ):
        entry = sec.get(key)
        if isinstance(entry, dict):
            c = {"v": entry.get("value"), "x": entry.get("vs_baseline")}
            for src, dst in (("achieved_gflops", "gflops"),
                             ("anchor_note", "anchor"),
                             ("link_mb_s", "link"),
                             ("link_d2h_mb_s", "link_d2h"),
                             ("train_acc", "acc"),
                             ("anchor_train_acc", "anchor_acc"),
                             ("wire", "wire")):
                if src in entry:
                    c[dst] = entry[src]
            configs[short] = c
    if isinstance(sec.get("seqrec"), dict):
        sq = sec["seqrec"]
        configs["seqrec"] = {
            "tokens_s": sq.get("tokens_per_sec"),
            "gflops": sq.get("achieved_gflops"),
        }
    ts = sec.get("train_streamed")
    if isinstance(ts, dict):
        configs["train_streamed"] = {
            "v": ts.get("value"),
            "overlap": ts.get("overlap_ratio"),
            "shard_x": ts.get("sharded_scaling_x"),
            "h2d_s": (ts.get("phases") or {}).get("h2d_s"),
            "device_s": (ts.get("phases") or {}).get("device_s"),
        }
        # trajectory fields ride the summary top level so the history
        # delta table can watch them (see HISTORY_FIELDS)
        s["train_streamed_eps"] = ts.get("value")
        s["train_stream_overlap"] = ts.get("overlap_ratio")
        s["train_sharded_x"] = ts.get("sharded_scaling_x")
        s["train_peak_bytes"] = (ts.get("device") or {}).get("peak_bytes")
    # device accounting (ISSUE 17): serving-stage compile total — the
    # steady-state flatness trajectory the history table watches
    dev = get("serving", "resident", "device") or get(
        "serving", "concurrent", "device"
    )
    if isinstance(dev, dict):
        s["serving_compiles"] = dev.get("compiles")
    if isinstance(sec.get("textclassification"), dict):
        tc = sec["textclassification"]
        configs["textclass"] = {
            "tokens_s": max(
                tc.get("pallas_tokens_per_sec") or 0.0,
                tc.get("xla_tokens_per_sec") or 0.0,
            ) or None,
            "x": tc.get("vs_baseline"),
        }
    ing = sec.get("eventserver_events_per_sec")
    if isinstance(ing, dict):
        flat = {}
        for backend, row in ing.items():
            if isinstance(row, dict):
                flat[f"{backend}_single"] = row.get("single_events_per_sec")
                if "single_p50_events_per_sec" in row:
                    flat[f"{backend}_p50"] = row["single_p50_events_per_sec"]
                flat[f"{backend}_batch"] = row.get("batch_events_per_sec")
        if flat:
            configs["ingest"] = flat
    ip = sec.get("ingest_partitioned")
    if isinstance(ip, dict):
        rates = ip.get("concurrent_events_per_sec") or {}
        c = {f"n{n}": rates.get(n) for n in ("1", "2", "4")
             if rates.get(n) is not None}
        if "ingest_part_x" in ip:
            c["x"] = ip["ingest_part_x"]
        rep = ip.get("replicated")
        if isinstance(rep, dict):
            if "repl_lag_p95_ms" in rep:
                c["lag_p95_ms"] = rep["repl_lag_p95_ms"]
            if "concurrent_events_per_sec" in rep:
                c["repl"] = rep["concurrent_events_per_sec"]
        if c:
            configs["ingest_part"] = c
    if configs:
        s["configs"] = configs
    s["full"] = os.path.basename(full_path)
    # belt and braces: if the summary somehow outgrows the budget, shed
    # down to the driver-required core rather than risk truncation again
    if len(json.dumps(s)) > SUMMARY_CHAR_BUDGET:
        s = {k: s.get(k) for k in
             ("metric", "value", "unit", "vs_baseline", "full")}
    return s


#: workload env knobs and their full-scale defaults — a knob set to a
#: NON-default value marks a SMOKE run, whose artifact must not clobber
#: the committed artifact of record (explicitly exporting a default is
#: still a full run)
_FULL_SCALE_DEFAULTS = {
    "PIO_TPU_BENCH_EDGES": "25000000",
    "PIO_TPU_BENCH_ITERS": "10",
    "PIO_TPU_BENCH_RANK": "16",
    "PIO_TPU_BENCH_CPU_EDGES": "2000000",
    "PIO_TPU_BENCH_QUERIES": "200",
    "PIO_TPU_BENCH_SECONDARY": "1",
    "PIO_TPU_BENCH_SCALE": "1",
    "PIO_TPU_BENCH_RANKSWEEP": "1",
    "PIO_TPU_BENCH_DEADLINE_S": "3000",
}


def _is_smoke_run() -> bool:
    for k, default in _FULL_SCALE_DEFAULTS.items():
        v = os.environ.get(k)
        if v is None:
            continue
        try:
            if float(v) != float(default):
                return True
        except ValueError:
            return True  # unparseable knob: refuse to claim full scale
    return False


def emit(full: dict, path: str | None = None,
         base_dir: str | None = None) -> str:
    """Write ``full`` to its JSON file and return the summary line (the
    ONLY thing main prints to stdout, as its last act). Full-scale runs
    write BENCH_FULL.json (the committed artifact of record); runs with
    any workload-shrinking env knob write the gitignored
    bench_full_smoke.json instead."""
    if path is None:
        if base_dir is None:
            base_dir = os.path.dirname(os.path.abspath(__file__))
        name = ("bench_full_smoke.json" if _is_smoke_run()
                else "BENCH_FULL.json")
        path = os.path.join(base_dir, name)
    # atomic replace: a mid-serialization failure (e.g. a stage leaking
    # a non-JSON type) must not destroy the previous artifact of record
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(full, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # failed mid-write: no .tmp litter
            os.unlink(tmp)
    print(f"# full result written to {path}", file=sys.stderr)
    return json.dumps(build_summary(full, full_path=path))


# ---------------------------------------------------------------------------
# bench history ledger (ISSUE 11): ``bench.py --history`` appends each
# run's trajectory fields to BENCH_HISTORY.jsonl and prints a
# delta-vs-previous-run table (to stderr — stdout stays the one summary
# line) with a configurable regression threshold. The BENCH_r0x
# artifacts are point-in-time snapshots; this is the trend line.
# ---------------------------------------------------------------------------

HISTORY_BASENAME = "BENCH_HISTORY.jsonl"
DEFAULT_REGRESSION_THRESHOLD = 0.05

#: trajectory fields and their good direction; a move against the
#: direction by more than the threshold is flagged REGRESSION
HISTORY_FIELDS = (
    ("value", "up"),                 # headline examples/sec/chip
    ("serving_qps", "up"),
    ("pool_qps", "up"),
    ("routed_qps", "up"),            # through the serving-fabric router
    ("router_overhead_ms", "down"),  # router hop p50 cost vs direct
    ("shadow_overhead_ms", "down"),  # shadow-mirroring p50 cost vs routed
    ("evfront_qps", "up"),
    ("evfront_p50_ms", "down"),
    ("p50_predict_ms", "down"),
    ("p95_predict_ms", "down"),
    ("serving_attributed", "up"),    # latency-attribution coverage
    ("serving_h2d_x", "up"),         # f32/i8 h2d byte ratio (wire win)
    ("shed_rate", "down"),           # overload stage shed fraction
    ("train_streamed_eps", "up"),    # streamed-feed examples/sec/chip
    ("train_stream_overlap", "up"),  # h2d hidden behind compute
    ("train_sharded_x", "up"),       # mesh vs single-chip train rate
    ("serving_compiles", "down"),    # attributed serving compiles (flat)
    ("train_peak_bytes", "down"),    # streamed-train HBM high-water
)


def _git_sha() -> str | None:
    import subprocess

    try:
        got = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5.0,
        )
        sha = got.stdout.strip()
        return sha or None
    except (OSError, subprocess.SubprocessError):
        return None


def history_record(full: dict, summary: dict,
                   git_sha: str | None = None,
                   timestamp: str | None = None) -> dict:
    """One BENCH_HISTORY.jsonl row: the trajectory fields only."""
    if timestamp is None:
        import datetime as _dt

        timestamp = _dt.datetime.now(_dt.timezone.utc).isoformat(
            timespec="seconds"
        )
    serving = full.get("serving") or {}
    conc = serving.get("concurrent") or {}
    overload = serving.get("overload") or {}
    rec = {
        "timestamp": timestamp,
        "git_sha": git_sha if git_sha is not None else _git_sha(),
        "smoke": _is_smoke_run(),
        "metric": summary.get("metric"),
        "value": summary.get("value"),
        "vs_baseline": summary.get("vs_baseline"),
        "serving_qps": summary.get("serving_qps"),
        "pool_qps": summary.get("pool_qps"),
        "routed_qps": summary.get("routed_qps"),
        "router_overhead_ms": summary.get("router_overhead_ms"),
        "shadow_overhead_ms": summary.get("shadow_overhead_ms"),
        "evfront_qps": summary.get("evfront_qps"),
        "evfront_p50_ms": summary.get("evfront_p50_ms"),
        "p50_predict_ms": summary.get("p50_predict_ms"),
        "p95_predict_ms": conc.get("p95_ms"),
        "serving_attributed": summary.get("serving_attributed"),
        "serving_h2d_x": summary.get("serving_h2d_x"),
        "shed_rate": overload.get("shed_rate"),
        "train_streamed_eps": summary.get("train_streamed_eps"),
        "train_stream_overlap": summary.get("train_stream_overlap"),
        "train_sharded_x": summary.get("train_sharded_x"),
        "serving_compiles": summary.get("serving_compiles"),
        "train_peak_bytes": summary.get("train_peak_bytes"),
        "shed_counts": {
            "offered": overload.get("offered"),
            "admitted": overload.get("admitted"),
            "server_shed": overload.get("server_shed"),
        },
    }
    return rec


def append_history(record: dict, path: str) -> None:
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


def read_history(path: str) -> list:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    print(f"# skipping malformed history line in {path}",
                          file=sys.stderr)
    except OSError:
        pass
    return out


def history_delta_table(prev: dict, cur: dict,
                        threshold: float) -> tuple:
    """``(table_lines, regressed_fields)`` comparing two history rows.
    A field counts as a regression when it moves AGAINST its good
    direction by more than ``threshold`` (fractional, e.g. 0.05).
    The direction-aware comparison itself is shared with the training
    run ledger (``pio runs --diff``) via trainwatch."""
    from pio_tpu.obs.trainwatch import delta_rows

    rows, regressed = delta_rows(prev, cur, HISTORY_FIELDS, threshold)
    lines = [
        f"bench history delta vs {prev.get('git_sha') or '?'} "
        f"({prev.get('timestamp') or '?'}), threshold "
        f"{threshold * 100:.1f}%:",
        f"  {'field':<20} {'prev':>12} {'now':>12} {'delta':>9}",
    ]
    for field, a, b, delta, tag in rows:
        lines.append(f"  {field:<20} {a:>12} {b:>12} {delta:>9}{tag}")
    if not rows:
        lines.append("  (no comparable numeric fields)")
    return lines, regressed


def parse_history_argv(argv: list) -> dict:
    """``--history [--history-file PATH] [--regression-threshold FRAC]``
    (also enabled by ``PIO_TPU_BENCH_HISTORY=1`` for env-only drivers).
    Unknown argv entries are ignored — bench is env-driven otherwise."""
    opts = {
        "history": os.environ.get("PIO_TPU_BENCH_HISTORY", "0") == "1",
        "history_file": os.environ.get("PIO_TPU_BENCH_HISTORY_FILE"),
        "threshold": DEFAULT_REGRESSION_THRESHOLD,
    }
    it = iter(argv)
    for a in it:
        if a == "--history":
            opts["history"] = True
        elif a == "--history-file":
            opts["history_file"] = next(it, None)
        elif a.startswith("--history-file="):
            opts["history_file"] = a.split("=", 1)[1]
        elif a == "--regression-threshold":
            raw = next(it, None)
            try:
                opts["threshold"] = float(raw)
            except (TypeError, ValueError):
                print(f"# bad --regression-threshold {raw!r}; keeping "
                      f"{opts['threshold']}", file=sys.stderr)
        elif a.startswith("--regression-threshold="):
            raw = a.split("=", 1)[1]
            try:
                opts["threshold"] = float(raw)
            except ValueError:
                print(f"# bad --regression-threshold {raw!r}; keeping "
                      f"{opts['threshold']}", file=sys.stderr)
    return opts


def maybe_record_history(full: dict, summary: dict, argv: list) -> None:
    """Append this run to the ledger and print the delta table (stderr).
    Best-effort by design: a ledger problem must never cost the summary
    line. The previous run compared against is the last ledger row with
    the SAME smoke flag — comparing a smoke run against a full-scale one
    would flag phantom regressions."""
    opts = parse_history_argv(argv)
    if not opts["history"]:
        return
    try:
        path = opts["history_file"] or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), HISTORY_BASENAME
        )
        rec = history_record(full, summary)
        prior = [
            r for r in read_history(path)
            if r.get("smoke") == rec.get("smoke")
        ]
        append_history(rec, path)
        print(f"# history appended to {path} "
              f"({'smoke' if rec['smoke'] else 'full'} run)",
              file=sys.stderr)
        if prior:
            lines, regressed = history_delta_table(
                prior[-1], rec, opts["threshold"]
            )
            for line in lines:
                print(f"# {line}", file=sys.stderr)
            if regressed:
                print(f"# REGRESSION in: {', '.join(regressed)}",
                      file=sys.stderr)
        else:
            print("# no prior comparable run in ledger; baseline row "
                  "recorded", file=sys.stderr)
    except Exception as exc:
        print(f"# bench history failed: {exc}", file=sys.stderr)


def run_check_history(argv: list) -> int:
    """``bench.py --check-history``: no benchmark run — read the ledger,
    diff the last two rows with the matching smoke flag, exit 1 on a
    regression past the threshold. Smoke wires this after its bench
    stage so a silent slowdown fails the pipeline loudly (ISSUE 16).
    Must run before :func:`main`'s PIO_TPU_HOME override — it only
    reads the ledger, it must not create a throwaway home."""
    opts = parse_history_argv(argv)
    path = opts["history_file"] or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), HISTORY_BASENAME
    )
    rows = read_history(path)
    if not rows:
        print(f"# no bench history at {path}; nothing to check",
              file=sys.stderr)
        return 0
    same = [r for r in rows if r.get("smoke") == rows[-1].get("smoke")]
    if len(same) < 2:
        print("# only one comparable run in ledger; baseline recorded, "
              "nothing to diff", file=sys.stderr)
        return 0
    lines, regressed = history_delta_table(
        same[-2], same[-1], opts["threshold"]
    )
    for line in lines:
        print(f"# {line}", file=sys.stderr)
    if regressed:
        print(f"# REGRESSION in: {', '.join(regressed)}", file=sys.stderr)
        return 1
    print("# no regression past threshold", file=sys.stderr)
    return 0


def main() -> None:
    # isolate the serving benchmark's storage in a throwaway home (must be
    # set before the first Storage touch; always overridden — bench junk
    # must never land in a real deployment home)
    os.environ["PIO_TPU_HOME"] = tempfile.mkdtemp(prefix="pio_tpu_bench_")
    t_main = time.perf_counter()
    import jax

    from pio_tpu.models.als import ALSConfig
    from pio_tpu.parallel.context import ComputeContext, default_mesh

    n_edges = int(os.environ.get("PIO_TPU_BENCH_EDGES", ML25M_EDGES))
    scale = n_edges / ML25M_EDGES
    n_users = max(64, int(ML25M_USERS * min(scale, 1.0)))
    n_items = max(64, int(ML25M_ITEMS * min(scale, 1.0)))
    # reference ALS template default numIterations=10 — the honest
    # workload depth; also amortizes fixed host/wire costs on BOTH the
    # accelerator and the anchor side, which stabilizes vs_baseline
    # against the tunnel's bandwidth swings
    iters = int(os.environ.get("PIO_TPU_BENCH_ITERS", 10))
    rank = int(os.environ.get("PIO_TPU_BENCH_RANK", 16))
    n_queries = int(os.environ.get("PIO_TPU_BENCH_QUERIES", 200))
    cfg = ALSConfig(rank=rank, iterations=iters, reg=0.1)

    u, i, r = _synth_ratings(n_edges, n_users, n_items)

    devices = jax.devices()
    n_chips = len(devices)
    ctx = ComputeContext(mesh=default_mesh(("data",), devices=devices))
    link_mb_s = _probe_link_mb_s()
    times, factors = _time_train(ctx, u, i, r, n_users, n_items, cfg)
    dt_median = times[len(times) // 2]
    rate_per_chip = n_edges * iters / dt_median / n_chips
    rate_best = n_edges * iters / times[0] / n_chips

    # phase decomposition: one PROFILED run (already warm) with blocking
    # between host-pack / host→device / device-compute — answers "how much
    # of the headline is TPU and how much is the link"
    phases = {}
    try:
        from pio_tpu.models.als import train_als as _train_als

        st = {}
        _train_als(ctx, u, i, r, n_users, n_items, cfg, stats=st)
        # normal-equation build term only (4·K·(K+1) FLOPs/edge/iter);
        # solves + packing excluded → conservative
        flops = 4 * cfg.rank * (cfg.rank + 1) * n_edges * iters
        phases = {
            "pack_s": round(st["pack_s"], 3),
            "h2d_s": round(st["h2d_s"], 3),
            "device_s": round(st["device_s"], 3),
            "wire_bytes": int(st["wire_bytes"]),
            "wire_mb_per_s": round(
                st["wire_bytes"] / st["h2d_s"] / 1e6, 1
            ),
            "encoding": st["encoding"],
            "n_stream": st["n_stream"],
            "overlapped_total_s": round(dt_median, 3),
            "device_examples_per_sec": round(
                n_edges * iters / st["device_s"], 1
            ),
            "achieved_gflops": round(flops / st["device_s"] / 1e9, 1),
        }
    except Exception as exc:
        print(f"# phase profile failed: {exc}", file=sys.stderr)

    p50_inproc = _predict_p50_inproc_ms(factors, n_users, n_queries)
    try:
        serving = _bench_server_p50(factors, n_users, n_items, n_queries)
    except Exception as exc:  # the headline number must survive a serving
        # stack failure; report the hole rather than crash
        print(f"# server p50 failed: {exc}", file=sys.stderr)
        serving = {}
    try:
        serving["pool"] = _bench_pool_serving(factors, n_users, n_items)
    except Exception as exc:
        print(f"# pool serving stage failed: {exc}", file=sys.stderr)
    try:
        serving["sharded"] = _bench_sharded_serving(
            factors, n_users, n_items,
            baseline_qps=serving.get("pool", {}).get("laned_qps"),
        )
    except Exception as exc:
        print(f"# sharded serving stage failed: {exc}", file=sys.stderr)
    try:
        serving["resident"] = _bench_resident_serving(
            min(n_queries, 200)
        )
    except Exception as exc:
        print(f"# resident serving stage failed: {exc}", file=sys.stderr)
    try:
        serving["evfront"] = _bench_evfront(min(n_queries, 400))
    except Exception as exc:
        print(f"# evfront serving stage failed: {exc}", file=sys.stderr)
    p50_server = serving.get("p50_ms")

    # CPU anchor: same XLA program, single host CPU device, subsampled edges.
    cpu_edges = int(os.environ.get("PIO_TPU_BENCH_CPU_EDGES",
                                   min(n_edges, 2_000_000)))
    cpu_rate = None
    try:
        cpu_dev = jax.devices("cpu")[0]
        sub = slice(0, cpu_edges)
        cpu_cfg = ALSConfig(rank=rank, iterations=iters, reg=0.1)
        with jax.default_device(cpu_dev):
            cpu_ctx = ComputeContext(mesh=None)
            # same median-of-N and the same iteration count as the
            # accelerator side: an asymmetric comparison (median vs best,
            # or amortized vs unamortized fixed costs) would inflate
            # vs_baseline
            cpu_times, _ = _time_train(cpu_ctx, u[sub], i[sub], r[sub],
                                       n_users, n_items, cpu_cfg,
                                       repeats=3)
        cpu_rate = cpu_edges * iters / cpu_times[len(cpu_times) // 2]
    except Exception as exc:  # pragma: no cover - CPU backend always present
        print(f"# cpu anchor failed: {exc}", file=sys.stderr)

    secondary = {}
    if os.environ.get("PIO_TPU_BENCH_SECONDARY", "1") != "0":
        sscale = float(os.environ.get("PIO_TPU_BENCH_SCALE", "1"))
        cpu_dev = jax.devices("cpu")[0]
        # the one JSON line must always print: past the deadline the
        # remaining secondary stages are skipped (with a stderr note)
        # rather than risking the whole run being cut off
        deadline_s = float(
            os.environ.get("PIO_TPU_BENCH_DEADLINE_S", "3000")
        )

        def over_deadline(stage: str) -> bool:
            if time.perf_counter() - t_main > deadline_s:
                print(f"# deadline reached; skipping {stage}",
                      file=sys.stderr)
                return True
            return False

        def run_on_cpu(fn, frac):
            """Own-CPU anchor: SAME program on the XLA-CPU device, with a
            subsampled workload (rates normalize per example, so the
            ratio is per-example speedup — the headline's anchor
            discipline applied to every config)."""
            with jax.default_device(cpu_dev):
                cpu_ctx = ComputeContext(
                    mesh=default_mesh(("data",), devices=[cpu_dev])
                )
                return fn(cpu_ctx, sscale * frac)

        for name, fn, cpu_frac in (
            ("classification_examples_per_sec", _bench_classification,
             0.25),
            ("similarproduct_examples_per_sec", _bench_similarproduct,
             0.1),
            ("twotower_examples_per_sec", _bench_twotower, 1.0),
        ):
            if over_deadline(name):
                continue  # note every skipped stage, not just the first
            try:
                def split(res):
                    # stages may return {"value": rate, ...metadata}
                    # (anchor methodology, link probe, accuracy) or a
                    # bare rate
                    if isinstance(res, dict):
                        extra = dict(res)
                        return float(extra.pop("value")), extra
                    return float(res), {}

                v, extra = split(fn(ctx, sscale))
                entry = {"value": round(v, 1), **extra}
                try:
                    cv, cextra = split(run_on_cpu(fn, cpu_frac))
                    entry["cpu_anchor"] = round(cv, 1)
                    entry["vs_baseline"] = round(v / cv, 2)
                    if "train_acc" in cextra:
                        # accuracy honesty: the quantized/bf16 device
                        # wire must not buy throughput with quality
                        entry["anchor_train_acc"] = cextra["train_acc"]
                except Exception as exc:
                    print(f"# cpu anchor {name} failed: {exc}",
                          file=sys.stderr)
                secondary[name] = entry
            except Exception as exc:
                print(f"# secondary {name} failed: {exc}", file=sys.stderr)

        if "twotower_examples_per_sec" in secondary:
            # achieved matmul GFLOP/s from the analytic per-example count
            # (two towers + the [B, B] in-batch-negative logits, ×3 for
            # backward; embedding gathers excluded → conservative). Uses
            # the e2e rate, so fixed host staging costs are included.
            B, E, H, O = _TT_BATCH, _TT_EMBED, _TT_HIDDEN, _TT_OUT
            fpe = 3 * (2 * (2 * E * H + 2 * H * O) + 2 * B * O)
            tt = secondary["twotower_examples_per_sec"]
            g = tt["value"] * fpe / 1e9
            tt["achieved_gflops"] = round(g, 1)
            tt["roofline_note"] = (
                f"{g / _V5E_BF16_PEAK_GFLOPS:.2%} of v5e bf16 peak — "
                "e2e wall-clock; bound = output table readback over "
                "the host link (see phases), training is one "
                "compiled scan"
            )

        if not over_deadline("train.streamed"):
            try:
                secondary["train_streamed"] = _bench_train_streamed(
                    ctx, sscale
                )
            except Exception as exc:
                print(f"# secondary train.streamed failed: {exc}",
                      file=sys.stderr)

        if not over_deadline("seqrec"):
            try:
                secondary["seqrec"] = _bench_seqrec(ctx, sscale)
            except Exception as exc:
                print(f"# secondary seqrec failed: {exc}", file=sys.stderr)

        if not over_deadline("textclassification"):
            try:
                tc = _bench_textclass(sscale)
                try:
                    with jax.default_device(cpu_dev):
                        tc_cpu = _bench_textclass(sscale * 0.25)
                    # the shipped op dispatches to XLA at this shape, so
                    # the device number of record is the faster path
                    best = max(
                        tc.get("pallas_tokens_per_sec", 0.0),
                        tc["xla_tokens_per_sec"],
                    )
                    tc["cpu_anchor"] = tc_cpu["xla_tokens_per_sec"]
                    tc["vs_baseline"] = round(
                        best / tc_cpu["xla_tokens_per_sec"], 2
                    )
                except Exception as exc:
                    print(f"# cpu anchor textclassification failed: {exc}",
                          file=sys.stderr)
                secondary["textclassification"] = tc
            except Exception as exc:
                print(f"# secondary textclassification failed: {exc}",
                      file=sys.stderr)

        if os.environ.get("PIO_TPU_BENCH_RANKSWEEP", "1") != "0" \
                and not over_deadline("als_rank_sweep"):
            try:
                secondary["als_rank_sweep"] = _bench_rank_sweep(
                    ctx, sscale
                )
            except Exception as exc:
                print(f"# rank sweep failed: {exc}", file=sys.stderr)

        if not over_deadline("eventserver_events_per_sec"):
            try:
                secondary["eventserver_events_per_sec"] = (
                    _bench_event_ingest(sscale)
                )
            except Exception as exc:
                print(f"# event ingest failed: {exc}", file=sys.stderr)

        if not over_deadline("ingest.partitioned"):
            try:
                secondary["ingest_partitioned"] = (
                    _bench_partitioned_ingest(sscale)
                )
            except Exception as exc:
                print(f"# partitioned ingest failed: {exc}",
                      file=sys.stderr)

    vs_baseline = rate_per_chip / cpu_rate if cpu_rate else 1.0
    out = {
        "metric": "ALS@MovieLens-25M examples/sec/chip",
        # tunnel-robust headline: MEDIAN of 5 end-to-end runs, with the
        # same-session link probe and the link-independent device-phase
        # rate promoted alongside (the tunnel swings ~2.5x run to run;
        # a best-of headline seesaws with it — see BASELINE.md)
        "value": round(rate_per_chip, 1),
        "value_best_of_5": round(rate_best, 1),
        "link_mb_s": round(link_mb_s, 1),
        "device_examples_per_sec": phases.get("device_examples_per_sec"),
        "unit": "examples/sec/chip",
        "vs_baseline": round(vs_baseline, 2),
        # BASELINE.md's second tracked metric: serving p50 through a LIVE
        # query server (HTTP); p50_inproc_ms is the round-1 continuity number
        "p50_predict_ms": (
            round(p50_server, 3) if p50_server is not None else None
        ),
        "p50_inproc_ms": round(p50_inproc, 3),
        # phase decomposition of the headline (pack / link / device) +
        # the device-only rate the tunnel hides
        "phases": phases,
        # serving under concurrent load (16 clients): qps/p50/p95, with
        # and without the micro-batching aggregator
        "serving": serving,
        "secondary": secondary,
    }
    line = emit(out)
    maybe_record_history(out, json.loads(line), sys.argv[1:])
    print(line)


if __name__ == "__main__":
    if "--check-history" in sys.argv[1:]:
        sys.exit(run_check_history(sys.argv[1:]))
    main()
