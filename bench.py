"""Benchmark harness — headline metric from BASELINE.json.

Metric: examples/sec/chip on the Recommendation (ALS) template at
MovieLens-25M scale (25M ratings, 162,541 users, 59,047 items). One
"example" = one rating edge processed through one full ALS iteration
(both half-steps). The reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is measured against our own single-host XLA-CPU run of the
same program — the "Spark-free CPU ALS reference anchor" from SURVEY.md §6.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "p50_predict_ms": N}   # last field: serving-path p50 (auxiliary)

Env knobs (for smoke runs): PIO_TPU_BENCH_EDGES, PIO_TPU_BENCH_ITERS,
PIO_TPU_BENCH_RANK, PIO_TPU_BENCH_CPU_EDGES.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# MovieLens-25M shape (ratings, users, movies)
ML25M_EDGES = 25_000_000
ML25M_USERS = 162_541
ML25M_ITEMS = 59_047


def _synth_ratings(n_edges: int, n_users: int, n_items: int, seed: int = 0):
    """Synthetic MovieLens-like COO ratings (zipf-ish item popularity)."""
    rng = np.random.default_rng(seed)
    user_idx = rng.integers(0, n_users, size=n_edges).astype(np.int32)
    # popularity-skewed items: square a uniform to bias toward low ids
    item_idx = (rng.random(n_edges) ** 2 * n_items).astype(np.int32)
    rating = (rng.integers(1, 11, size=n_edges) * 0.5).astype(np.float32)
    return user_idx, item_idx, rating


def _time_train(ctx, u, i, r, n_users, n_items, cfg, repeats=3):
    """Warmup/compile once, then best-of-``repeats`` timed runs (the
    host↔device link shares a tunnel whose bandwidth fluctuates run to
    run; min time is the stable throughput estimate).

    Returns (seconds, trained factors) — the factors feed the serving
    latency measurement.
    """
    from pio_tpu.models.als import train_als

    train_als(ctx, u, i, r, n_users, n_items, cfg)  # warmup/compile
    best, factors = None, None
    for _ in range(repeats):
        t0 = time.perf_counter()
        factors = train_als(ctx, u, i, r, n_users, n_items, cfg)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best, factors


def _predict_p50_ms(factors, n_users: int, n_queries: int = 300) -> float:
    """p50 of the serving hot path (BASELINE.md's second tracked metric):
    one user row against the full item-factor matrix + top-10, exactly
    what Query-server POST /queries.json does per request."""
    from pio_tpu.models.als import predict_scores, top_n

    lat = []
    for q in range(n_queries):
        user = (q * 7919) % n_users
        t0 = time.perf_counter()
        scores = predict_scores(
            factors.user_factors, factors.item_factors, user
        )
        top_n(scores, 10)
        lat.append(time.perf_counter() - t0)
    return float(np.percentile(np.array(lat) * 1000.0, 50))


def main() -> None:
    import jax

    from pio_tpu.models.als import ALSConfig
    from pio_tpu.parallel.context import ComputeContext, default_mesh

    n_edges = int(os.environ.get("PIO_TPU_BENCH_EDGES", ML25M_EDGES))
    scale = n_edges / ML25M_EDGES
    n_users = max(64, int(ML25M_USERS * min(scale, 1.0)))
    n_items = max(64, int(ML25M_ITEMS * min(scale, 1.0)))
    iters = int(os.environ.get("PIO_TPU_BENCH_ITERS", 3))
    rank = int(os.environ.get("PIO_TPU_BENCH_RANK", 16))
    cfg = ALSConfig(rank=rank, iterations=iters, reg=0.1)

    u, i, r = _synth_ratings(n_edges, n_users, n_items)

    devices = jax.devices()
    n_chips = len(devices)
    ctx = ComputeContext(mesh=default_mesh(("data",), devices=devices))
    dt, factors = _time_train(ctx, u, i, r, n_users, n_items, cfg)
    rate_per_chip = n_edges * iters / dt / n_chips
    p50_ms = _predict_p50_ms(factors, n_users)

    # CPU anchor: same XLA program, single host CPU device, subsampled edges.
    cpu_edges = int(os.environ.get("PIO_TPU_BENCH_CPU_EDGES",
                                   min(n_edges, 2_000_000)))
    cpu_rate = None
    try:
        cpu_dev = jax.devices("cpu")[0]
        sub = slice(0, cpu_edges)
        cpu_cfg = ALSConfig(rank=rank, iterations=1, reg=0.1)
        with jax.default_device(cpu_dev):
            cpu_ctx = ComputeContext(mesh=None)
            # same best-of-3 as the accelerator side: an asymmetric
            # (min vs single-run) comparison would inflate vs_baseline
            cpu_dt, _ = _time_train(cpu_ctx, u[sub], i[sub], r[sub],
                                    n_users, n_items, cpu_cfg)
        cpu_rate = cpu_edges * 1 / cpu_dt
    except Exception as exc:  # pragma: no cover - CPU backend always present
        print(f"# cpu anchor failed: {exc}", file=sys.stderr)

    vs_baseline = rate_per_chip / cpu_rate if cpu_rate else 1.0
    print(json.dumps({
        "metric": "ALS@MovieLens-25M examples/sec/chip",
        "value": round(rate_per_chip, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(vs_baseline, 2),
        # BASELINE.md's second tracked metric, as an auxiliary field
        "p50_predict_ms": round(p50_ms, 3),
    }))


if __name__ == "__main__":
    main()
