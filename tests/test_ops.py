"""Pallas ops tests — run on CPU via interpret mode (conftest pins cpu).

The TPU-compiled path is exercised by bench.py and the driver's real-chip
runs; here the same kernel body runs under the Pallas interpreter and must
match the XLA fallback bit-for-bit-ish (f32 tolerances).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pio_tpu.ops.embedding import (
    _embedding_bag_pallas,
    _embedding_bag_xla,
    embedding_bag,
    pack_bags,
)


@pytest.fixture()
def bag_case():
    rng = np.random.default_rng(7)
    V, D, B, L = 64, 128, 5, 11
    table = jnp.asarray(rng.normal(size=(V, D)), jnp.float32)
    ids, w = pack_bags(
        [rng.integers(0, V, size=rng.integers(1, L)) for _ in range(B)],
        [rng.random(L) for _ in range(B)],
    )
    return table, jnp.asarray(ids), jnp.asarray(w)


def test_pack_bags_pads_and_zero_weights():
    ids, w = pack_bags([[3, 4], [5]], [[1.0, 2.0], [0.5]])
    assert ids.shape == w.shape
    assert ids.shape[1] % 8 == 0
    assert ids[0, 0] == 3 and w[0, 1] == 2.0
    assert w[1, 1:].sum() == 0.0  # padding contributes nothing


def test_kernel_matches_xla_interpret(bag_case):
    table, ids, w = bag_case
    ref = _embedding_bag_xla(table, ids, w)
    out = _embedding_bag_pallas(table, ids, w, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_embedding_bag_dispatch_cpu(bag_case):
    # on CPU the public entry point takes the XLA path
    table, ids, w = bag_case
    out = embedding_bag(table, ids, w)
    ref = _embedding_bag_xla(table, ids, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_embedding_bag_grads_match_explicit(bag_case):
    table, ids, w = bag_case

    def loss_custom(t, ww):
        return jnp.sum(embedding_bag(t, ids, ww) ** 2)

    def loss_explicit(t, ww):
        rows = t[ids]
        out = jnp.einsum("bld,bl->bd", rows, ww)
        return jnp.sum(out**2)

    g1t, g1w = jax.grad(loss_custom, argnums=(0, 1))(table, w)
    g2t, g2w = jax.grad(loss_explicit, argnums=(0, 1))(table, w)
    np.testing.assert_allclose(np.asarray(g1t), np.asarray(g2t), atol=1e-3)
    np.testing.assert_allclose(np.asarray(g1w), np.asarray(g2w), atol=1e-3)


def test_duplicate_ids_accumulate():
    table = jnp.asarray(np.eye(8, 128, dtype=np.float32))
    ids = jnp.asarray([[2, 2, 2, 0, 0, 0, 0, 0]], jnp.int32)
    w = jnp.asarray([[1.0, 2.0, 3.0, 0, 0, 0, 0, 0]], jnp.float32)
    out = embedding_bag(table, ids, w)
    assert float(out[0, 2]) == pytest.approx(6.0)


def test_embedding_bag_dispatch_by_intermediate_size(monkeypatch):
    """Dispatch policy: XLA while the gathered [B, L, D] intermediate is
    small (measured faster at equal accuracy on TPU), the Pallas
    streaming kernel beyond the cutoff (O(1) scratch)."""
    import pio_tpu.ops.embedding as emb

    calls = []
    monkeypatch.delenv("PIO_TPU_EMBED_PALLAS_OVER_MB", raising=False)
    monkeypatch.setattr(emb, "_use_pallas", lambda t: True)
    monkeypatch.setattr(
        emb, "_embedding_bag_pallas",
        lambda t, i, w: calls.append("pallas") or emb._embedding_bag_xla(
            t, i, w
        ),
    )
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(64, 128)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, 64, (4, 8)).astype(np.int32))
    w = jnp.asarray(rng.random((4, 8)).astype(np.float32))
    # 4*8*128*4 B = 16 KB — far under any sane cutoff → XLA
    emb.embedding_bag.__wrapped__(table, ids, w)
    assert calls == []
    # force a 1-byte cutoff → kernel path
    monkeypatch.setenv("PIO_TPU_EMBED_PALLAS_OVER_MB", "0.000001")
    emb.embedding_bag.__wrapped__(table, ids, w)
    assert calls == ["pallas"]
