"""Server plugin hook tests (reference EventServerPlugin/EngineServerPlugin)."""

import json
import urllib.error
import urllib.request

import pytest

import pio_tpu.templates  # noqa: F401
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.server import (
    EngineServerPlugin,
    EventServerPlugin,
    clear_plugins,
    create_event_server,
    create_query_server,
    installed_plugins,
    register_plugin,
)
from pio_tpu.server.plugins import (
    INPUT_BLOCKER,
    OUTPUT_BLOCKER,
    OUTPUT_SNIFFER,
    load_plugins_from_env,
)
from pio_tpu.storage import AccessKey, App, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict


@pytest.fixture(autouse=True)
def isolated(tmp_home):
    Storage.reset()
    clear_plugins()
    yield
    clear_plugins()
    Storage.reset()


def http(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


class Blocklist(EventServerPlugin):
    plugin_name = "blocklist"
    plugin_description = "rejects banned entity ids"
    plugin_type = INPUT_BLOCKER

    def __init__(self):
        self.seen = []

    def process(self, event, app_id, channel_id):
        self.seen.append(event.get("entityId"))
        if event.get("entityId") == "banned":
            raise ValueError("entity is banned")


class ResponseTap(EngineServerPlugin):
    plugin_name = "tap"
    plugin_description = "records responses"
    plugin_type = OUTPUT_SNIFFER

    def __init__(self):
        self.outputs = []

    def process(self, query, prediction):
        self.outputs.append((query, prediction))


class TestEventServerPlugins:
    def test_input_blocker_rejects(self):
        plugin = Blocklist()
        register_plugin(plugin)
        app_id = Storage.get_meta_data_apps().insert(App(0, "plg"))
        key = Storage.get_meta_data_access_keys().insert(AccessKey("", app_id))
        server = create_event_server(host="127.0.0.1", port=0).start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            ok = {"event": "view", "entityType": "user", "entityId": "fine"}
            status, _ = http("POST", f"{base}/events.json?accessKey={key}", ok)
            assert status == 201
            bad = {"event": "view", "entityType": "user", "entityId": "banned"}
            status, body = http(
                "POST", f"{base}/events.json?accessKey={key}", bad
            )
            assert status == 400 and "banned" in body["message"]
            assert plugin.seen == ["fine", "banned"]
            # nothing persisted for the blocked event
            assert len(Storage.get_pevents().find(app_id)) == 1
            # plugins listed
            status, listing = http("GET", f"{base}/plugins.json")
            assert listing["eventServerPlugins"][0]["name"] == "blocklist"
        finally:
            server.stop()


class TestEngineServerPlugins:
    def test_output_sniffer_sees_responses(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "plg-q"))
        le = Storage.get_levents()
        import datetime as dt

        t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
        for u in range(4):
            for i in range(4):
                if (u < 2) == (i < 2):
                    le.insert(
                        Event("rate", "user", f"u{u}", "item", f"i{i}",
                              properties={"rating": 5.0},
                              event_time=t0),
                        app_id,
                    )
        variant = variant_from_dict({
            "id": "plg-e2e",
            "engineFactory": "templates.recommendation",
            "datasource": {"params": {"app_name": "plg-q"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "num_iterations": 5, "lambda_": 0.1}}],
        })
        engine, ep = build_engine(variant)
        run_train(engine, ep, variant, ctx=ComputeContext.create(seed=0))

        tap = ResponseTap()
        register_plugin(tap)
        server, _service = create_query_server(
            variant, host="127.0.0.1", port=0
        )
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            status, body = http(
                "POST", f"{base}/queries.json", {"user": "u0", "num": 2}
            )
            assert status == 200 and body["itemScores"]
            assert len(tap.outputs) == 1
            query, out = tap.outputs[0]
            assert query == {"user": "u0", "num": 2}
            assert out["itemScores"]
            status, listing = http("GET", f"{base}/plugins.json")
            assert listing["engineServerPlugins"][0]["name"] == "tap"
        finally:
            server.stop()


class QueryVeto(EngineServerPlugin):
    plugin_name = "veto"
    plugin_type = OUTPUT_BLOCKER

    def __init__(self):
        self.predictions = []

    def process(self, query, prediction):
        # blockers run post-predict: the response must be visible here
        self.predictions.append(prediction)
        if isinstance(query, dict) and query.get("user") == "blocked":
            raise ValueError("user is blocked")
        if prediction is None:
            raise ValueError("blocker saw no prediction")


class TestOutputBlocker:
    def test_veto_is_client_400(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "plg-b"))
        le = Storage.get_levents()
        import datetime as dt

        t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
        for u in range(4):
            for i in range(4):
                le.insert(
                    Event("rate", "user", f"u{u}", "item", f"i{i}",
                          properties={"rating": 3.0}, event_time=t0),
                    app_id,
                )
        variant = variant_from_dict({
            "id": "plg-b",
            "engineFactory": "templates.recommendation",
            "datasource": {"params": {"app_name": "plg-b"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "num_iterations": 3, "lambda_": 0.1}}],
        })
        engine, ep = build_engine(variant)
        run_train(engine, ep, variant, ctx=ComputeContext.create(seed=0))
        veto = QueryVeto()
        register_plugin(veto)
        server, _svc = create_query_server(variant, host="127.0.0.1", port=0)
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            status, body = http(
                "POST", f"{base}/queries.json", {"user": "blocked"}
            )
            assert status == 400 and "blocked" in body["message"]
            status, _ = http(
                "POST", f"{base}/queries.json", {"user": "u0"}
            )
            assert status == 200
            # blocker received real predictions, not None
            assert len(veto.predictions) == 2
            assert all(p is not None for p in veto.predictions)
        finally:
            server.stop()


class TestPluginTypeValidation:
    def test_unknown_event_plugin_type_rejected(self):
        class Typo(EventServerPlugin):
            plugin_name = "typo"
            plugin_type = "input_blocker"  # not the INPUT_BLOCKER constant

            def process(self, event, app_id, channel_id):
                raise ValueError("should never install")

        with pytest.raises(ValueError, match="plugin_type"):
            register_plugin(Typo())
        assert installed_plugins()["eventServerPlugins"] == []

    def test_unknown_engine_plugin_type_rejected(self):
        class Typo(EngineServerPlugin):
            plugin_name = "typo"
            plugin_type = "OutputBlocker"

            def process(self, query, prediction):
                raise ValueError("should never install")

        with pytest.raises(ValueError, match="plugin_type"):
            register_plugin(Typo())
        assert installed_plugins()["engineServerPlugins"] == []


class TestEnvDiscovery:
    def test_load_plugins_from_env(self, monkeypatch, tmp_path):
        mod = tmp_path / "my_test_plugin.py"
        mod.write_text(
            "from pio_tpu.server import EventServerPlugin, register_plugin\n"
            "class P(EventServerPlugin):\n"
            "    plugin_name = 'envp'\n"
            "    def process(self, event, app_id, channel_id):\n"
            "        pass\n"
            "register_plugin(P())\n"
        )
        monkeypatch.syspath_prepend(str(tmp_path))
        monkeypatch.setenv("PIO_TPU_PLUGINS", "my_test_plugin")
        loaded = load_plugins_from_env()
        assert loaded == ["my_test_plugin"]
        names = [
            p["name"] for p in installed_plugins()["eventServerPlugins"]
        ]
        assert "envp" in names

    def test_reload_after_clear_reregisters(self, monkeypatch, tmp_path):
        # import caching must not leave the registry empty on a second load
        mod = tmp_path / "my_reload_plugin.py"
        mod.write_text(
            "from pio_tpu.server import EventServerPlugin, register_plugin\n"
            "class P(EventServerPlugin):\n"
            "    plugin_name = 'reloaded'\n"
            "    def process(self, event, app_id, channel_id):\n"
            "        pass\n"
            "register_plugin(P())\n"
        )
        monkeypatch.syspath_prepend(str(tmp_path))
        monkeypatch.setenv("PIO_TPU_PLUGINS", "my_reload_plugin")
        assert load_plugins_from_env() == ["my_reload_plugin"]
        clear_plugins()
        assert load_plugins_from_env() == ["my_reload_plugin"]
        names = [
            p["name"] for p in installed_plugins()["eventServerPlugins"]
        ]
        assert names.count("reloaded") == 1

    def test_bad_module_is_logged_not_fatal(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_PLUGINS", "definitely_not_a_module")
        assert load_plugins_from_env() == []
