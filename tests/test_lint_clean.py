"""Tier-1 gate: the repo's own code must pass `pio lint` clean.

This is the whole point of a project-native linter — every rule ships
with the tree already conforming, so any finding here is a regression
introduced by the change under test (or a rule bug; either way it
blocks).
"""

from __future__ import annotations

import os

from pio_tpu.analysis import run_lint
from pio_tpu.analysis.core import all_rules

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_interprocedural_rules_registered():
    """The hot-path contract rules run as part of the clean gate —
    losing one of them would silently drop the CI enforcement."""
    rules = all_rules()
    for rid in ("hotpath-blocking", "hotpath-zero-copy",
                "shm-frame-layout", "lock-blocking-call"):
        assert rid in rules, f"rule {rid} missing from registry"


def test_repo_is_lint_clean():
    findings = run_lint([
        os.path.join(REPO_ROOT, "pio_tpu"),
        os.path.join(REPO_ROOT, "tests"),
    ])
    assert findings == [], "pio lint findings:\n" + "\n".join(
        f.render() for f in findings
    )
