"""Tier-1 gate: the repo's own code must pass `pio lint` clean.

This is the whole point of a project-native linter — every rule ships
with the tree already conforming, so any finding here is a regression
introduced by the change under test (or a rule bug; either way it
blocks).
"""

from __future__ import annotations

import os

from pio_tpu.analysis import run_lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_is_lint_clean():
    findings = run_lint([
        os.path.join(REPO_ROOT, "pio_tpu"),
        os.path.join(REPO_ROOT, "tests"),
    ])
    assert findings == [], "pio lint findings:\n" + "\n".join(
        f.render() for f in findings
    )
