"""Chaos coverage (ISSUE 20): every registered failpoint is armed
through its REAL call path at least once, so the ``failpoint-coverage``
lint rule holds on the live tree.

These are not unit tests of the fault registry (tests/test_faults.py
owns that) — each test installs a fault spec and then drives the
production code that hosts the failpoint: a recorder recording, a lane
draining, a router hedging, a scorer dispatching. Arming through the
real path is the point: it proves the failpoint still sits on the
code the chaos specs think it guards.
"""

import datetime as dt
import json
import threading
import time

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers engine factories)
from pio_tpu import faults
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.faults import FaultInjected
from pio_tpu.obs import trainwatch
from pio_tpu.obs.metrics import MetricsRegistry, monotonic_s
from pio_tpu.router.core import ServingRouter
from pio_tpu.server.batchlane import (
    BatchLaneSegment,
    LaneClient,
    LaneDrainer,
)
from pio_tpu.server.http import JsonHTTPServer, Router
from pio_tpu.server.query_server import QueryServerService
from pio_tpu.storage import App, Storage
from pio_tpu.storage.blobstore import FileBlobBackend
from pio_tpu.storage.partlog import PartitionedEventLog
from pio_tpu.storage.partlog.segments import SegmentLog
from pio_tpu.templates.classification import Query
from pio_tpu.workflow import build_engine, run_train, variant_from_dict


@pytest.fixture(autouse=True)
def clean_faults():
    faults.uninstall()
    yield
    faults.uninstall()


def _T(h=1):
    return dt.datetime(2026, 1, 1, h, tzinfo=dt.timezone.utc)


def _ev(i=0):
    return Event(event="rate", entity_type="user", entity_id=f"u{i}",
                 properties={"rating": float(i)}, event_time=_T())


# ---------------------------------------------------------- trainwatch
class TestTrainwatchChaos:
    def test_record_failpoint_fires_on_real_step(self):
        rec = trainwatch.StepRecorder("run-chaos", "eng-chaos")
        rec.begin_algo("als", total_steps=4)
        faults.install("trainwatch.record=error")
        with pytest.raises(FaultInjected):
            rec.record_steps(1, examples=10)
        faults.uninstall()
        rec.record_steps(1, examples=10)
        # the injected step never landed — failure before mutation
        assert rec.steps_done == 1

    def test_payload_failpoint_fires_on_scrape(self):
        rec = trainwatch.StepRecorder("run-chaos", "eng-chaos")
        faults.install("trainwatch.payload=error")
        with pytest.raises(FaultInjected):
            rec.payload()
        faults.uninstall()
        assert isinstance(rec.payload(), dict)

    def test_append_failpoint_blocks_ledger_write(self, tmp_path):
        path = str(tmp_path / "runs.jsonl")
        record = {"engine_id": "eng-chaos", "run_id": "r1"}
        faults.install("trainwatch.append=error")
        with pytest.raises(FaultInjected):
            trainwatch.append_run(record, path=path)
        faults.uninstall()
        trainwatch.append_run(record, path=path)
        # exactly the post-fault append is on disk — the injected one
        # failed before the file was touched
        assert len(trainwatch.read_runs(path=path)) == 1


# ------------------------------------------------------------- storage
try:
    from pio_tpu.native import event_log_lib

    event_log_lib()
    from pio_tpu.storage.eventlog import EventLogEvents

    _HAVE_NATIVE = True
except Exception:  # pragma: no cover - no toolchain
    _HAVE_NATIVE = False

needs_native = pytest.mark.skipif(
    not _HAVE_NATIVE, reason="native eventlog unavailable"
)


class TestStorageChaos:
    @needs_native
    def test_eventlog_after_write_window_is_durable(self, tmp_path):
        b = EventLogEvents(str(tmp_path / "log"))
        faults.install("eventlog.append.after_write=error")
        with pytest.raises(FaultInjected):
            b.insert(_ev(0), 1)
        faults.uninstall()
        # the fault fired AFTER the bytes landed: the row is durable
        # even though the caller saw an error — the crash-between-
        # write-and-ack window every at-least-once producer must absorb
        assert b.count(1) == 1

    def test_partlog_scan_and_compact_failpoints(self, tmp_path):
        b = PartitionedEventLog(str(tmp_path / "plog"))
        b.insert(_ev(0), 1)
        faults.install("partlog.scan=error")
        with pytest.raises(FaultInjected):
            b.find(1)
        faults.uninstall()
        assert len(b.find(1)) == 1
        faults.install("partlog.compact=error")
        with pytest.raises(FaultInjected):
            b.compact()
        faults.uninstall()
        assert isinstance(b.compact(), dict)

    def test_partlog_seal_failpoint_fires_on_rollover(self, tmp_path):
        s = SegmentLog(str(tmp_path / "p"), partition=0, seg_bytes=40)
        faults.install("partlog.seal=error")
        with pytest.raises(FaultInjected):
            for _ in range(8):
                s.append(b"x" * 24)  # crosses seg_bytes → seal fires
        faults.uninstall()

    def test_repl_connect_failpoint(self):
        from pio_tpu.storage.partlog.replication import _FollowerLink

        owner = type("Owner", (), {"partitions": 2})()
        link = _FollowerLink(
            owner, ("127.0.0.1", 1), threading.Condition()
        )
        faults.install("repl.connect=error")
        # fires before any socket is opened — the reconnect loop's
        # first casualty, which the link's backoff must absorb
        with pytest.raises(FaultInjected):
            link._connect()

    def test_blobstore_persist_failpoint_leaves_no_partial(
            self, tmp_path):
        b = FileBlobBackend(str(tmp_path / "root"))
        faults.install("storage.blobstore.persist=error")
        with pytest.raises(FaultInjected):
            b.put("models/m1", b"payload")
        faults.uninstall()
        # a failed publish is invisible: no blob, no staging litter
        assert b.get("models/m1") is None
        litter = [p for p in (tmp_path / "root").rglob("*")
                  if p.is_file()]
        assert litter == []
        b.put("models/m1", b"payload")
        assert b.get("models/m1") == b"payload"


# ----------------------------------------------------------- batch lane
class TestLaneChaos:
    def _lane(self, tmp_path, n_workers=2):
        seg = BatchLaneSegment.create(
            str(tmp_path / "lane.shm"), n_workers
        )
        doorbell = threading.Event()
        resp = [threading.Event() for _ in range(n_workers)]
        return seg, doorbell, resp

    def test_submit_failpoint_fires_before_the_ring(self, tmp_path):
        seg, doorbell, resp = self._lane(tmp_path)
        client = LaneClient(seg, 1, doorbell, resp[1], timeout_s=1.0)
        faults.install("batchlane.submit=error")
        with pytest.raises(FaultInjected):
            client.submit({"user": "u1"})
        faults.uninstall()
        # nothing was posted — the fault preceded slot allocation
        assert seg.pending_depth() == 0

    def test_drain_failpoint_fires_per_cycle(self, tmp_path):
        seg, doorbell, resp = self._lane(tmp_path)
        drainer = LaneDrainer(seg, lambda bodies: [], doorbell, resp)
        faults.install("batchlane.drain=error")
        with pytest.raises(FaultInjected):
            drainer.drain_once()
        faults.uninstall()
        assert drainer.drain_once() == 0


# --------------------------------------------------------------- router
class _ChaosMember:
    """Minimal live member for the hedge path: /queries.json answers
    with its own name after an optional delay."""

    def __init__(self, name, delay_s=0.0):
        self.name = name
        self.delay_s = delay_s
        router = Router()
        router.add("POST", "/queries\\.json", self._query)
        self.server = JsonHTTPServer(
            router, "127.0.0.1", 0, name=f"chaos-{name}"
        ).start()
        self.port = self.server.port

    def _query(self, req):
        if self.delay_s:
            time.sleep(self.delay_s)
        return 200, {"member": self.name}

    def stop(self):
        self.server.stop()


class TestRouterChaos:
    def test_hedge_failpoint_sits_on_the_hedge_decision(self):
        slow = _ChaosMember("a", delay_s=0.4)
        fast = _ChaosMember("b")
        sr = ServingRouter(
            [("a", f"http://127.0.0.1:{slow.port}"),
             ("b", f"http://127.0.0.1:{fast.port}")],
            MetricsRegistry(), hedge_ms=40.0,
        )
        try:
            entity = next(
                k for k in (f"user{i}" for i in range(400))
                if sr.ring.rank(k)[0] == "a"
            )
            faults.install("router.forward.hedge=error")
            # the fault fires exactly when the budget elapses and the
            # hedge would launch — never on the fast path
            with pytest.raises(FaultInjected):
                sr.forward(
                    "POST", "/queries.json", b"{}", {},
                    entity_id=entity, priority="interactive",
                )
            faults.uninstall()
            t0 = monotonic_s()
            status, _, _, member = sr.forward(
                "POST", "/queries.json", b"{}", {},
                entity_id=entity, priority="interactive",
            )
            assert status == 200 and member == "b"
            assert monotonic_s() - t0 < 0.35
        finally:
            sr.close()
            slow.stop()
            fast.stop()


# --------------------------------------------------------------- scorer
def _seed_users(app_id):
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    rng = np.random.default_rng(7)
    n = 0
    for plan, hot in (("basic", 0), ("premium", 1), ("pro", 2)):
        for _ in range(8):
            attrs = rng.integers(0, 3, size=3)
            attrs[hot] += 6
            props = {f"attr{j}": int(attrs[j]) for j in range(3)}
            props["plan"] = plan
            le.insert(
                Event("$set", "user", f"u{n}", properties=props,
                      event_time=t0 + dt.timedelta(minutes=n)),
                app_id,
            )
            n += 1


@pytest.fixture()
def scorer_service(tmp_home, monkeypatch):
    Storage.reset()
    monkeypatch.setenv("PIO_TPU_DEVICE_RESIDENT", "1")
    monkeypatch.setenv("PIO_TPU_BATCH_BUCKETS", "1,2,4")
    monkeypatch.setenv("PIO_TPU_BUCKET_WARMUP", "1")
    app_id = Storage.get_meta_data_apps().insert(App(0, "chaos-test"))
    _seed_users(app_id)
    variant = variant_from_dict({
        "id": "chaos-e2e",
        "engineFactory": "templates.classification",
        "datasource": {"params": {"app_name": "chaos-test"}},
        "algorithms": [{"name": "logreg", "params": {}}],
    })
    engine, ep = build_engine(variant)
    ctx = ComputeContext.create(seed=0)
    run_train(engine, ep, variant, ctx=ctx)
    yield QueryServerService(variant, ctx=ctx)
    Storage.reset()


class TestScorerChaos:
    def test_solo_dispatch_failpoint(self, scorer_service):
        q = Query(attrs=(9.0, 1.0, 1.0))
        faults.install("scorer.dispatch.solo=error")
        with pytest.raises(FaultInjected):
            scorer_service._predict_one(q)
        faults.uninstall()
        assert scorer_service._predict_one(q).label == "basic"

    def test_batch_dispatch_failpoint(self, scorer_service):
        qs = [Query(attrs=(9.0, 1.0, 1.0)),
              Query(attrs=(1.0, 9.0, 1.0))]
        faults.install("scorer.dispatch.batch=error")
        with pytest.raises(FaultInjected):
            scorer_service._predict_batch(qs)
        faults.uninstall()
        got = scorer_service._predict_batch(qs)
        assert [r.label for r in got] == ["basic", "premium"]

    def test_packed_dispatch_failpoint(self, scorer_service):
        frame = scorer_service.pack_query_body(
            {"attrs": [9.0, 1.0, 1.0]}
        )
        assert frame is not None  # int8 resident scorer is placed
        faults.install("scorer.dispatch.packed=error")
        with pytest.raises(FaultInjected):
            scorer_service._query_packed_local(frame)
        faults.uninstall()
        out = json.loads(scorer_service._query_packed_local(frame))
        assert out["label"] == "basic"
