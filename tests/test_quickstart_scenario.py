"""Integration scenario: the full quickstart lifecycle through REAL
processes — the rebuild of the reference's Python integration harness
(``tests/pio_tests/scenarios/quickstart_test.py``, SURVEY.md §4 tier 2:
app new → ingest over HTTP → train → deploy → query → undeploy), with
`python -m pio_tpu` subprocesses instead of pio shell scripts.

Every step crosses a process boundary: state flows only through the
storage layer ($PIO_TPU_HOME sqlite defaults) and HTTP.
"""

import json
import os
import re
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from pio_tpu.obs import monotonic_s

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _cli_env(home):
    env = dict(os.environ)
    env["PIO_TPU_HOME"] = str(home)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    # the scenario exercises process plumbing, not collectives
    env.pop("XLA_FLAGS", None)
    return env


def _run(args, env, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "pio_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def _wait_http(url, timeout=60):
    deadline = monotonic_s() + timeout
    while monotonic_s() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.3)
    raise TimeoutError(f"server at {url} never came up")


def _post(url, body, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


#: storage-combo matrix — the reference CI ran its quickstart over
#: backend combinations (SURVEY.md §4: "matrix over storage combos";
#: PGSQL-everything; ES-meta + HBase-events + localfs-models). The
#: analogs here: sqlite-everything (default), searchable-meta +
#: native-eventlog-events + blob-models, searchable-everything.
STORAGE_COMBOS = {
    "default": {},
    "es-hbase-analog": {
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "ES",
        "PIO_STORAGE_SOURCES_ES_TYPE": "elasticsearch",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "BLOB",
        "PIO_STORAGE_SOURCES_BLOB_TYPE": "blob",
        # also exercise the serving micro-batch aggregator through the
        # CLI-deployed server in this combo
        "PIO_TPU_SERVE_MICROBATCH_US": "1000",
    },
    "searchable-everything": {
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "ES",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "ES",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "ES",
        "PIO_STORAGE_SOURCES_ES_TYPE": "searchable",
    },
    # models behind a SOCKET: blob daemon + http:// scheme (the HDFS/S3
    # remoteness made real — train persists and deploy loads over HTTP).
    # __BLOB_DAEMON__ is replaced with the live daemon URL by the test.
    "remote-blob-models": {
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "RB",
        "PIO_STORAGE_SOURCES_RB_TYPE": "blob",
        "PIO_STORAGE_SOURCES_RB_PATH": "__BLOB_DAEMON__",
    },
}


@pytest.mark.slow
@pytest.mark.parametrize("combo", sorted(STORAGE_COMBOS))
def test_full_quickstart_lifecycle(tmp_path, combo):
    env = _cli_env(tmp_path)
    env.update(STORAGE_COMBOS[combo])
    if "eventlog" in STORAGE_COMBOS[combo].values():
        from pio_tpu.native import NativeUnavailable

        try:
            from pio_tpu.native import event_log_lib

            event_log_lib()
        except NativeUnavailable as e:
            pytest.skip(f"native eventlog unavailable: {e}")
    procs = []
    try:
        if "__BLOB_DAEMON__" in env.values():
            # ---- pio blobserver (remote Models endpoint) ----------------
            bs_port = _free_port()
            bs = subprocess.Popen(
                [sys.executable, "-m", "pio_tpu", "blobserver",
                 "--root", str(tmp_path / "blobroot"),
                 "--ip", "127.0.0.1", "--port", str(bs_port)],
                env=env, cwd=REPO,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            procs.append(bs)
            url = f"http://127.0.0.1:{bs_port}"
            assert _wait_http(f"{url}/")["status"] == "alive"
            for k, v in list(env.items()):
                if v == "__BLOB_DAEMON__":
                    env[k] = url

        # ---- pio app new ------------------------------------------------
        out = _run(["app", "new", "quickstart"], env)
        assert out.returncode == 0, out.stderr[-1000:]
        m = re.search(r"Access key: (\S+)", out.stdout)
        assert m, out.stdout
        key = m.group(1)

        # ---- event server + HTTP ingest ---------------------------------
        es_port = _free_port()
        es = subprocess.Popen(
            [sys.executable, "-m", "pio_tpu", "eventserver",
             "--ip", "127.0.0.1", "--port", str(es_port)],
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        procs.append(es)
        assert _wait_http(f"http://127.0.0.1:{es_port}/")["status"] == "alive"

        batch = [
            {"event": "rate", "entityType": "user",
             "entityId": f"u{(i * 13) % 40}",
             "targetEntityType": "item", "targetEntityId": f"i{i % 25}",
             "properties": {"rating": float(1 + (i * 7) % 5)},
             "eventTime": f"2026-01-01T00:{i % 60:02d}:00.000Z"}
            for i in range(50)
        ]
        st, body = _post(
            f"http://127.0.0.1:{es_port}/batch/events.json?accessKey={key}",
            batch,
        )
        assert st == 200 and all(r["status"] == 201 for r in body), body
        # duplicate the batch so the export step below sees 100 events
        # (same 50 distinct user-item edges either way)
        st, _ = _post(
            f"http://127.0.0.1:{es_port}/batch/events.json?accessKey={key}",
            batch,
        )
        assert st == 200

        # ---- engine.json + pio train ------------------------------------
        variant = {
            "id": "qs1", "engineFactory": "templates.recommendation",
            "datasource": {"params": {"app_name": "quickstart",
                                      "rate_event": "rate"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 8, "num_iterations": 5, "lambda_": 0.1}}],
        }
        vpath = tmp_path / "engine.json"
        vpath.write_text(json.dumps(variant))
        out = _run(["train", "--engine-json", str(vpath)], env)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "Training completed" in out.stdout

        # ---- pio deploy + query -----------------------------------------
        qs_port = _free_port()
        qs = subprocess.Popen(
            [sys.executable, "-m", "pio_tpu", "deploy",
             "--engine-json", str(vpath),
             "--ip", "127.0.0.1", "--port", str(qs_port)],
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        procs.append(qs)
        _wait_http(f"http://127.0.0.1:{qs_port}/stats.json")

        st, body = _post(
            f"http://127.0.0.1:{qs_port}/queries.json",
            {"user": "u1", "num": 4},
        )
        assert st == 200, body
        assert len(body["itemScores"]) == 4, body
        scores = [x["score"] for x in body["itemScores"]]
        assert scores == sorted(scores, reverse=True)

        # ---- pio undeploy (graceful stop over HTTP) ---------------------
        out = _run(["undeploy", "--ip", "127.0.0.1",
                    "--port", str(qs_port)], env, timeout=60)
        assert out.returncode == 0, out.stderr[-500:]
        qs.wait(timeout=30)

        # ---- pio export round-trips the ingested events -----------------
        out_file = tmp_path / "events.jsonl"
        out = _run(["export", "--app", "quickstart",
                    "--output", str(out_file)], env)
        assert out.returncode == 0, out.stderr[-500:]
        lines = out_file.read_text().strip().splitlines()
        assert len(lines) == 100
        assert json.loads(lines[0])["event"] == "rate"

        # ---- pio status self-check --------------------------------------
        out = _run(["status"], env)
        assert out.returncode == 0, out.stderr[-500:]
        assert "sanity check passed" in out.stdout

        if combo == "remote-blob-models":
            # the trained model actually lives behind the daemon's socket
            blob_objects = tmp_path / "blobroot" / "objects"
            assert blob_objects.is_dir() and any(blob_objects.rglob("*"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
