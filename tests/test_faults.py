"""Fault-injection subsystem + crash-safety chaos suite.

Covers the failpoint registry (grammar, matching, actions, counters),
the retrying() storage wrapper, the durability knob, CRC-framed
event-log torn-tail recovery (v1 back-compat included), last-known-good
model fallback, the /faults.json endpoint, and subprocess crash-
consistency scenarios: a writer killed mid group-commit flush / mid
model persist must leave a store that reopens with every acked write.
"""

import datetime as dt
import hashlib
import json
import os
import sqlite3
import struct
import subprocess
import sys
import textwrap
import time
import urllib.request

import pytest

from pio_tpu.obs import monotonic_s

from pio_tpu import faults
from pio_tpu.faults import FaultError, FaultInjected
from pio_tpu.faults.registry import CRASH_EXIT_CODE, ENV_VAR
from pio_tpu.qos.deadline import Deadline
from pio_tpu.storage import durability
from pio_tpu.storage.base import StorageError
from pio_tpu.storage.retry import is_transient, retrying


@pytest.fixture(autouse=True)
def clean_faults(monkeypatch):
    monkeypatch.delenv(ENV_VAR, raising=False)
    faults.uninstall()
    yield
    faults.uninstall()


# ---------------------------------------------------------------- grammar
class TestSpecGrammar:
    def test_full_spec_parses(self):
        rules = faults.parse_faults(
            "eventlog.flush.*=error:0.1,storage.sqlite.commit=latency:200ms,"
            "worker.serve=crash:once"
        )
        assert [r.pattern for r in rules] == [
            "eventlog.flush.*", "storage.sqlite.commit", "worker.serve",
        ]
        assert rules[0].action == "error" and rules[0].probability == 0.1
        assert rules[1].action == "latency" and rules[1].delay_s == 0.2
        assert rules[2].action == "crash" and rules[2].once

    def test_torn_write_underscore_alias(self):
        (r,) = faults.parse_faults("eventlog.append.before_write=torn_write")
        assert r.action == "torn-write"

    def test_latency_takes_modifier_after_duration(self):
        (r,) = faults.parse_faults("p=latency:10ms:0.5")
        assert r.delay_s == 0.01 and r.probability == 0.5

    @pytest.mark.parametrize("bad", [
        "nope",                      # not point=action
        "p=explode",                 # unknown action
        "p=latency",                 # latency needs a duration
        "p=latency:soon",            # unparseable duration
        "p=error:0",                 # probability must be > 0
        "p=error:1.5",               # probability must be <= 1
        "p=error:maybe",             # neither number nor 'once'
        "p=error:0.5:once",          # too many modifiers
        "=error",                    # empty point
    ])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(FaultError):
            faults.parse_faults(bad)

    def test_fault_error_is_value_error(self):
        assert issubclass(FaultError, ValueError)


# --------------------------------------------------------------- registry
class TestRegistry:
    def test_inert_without_spec(self):
        assert faults.failpoint("anything.at.all") is None
        assert faults.trigger_counts() == {}
        assert faults.snapshot()["enabled"] is False

    def test_error_action_raises_and_counts(self):
        faults.install("a.b=error")
        with pytest.raises(FaultInjected) as ei:
            faults.failpoint("a.b")
        assert ei.value.point == "a.b" and ei.value.action == "error"
        assert faults.trigger_counts() == {("a.b", "error"): 1}

    def test_latency_action_sleeps(self):
        faults.install("a.b=latency:60ms")
        t0 = monotonic_s()
        assert faults.failpoint("a.b") is None
        assert monotonic_s() - t0 >= 0.05

    def test_once_disarms_after_first_trigger(self):
        faults.install("a.b=error:once")
        with pytest.raises(FaultInjected):
            faults.failpoint("a.b")
        assert faults.failpoint("a.b") is None  # disarmed
        snap = faults.snapshot()
        assert snap["rules"][0]["disarmed"] is True
        assert snap["rules"][0]["triggered"] == 1

    def test_glob_match_and_spec_order_wins(self):
        # the glob precedes the exact rule, so it must win for a.b
        faults.install("a.*=latency:1ms,a.b=error")
        assert faults.failpoint("a.b") is None  # latency, not error
        assert ("a.b", "latency") in faults.trigger_counts()

    def test_unmatched_point_stays_inert(self):
        faults.install("a.b=error")
        assert faults.failpoint("c.d") is None

    def test_install_from_env(self, monkeypatch):
        monkeypatch.setenv(ENV_VAR, "x.y=error")
        faults.install()
        with pytest.raises(FaultInjected):
            faults.failpoint("x.y")

    def test_reinstall_keeps_counts_uninstall_clears(self):
        faults.install("a.b=error")
        with pytest.raises(FaultInjected):
            faults.failpoint("a.b")
        faults.install("")  # disarm via empty spec
        assert faults.failpoint("a.b") is None
        assert faults.trigger_counts() == {("a.b", "error"): 1}
        faults.uninstall()
        assert faults.trigger_counts() == {}

    def test_torn_write_returns_strict_prefix(self):
        faults.install("w=torn-write")
        data = b"0123456789"
        for _ in range(20):
            torn = faults.failpoint("w", data)
            assert torn is not None and len(torn) < len(data)
            assert data.startswith(torn)

    def test_torn_write_without_data_degrades_to_error(self):
        faults.install("w=torn-write")
        with pytest.raises(FaultInjected) as ei:
            faults.failpoint("w")
        assert ei.value.action == "torn-write"

    def test_exposition_lines(self):
        faults.install("a.b=error")
        with pytest.raises(FaultInjected):
            faults.failpoint("a.b")
        lines = faults.exposition_lines()
        assert "# TYPE pio_tpu_fault_triggered_total counter" in lines
        assert (
            'pio_tpu_fault_triggered_total{point="a.b",action="error"} 1'
            in lines
        )

    def test_exposition_empty_when_never_triggered(self):
        assert faults.exposition_lines() == []


# --------------------------------------------------------------- retrying
class TestRetrying:
    def test_transient_errors_are_absorbed(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise FaultInjected("p")
            return "ok"

        assert retrying(fn, base_s=0.001, cap_s=0.002) == "ok"
        assert len(calls) == 3

    def test_non_transient_raises_immediately(self):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("broken")

        with pytest.raises(ValueError):
            retrying(fn, base_s=0.001)
        assert len(calls) == 1

    def test_exhausted_attempts_reraise_last(self):
        calls = []

        def fn():
            calls.append(1)
            raise FaultInjected("p")

        with pytest.raises(FaultInjected):
            retrying(fn, attempts=3, base_s=0.001, cap_s=0.002)
        assert len(calls) == 3

    def test_expired_deadline_stops_retrying(self):
        calls = []
        deadline = Deadline(budget_ms=0.0)

        def fn():
            calls.append(1)
            raise FaultInjected("p")

        with pytest.raises(FaultInjected):
            retrying(fn, base_s=0.001, deadline=deadline)
        assert len(calls) == 1  # no sleep for a client that gave up

    def test_is_transient_classification(self):
        assert is_transient(FaultInjected("p"))
        assert is_transient(sqlite3.OperationalError("database is locked"))
        assert is_transient(sqlite3.OperationalError("database is busy"))
        assert not is_transient(sqlite3.OperationalError("syntax error"))
        assert is_transient(StorageError("blob server unreachable: refused"))
        assert not is_transient(StorageError("access denied"))
        assert not is_transient(ValueError("nope"))


# ------------------------------------------------------------- durability
class TestDurability:
    def test_default_mode_is_batch(self, monkeypatch):
        monkeypatch.delenv(durability.ENV_VAR, raising=False)
        assert durability.mode() == "batch"

    def test_unknown_mode_is_loud(self, monkeypatch):
        monkeypatch.setenv(durability.ENV_VAR, "yolo")
        with pytest.raises(ValueError):
            durability.mode()

    def _count_fsyncs(self, monkeypatch):
        count = {"n": 0}
        real = os.fsync

        def counting(fd):
            count["n"] += 1
            return real(fd)

        monkeypatch.setattr(os, "fsync", counting)
        return count

    def test_fsync_fileobj_by_mode(self, monkeypatch, tmp_path):
        count = self._count_fsyncs(monkeypatch)
        p = tmp_path / "f"
        monkeypatch.setenv(durability.ENV_VAR, "commit")
        with open(p, "wb") as f:
            f.write(b"x")
            durability.fsync_fileobj(f)
        assert count["n"] == 1
        monkeypatch.setenv(durability.ENV_VAR, "os")
        with open(p, "wb") as f:
            f.write(b"x")
            durability.fsync_fileobj(f)
        assert count["n"] == 1  # unchanged

    def test_replace_durable_fsyncs_parent_dir(self, monkeypatch, tmp_path):
        count = self._count_fsyncs(monkeypatch)
        tmp, dst = tmp_path / "a.tmp", tmp_path / "a"
        tmp.write_bytes(b"payload")
        monkeypatch.setenv(durability.ENV_VAR, "batch")
        durability.replace_durable(str(tmp), str(dst))
        assert dst.read_bytes() == b"payload" and not tmp.exists()
        assert count["n"] == 1  # the directory fd
        tmp.write_bytes(b"payload2")
        monkeypatch.setenv(durability.ENV_VAR, "os")
        durability.replace_durable(str(tmp), str(dst))
        assert dst.read_bytes() == b"payload2"
        assert count["n"] == 1  # no dir fsync under os

    def test_interval_syncer_modes(self, monkeypatch):
        s = durability.IntervalSyncer(interval_s=60.0)
        monkeypatch.setenv(durability.ENV_VAR, "commit")
        assert s.due("k") and s.due("k")
        monkeypatch.setenv(durability.ENV_VAR, "os")
        assert not s.due("k")
        monkeypatch.setenv(durability.ENV_VAR, "batch")
        assert s.due("k")  # never synced yet
        s.mark("k")
        assert not s.due("k")  # within the interval
        assert s.due("other")  # per-key schedule

    def test_sqlite_pragmas(self, tmp_path, monkeypatch):
        from pio_tpu.storage.sqlite import SQLiteClient

        monkeypatch.delenv(durability.ENV_VAR, raising=False)
        conn = SQLiteClient(str(tmp_path / "t.db")).conn()
        assert conn.execute("PRAGMA journal_mode").fetchone()[0] == "wal"
        assert conn.execute("PRAGMA busy_timeout").fetchone()[0] == 30000
        # batch (default) → synchronous=NORMAL (1)
        assert conn.execute("PRAGMA synchronous").fetchone()[0] == 1

    def test_sqlite_synchronous_tracks_mode(self, tmp_path, monkeypatch):
        from pio_tpu.storage.sqlite import SQLiteClient

        monkeypatch.setenv(durability.ENV_VAR, "commit")
        conn = SQLiteClient(str(tmp_path / "full.db")).conn()
        assert conn.execute("PRAGMA synchronous").fetchone()[0] == 2  # FULL
        monkeypatch.setenv(durability.ENV_VAR, "os")
        conn = SQLiteClient(str(tmp_path / "off.db")).conn()
        assert conn.execute("PRAGMA synchronous").fetchone()[0] == 0  # OFF


# ------------------------------------------------- eventlog CRC + failpoints
try:
    from pio_tpu.native import event_log_lib

    event_log_lib()
    from pio_tpu.storage.eventlog import EventLogEvents, _encode_record

    _HAVE_NATIVE = True
except Exception:  # pragma: no cover - no toolchain
    _HAVE_NATIVE = False

needs_native = pytest.mark.skipif(
    not _HAVE_NATIVE, reason="native eventlog unavailable"
)


def _T(h=1):
    return dt.datetime(2026, 1, 1, h, tzinfo=dt.timezone.utc)


def _ev(i=0):
    from pio_tpu.data.event import Event

    return Event(event="rate", entity_type="user", entity_id=f"u{i}",
                 properties={"rating": float(i)}, event_time=_T())


@needs_native
class TestEventlogFaults:
    def test_injected_torn_write_heals_on_reopen(self, tmp_path):
        root = str(tmp_path / "log")
        b = EventLogEvents(root)
        b.insert(_ev(0), 1)
        faults.install("eventlog.append.before_write=torn-write")
        with pytest.raises(StorageError, match="injected torn write"):
            b.insert(_ev(1), 1)
        faults.uninstall()
        b2 = EventLogEvents(root)  # fresh handle: repair on first append
        assert b2.count(1) == 1  # torn tail tolerated by the scan
        b2.insert(_ev(2), 1)  # repair truncates, then appends cleanly
        assert b2.count(1) == 2

    def test_flush_failpoint_fails_insert(self, tmp_path):
        b = EventLogEvents(str(tmp_path / "log"))
        faults.install("eventlog.flush.before_write=error")
        with pytest.raises(FaultInjected):
            b.insert(_ev(0), 1)
        # triggered in the batched flush AND the solo retry
        assert faults.trigger_counts()[
            ("eventlog.flush.before_write", "error")
        ] >= 2
        faults.uninstall()
        b.insert(_ev(1), 1)
        assert b.count(1) == 1

    def test_scan_failpoint(self, tmp_path):
        b = EventLogEvents(str(tmp_path / "log"))
        b.insert(_ev(0), 1)
        faults.install("eventlog.scan=error")
        with pytest.raises(FaultInjected):
            b.find(1)

    def test_crc_catches_tail_corruption_as_torn(self, tmp_path):
        root = str(tmp_path / "log")
        b = EventLogEvents(root)
        for i in range(3):
            b.insert(_ev(i), 1)
        path = os.path.join(root, "app_1.pel")
        with open(path, "r+b") as f:
            f.seek(-1, os.SEEK_END)  # last CRC byte of the final record
            last = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([last[0] ^ 0xFF]))
        b2 = EventLogEvents(root)
        # CRC failure at exact EOF = torn tail → dropped, not fatal
        assert b2.count(1) == 2

    def test_crc_catches_mid_file_corruption_as_corrupt(self, tmp_path):
        root = str(tmp_path / "log")
        b = EventLogEvents(root)
        for i in range(3):
            b.insert(_ev(i), 1)
        path = os.path.join(root, "app_1.pel")
        with open(path, "r+b") as f:
            f.seek(8 + 4 + 2)  # inside the FIRST record's payload
            byte = f.read(1)
            f.seek(8 + 4 + 2)
            f.write(bytes([byte[0] ^ 0xFF]))
        b2 = EventLogEvents(root)
        with pytest.raises(StorageError, match="corrupt"):
            b2.count(1)

    def test_v1_file_reads_and_upgrades_on_append(self, tmp_path):
        root = str(tmp_path / "log")
        os.makedirs(root)
        # hand-craft a v1 file: PEL1 magic + unchecksummed framing
        rec_v2 = _encode_record(0, 1000, 2000, [
            b"E1", b"rate", b"user", b"u0", b"", b"", b"", b"[]", b"{}",
        ])
        payload = rec_v2[4:-4]  # strip length prefix + CRC trailer
        path = os.path.join(root, "app_1.pel")
        with open(path, "wb") as f:
            f.write(b"PEL1\0\0\0\0")
            f.write(struct.pack("<I", len(payload)) + payload)
        b = EventLogEvents(root)
        assert b.count(1) == 1  # v1 still readable
        b.insert(_ev(1), 1)  # first append upgrades the file in place
        assert b.count(1) == 2
        with open(path, "rb") as f:
            assert f.read(4) == b"PEL2"
        # upgraded records carry CRCs: whole-file parse must still be clean
        assert len(EventLogEvents(root).find(1)) == 2


# -------------------------------------------------- last-known-good models
@pytest.fixture()
def mem_storage(tmp_home, monkeypatch):
    from pio_tpu.storage import Storage

    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "MEM")
    Storage.reset()
    yield
    Storage.reset()


class _Engine:
    algorithm_class_map: dict = {}


class _Params:
    algorithm_params_list = [("algo", None)]


def _variant():
    from pio_tpu.workflow.engine_json import EngineVariant

    return EngineVariant(
        engine_id="eng", engine_version="1", engine_factory="f",
        variant={}, path="eng",
    )


class TestModelFallback:
    def _persist(self, iid, payload, start_h, manifest=True):
        from pio_tpu.storage import EngineInstance, Model, RunStatus, Storage
        from pio_tpu.workflow.core_workflow import (
            MANIFEST_SUFFIX, serialize_models,
        )

        t = _T(start_h)
        Storage.get_meta_data_engine_instances().insert(EngineInstance(
            id=iid, status=RunStatus.COMPLETED, start_time=t, end_time=t,
            engine_id="eng", engine_version="1", engine_variant="eng",
            engine_factory="f",
        ))
        blob = serialize_models([payload])
        ms = Storage.get_model_data_models()
        ms.insert(Model(id=iid, models=blob))
        if manifest:
            ms.insert(Model(id=iid + MANIFEST_SUFFIX, models=json.dumps({
                "sha256": hashlib.sha256(blob).hexdigest(),
                "size": len(blob),
            }).encode()))

    def _corrupt(self, iid):
        from pio_tpu.storage import Model, Storage

        Storage.get_model_data_models().insert(
            Model(id=iid, models=b"\x80garbage-not-a-pickle")
        )

    def test_verified_load(self, mem_storage):
        from pio_tpu.workflow.core_workflow import load_models_for_instance

        self._persist("inst-1", "model-1", start_h=1)
        models = load_models_for_instance(
            "inst-1", _Engine(), _Params(), None, variant=_variant()
        )
        assert models == ["model-1"]

    def test_missing_manifest_loads_unverified(self, mem_storage):
        from pio_tpu.workflow.core_workflow import load_models_for_instance

        self._persist("inst-1", "model-1", start_h=1, manifest=False)
        assert load_models_for_instance(
            "inst-1", _Engine(), _Params(), None
        ) == ["model-1"]

    def test_corrupt_blob_falls_back_to_last_known_good(self, mem_storage):
        from pio_tpu.workflow.core_workflow import (
            _MODEL_FALLBACK, load_models_for_instance,
        )

        self._persist("inst-old", "model-old", start_h=1)
        self._persist("inst-new", "model-new", start_h=2)
        self._corrupt("inst-new")  # checksum now fails
        before = _MODEL_FALLBACK.value()
        models = load_models_for_instance(
            "inst-new", _Engine(), _Params(), None, variant=_variant()
        )
        assert models == ["model-old"]
        assert _MODEL_FALLBACK.value() == before + 1

    def test_corrupt_blob_without_manifest_still_falls_back(
        self, mem_storage
    ):
        # no manifest → verification skipped, but the unpickle failure
        # itself must trigger the same fallback
        from pio_tpu.workflow.core_workflow import load_models_for_instance

        self._persist("inst-old", "model-old", start_h=1)
        self._persist("inst-new", "model-new", start_h=2, manifest=False)
        self._corrupt("inst-new")
        assert load_models_for_instance(
            "inst-new", _Engine(), _Params(), None, variant=_variant()
        ) == ["model-old"]

    def test_corrupt_blob_without_variant_raises(self, mem_storage):
        from pio_tpu.workflow.core_workflow import load_models_for_instance

        self._persist("inst-1", "model-1", start_h=1)
        self._corrupt("inst-1")
        with pytest.raises(RuntimeError, match="checksum|deserialize"):
            load_models_for_instance("inst-1", _Engine(), _Params(), None)

    def test_no_intact_candidate_reraises_primary(self, mem_storage):
        from pio_tpu.workflow.core_workflow import load_models_for_instance

        self._persist("inst-1", "model-1", start_h=1)
        self._corrupt("inst-1")
        with pytest.raises(RuntimeError):
            load_models_for_instance(
                "inst-1", _Engine(), _Params(), None, variant=_variant()
            )

    def test_run_train_writes_manifest(self, mem_storage):
        # the real persist path must produce a blob the verifier accepts
        from pio_tpu.controller import ComputeContext
        from pio_tpu.storage import Storage
        from pio_tpu.workflow import build_engine, run_train, variant_from_dict
        from pio_tpu.workflow.core_workflow import (
            MANIFEST_SUFFIX, _verified_blob_models,
        )
        from tests.fixtures import fixture_engine  # noqa: F401  (registers)
        from tests.test_controller import variant

        v = variant_from_dict(
            variant(algos=[{"name": "algo", "params": {"id": 1, "mult": 4}}])
        )
        engine, ep = build_engine(v)
        iid = run_train(engine, ep, v, ctx=ComputeContext.local())
        ms = Storage.get_model_data_models()
        assert ms.get(iid + MANIFEST_SUFFIX) is not None
        # round-trips through the checksum verifier
        assert _verified_blob_models(ms, iid)


# ------------------------------------------------------ /faults.json + obs
class TestFaultsEndpoint:
    def test_faults_json_and_metrics(self, mem_storage):
        from pio_tpu.server import create_event_server

        server = create_event_server(host="127.0.0.1", port=0).start()
        try:
            base = f"http://127.0.0.1:{server.port}"

            def get(path):
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    return r.read().decode()

            body = json.loads(get("/faults.json"))
            assert body["enabled"] is False and body["rules"] == []
            faults.install("p.q=latency:1ms")
            faults.failpoint("p.q")
            body = json.loads(get("/faults.json"))
            assert body["enabled"] is True
            assert body["spec"] == "p.q=latency:1ms"
            assert body["triggered"] == [
                {"point": "p.q", "action": "latency", "count": 1}
            ]
            metrics = get("/metrics")
            assert (
                'pio_tpu_fault_triggered_total{point="p.q",'
                'action="latency"} 1' in metrics
            )
        finally:
            server.stop()


# --------------------------------------------------- crash consistency
_CRASH_WRITER = textwrap.dedent("""
    import datetime as dt
    import os
    import sys

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PIO_TPU_DURABILITY"] = "commit"  # acked == on disk
    root, ackfile = sys.argv[1], sys.argv[2]

    from pio_tpu.data.event import Event
    from pio_tpu.storage.eventlog import EventLogEvents

    b = EventLogEvents(root)
    t = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    ack = open(ackfile, "w")
    for i in range(5):
        eid = b.insert(
            Event(event="e", entity_type="u", entity_id=f"u{i}",
                  event_time=t),
            1,
        )
        # the ack protocol: an id reaches this file only AFTER insert
        # returned (the 201 analog), fsynced so the parent can trust it
        ack.write(eid + "\\n")
        ack.flush()
        os.fsync(ack.fileno())

    from pio_tpu import faults
    faults.install("groupcommit.flush.eventlog=crash:once")
    b.insert(
        Event(event="e", entity_type="u", entity_id="boom", event_time=t),
        1,
    )
    print("UNREACHABLE")  # the crash failpoint must have fired
""")

_PERSIST_WRITER = textwrap.dedent("""
    import os
    import sys

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PIO_TPU_DURABILITY"] = "commit"
    root = sys.argv[1]

    from pio_tpu.storage.localfs import LocalFSModels
    from pio_tpu.storage.records import Model

    s = LocalFSModels(root)
    s.insert(Model("good", b"payload-1"))

    from pio_tpu import faults
    faults.install("storage.localfs.persist=crash:once")
    s.insert(Model("doomed", b"payload-2"))
    print("UNREACHABLE")
""")


def _run_writer(script, *argv):
    env = dict(os.environ)
    env.pop(ENV_VAR, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-c", script, *argv],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


@needs_native
class TestCrashConsistency:
    def test_sigkill_mid_group_commit_flush(self, tmp_path):
        """Writer dies (os._exit, no unwinding) inside the group-commit
        leader, mid-flush. On reopen: the log scans clean and every
        acked event is present — an ack under durability=commit is a
        promise that survives the crash."""
        root = str(tmp_path / "log")
        ackfile = str(tmp_path / "acks")
        proc = _run_writer(_CRASH_WRITER, root, ackfile)
        assert proc.returncode == CRASH_EXIT_CODE, proc.stderr
        assert "injected crash" in proc.stderr
        assert "UNREACHABLE" not in proc.stdout
        with open(ackfile) as f:
            acked = [line.strip() for line in f if line.strip()]
        assert len(acked) == 5
        b = EventLogEvents(root)  # reopen as a recovering server would
        events = b.find(1)  # scan must succeed (torn tail tolerated)
        got = {e.event_id for e in events}
        assert set(acked) <= got, f"lost acked events: {set(acked) - got}"
        assert "boom" not in {e.entity_id for e in events}
        # and the log accepts new writes after recovery
        b.insert(_ev(9), 1)
        assert b.count(1) == len(events) + 1

    def test_crash_mid_model_persist(self, tmp_path):
        """Writer dies between writing the temp file and the durable
        rename: the previous model must be intact and the half-written
        one invisible (temp never published)."""
        from pio_tpu.storage.localfs import LocalFSModels

        root = str(tmp_path / "models")
        proc = _run_writer(_PERSIST_WRITER, root)
        assert proc.returncode == CRASH_EXIT_CODE, proc.stderr
        assert "injected crash" in proc.stderr
        s = LocalFSModels(root)
        good = s.get("good")
        assert good is not None and good.models == b"payload-1"
        assert s.get("doomed") is None  # tmp written, never published
        assert os.path.exists(os.path.join(root, "doomed.bin.tmp"))


# ----------------------------------------------------- worker failpoint
def test_worker_serve_failpoint_is_wired():
    # the serve loop calls failpoint("worker.serve") every iteration; a
    # full pool boot is covered by test_worker_pool — here just prove the
    # point name is armed/counted through the registry like any other
    faults.install("worker.serve=latency:1ms")
    assert faults.failpoint("worker.serve") is None
    assert ("worker.serve", "latency") in faults.trigger_counts()
