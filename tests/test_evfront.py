"""HTTP/1.1 edge cases over BOTH fronts (ISSUE 13).

One parametrized suite runs the same raw-socket scenarios against the
threaded front (`JsonHTTPServer`) and the event-loop front
(`EvLoopHTTPServer`): pipelined bursts, byte-by-byte partial arrival,
oversized-body 413, idle-timeout close, malformed request line 400, and
keep-alive vs ``Connection: close`` semantics. Plus the evfront-specific
regressions: per-connection write buffers (two pipelined responses must
neither interleave nor alias) and the packed int8 zero-copy ingest
(exact parity with the JSON path, and raw-frame lane submit).
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers engine factories)
from pio_tpu.controller import ComputeContext
from pio_tpu.server.batchlane import (
    BatchLaneSegment,
    LaneClient,
    LaneDrainer,
    PACKED_MAGIC,
    pack_query_i8,
    packed_frame_ok,
)
from pio_tpu.server.evfront import EvLoopHTTPServer
from pio_tpu.server.http import (
    JsonHTTPServer,
    PACKED_QUERY_CONTENT_TYPE,
    Request,
    Router,
)
from pio_tpu.server.query_server import QueryServerService
from pio_tpu.storage import Storage

FRONTS = ("threaded", "evloop")


def _make_front(front: str, router: Router):
    if front == "evloop":
        return EvLoopHTTPServer(
            router, host="127.0.0.1", port=0, ssl_context=None
        ).start()
    return JsonHTTPServer(
        router, host="127.0.0.1", port=0, ssl_context=None
    ).start()


def _echo_router() -> Router:
    r = Router()

    def echo(req: Request):
        return 200, {"got": req.body}

    r.add("POST", "/echo", echo)
    r.add("GET", "/ping", lambda req: (200, {"pong": True}))
    return r


@pytest.fixture(params=FRONTS)
def front(request):
    srv = _make_front(request.param, _echo_router())
    yield request.param, srv
    srv.stop()


def _drain(sock: socket.socket, timeout: float = 3.0) -> bytes:
    """Read until the peer closes (or the timeout elapses)."""
    sock.settimeout(timeout)
    out = b""
    try:
        while True:
            got = sock.recv(65536)
            if not got:
                break
            out += got
    except socket.timeout:
        pass
    return out


def _request(port: int, payload: bytes, timeout: float = 3.0) -> bytes:
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    try:
        s.sendall(payload)
        return _drain(s, timeout)
    finally:
        s.close()


def _post(path: str, body: bytes, ctype: str = "application/json",
          close: bool = False) -> bytes:
    conn = b"Connection: close\r\n" if close else b""
    return (
        b"POST %s HTTP/1.1\r\nHost: t\r\nContent-Type: %s\r\n"
        b"Content-Length: %d\r\n%s\r\n%s"
        % (path.encode(), ctype.encode(), len(body), conn, body)
    )


def _split_responses(blob: bytes):
    """Parse a byte stream of HTTP/1.1 responses into
    ``[(status, headers, body)]`` using Content-Length framing — any
    interleaving or mis-framing breaks the parse or the count."""
    out = []
    rest = blob
    while rest:
        head, sep, rest = rest.partition(b"\r\n\r\n")
        assert sep, f"unterminated head: {head[:120]!r}"
        lines = head.split(b"\r\n")
        status = int(lines[0].split()[1])
        headers = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(b":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get(b"content-length", b"0"))
        body, rest = rest[:n], rest[n:]
        assert len(body) == n, "truncated body"
        out.append((status, headers, body))
    return out


class TestFrontEdgeCases:
    def test_basic_roundtrip(self, front):
        _, srv = front
        resp = _request(
            srv.port, b"GET /ping HTTP/1.1\r\nHost: t\r\n"
            b"Connection: close\r\n\r\n",
        )
        [(status, headers, body)] = _split_responses(resp)
        assert status == 200
        assert json.loads(body) == {"pong": True}
        assert headers[b"connection"] == b"close"

    def test_pipelined_burst_in_order(self, front):
        _, srv = front
        bodies = [json.dumps({"i": i}).encode() for i in range(8)]
        blob = b"".join(_post("/echo", b) for b in bodies[:-1])
        blob += _post("/echo", bodies[-1], close=True)
        resp = _request(srv.port, blob)
        got = _split_responses(resp)
        assert [st for st, _, _ in got] == [200] * 8
        for i, (_, _, body) in enumerate(got):
            assert json.loads(body) == {"got": {"i": i}}

    def test_pipelined_responses_do_not_interleave_or_alias(self, front):
        # per-connection write buffers (satellite 2): two pipelined
        # responses of very different sizes must come back exactly
        # framed, in order, each with its own payload bytes
        _, srv = front
        big = json.dumps({"blob": "x" * 30000}).encode()
        small = json.dumps({"tiny": 1}).encode()
        blob = _post("/echo", big) + _post("/echo", small, close=True)
        got = _split_responses(_request(srv.port, blob))
        assert len(got) == 2
        assert json.loads(got[0][2]) == {"got": {"blob": "x" * 30000}}
        assert json.loads(got[1][2]) == {"got": {"tiny": 1}}

    def test_byte_by_byte_arrival(self, front):
        _, srv = front
        body = json.dumps({"slow": True}).encode()
        payload = _post("/echo", body, close=True)
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            for i in range(len(payload)):
                s.sendall(payload[i:i + 1])
            [(status, _, got)] = _split_responses(_drain(s))
        finally:
            s.close()
        assert status == 200
        assert json.loads(got) == {"got": {"slow": True}}

    def test_oversized_body_413(self, front):
        # a structured Content-Length over the JSON cap is refused from
        # the headers alone — no body needs to be sent (or read)
        _, srv = front
        resp = _request(
            srv.port,
            b"POST /echo HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: 104857600\r\n\r\n",
        )
        assert resp.split(b"\r\n", 1)[0] == b"HTTP/1.1 413 Content Too Large"

    def test_malformed_request_line_400(self, front):
        _, srv = front
        resp = _request(srv.port, b"NONSENSE\r\n\r\n")
        assert resp.split(b"\r\n", 1)[0] == b"HTTP/1.1 400 Bad Request"

    def test_keep_alive_sequential_then_close(self, front):
        _, srv = front
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            s.sendall(_post("/echo", json.dumps({"a": 1}).encode()))
            # wait for the first full response before the second request
            s.settimeout(3)
            first = b""
            while b"\r\n\r\n" not in first or not first.endswith(b"}"):
                got = s.recv(65536)
                assert got, "server closed a keep-alive connection"
                first += got
            [(st1, h1, b1)] = _split_responses(first)
            assert st1 == 200 and json.loads(b1) == {"got": {"a": 1}}
            assert h1.get(b"connection") != b"close"
            s.sendall(_post("/echo", json.dumps({"b": 2}).encode(),
                            close=True))
            [(st2, h2, b2)] = _split_responses(_drain(s))
            assert st2 == 200 and json.loads(b2) == {"got": {"b": 2}}
            assert h2[b"connection"] == b"close"
        finally:
            s.close()

    def test_idle_timeout_closes_connection(self, monkeypatch, front):
        name, srv = front
        srv.stop()
        monkeypatch.setenv("PIO_TPU_HTTP_IDLE_TIMEOUT_S", "0.5")
        srv2 = _make_front(name, _echo_router())
        try:
            s = socket.create_connection(
                ("127.0.0.1", srv2.port), timeout=5
            )
            try:
                # send nothing: the idle/slowloris guard must close
                s.settimeout(5)
                assert s.recv(1) == b""  # orderly close, not a hang
            finally:
                s.close()
        finally:
            srv2.stop()


class TestEvloopSpecifics:
    def test_tls_refused(self, monkeypatch):
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        with pytest.raises(ValueError, match="TLS"):
            EvLoopHTTPServer(_echo_router(), ssl_context=ctx)

    def test_large_uploads_refused(self):
        with pytest.raises(ValueError, match="threaded"):
            EvLoopHTTPServer(
                _echo_router(), ssl_context=None, large_uploads=True
            )

    def test_max_pipeline_knob_batches_but_serves_all(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_HTTP_MAX_PIPELINE", "2")
        srv = _make_front("evloop", _echo_router())
        try:
            bodies = [json.dumps({"i": i}).encode() for i in range(7)]
            blob = b"".join(_post("/echo", b) for b in bodies[:-1])
            blob += _post("/echo", bodies[-1], close=True)
            got = _split_responses(_request(srv.port, blob))
            assert [json.loads(b)["got"]["i"] for _, _, b in got] \
                == list(range(7))
        finally:
            srv.stop()

    def test_connection_metrics_registered(self):
        from pio_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        srv = EvLoopHTTPServer(
            _echo_router(), host="127.0.0.1", ssl_context=None,
            registry=reg,
        ).start()
        try:
            blob = _post("/echo", b'{"a":1}') \
                + _post("/echo", b'{"b":2}', close=True)
            _split_responses(_request(srv.port, blob))
            lines = "\n".join(reg.render())
            assert "pio_tpu_http_connections_active" in lines
            assert "pio_tpu_http_pipelined_total" in lines
        finally:
            srv.stop()


# ------------------------------------------------------ packed int8 wire
class TestPackedFrameCheck:
    def test_structural_check(self):
        frame = pack_query_i8(np.array([1, -2, 3], np.int8))
        assert packed_frame_ok(frame)
        assert packed_frame_ok(memoryview(frame))
        assert not packed_frame_ok(frame[:-1])  # truncated
        assert not packed_frame_ok(b"\x01" + frame[1:])  # bad magic
        assert not packed_frame_ok(b"")

    def test_submit_packed_returns_raw_json_bytes(self, tmp_path):
        seg = BatchLaneSegment.create(str(tmp_path / "lane.shm"), 2)
        doorbell = threading.Event()
        resp = [threading.Event() for _ in range(2)]
        seen = []

        def dispatch(bodies):
            seen.extend(bodies)
            return [{"n": int(len(b))} for b in bodies]

        drainer = LaneDrainer(seg, dispatch, doorbell, resp,
                              poll_s=0.01).start()
        try:
            client = LaneClient(seg, 1, doorbell, resp[1], timeout_s=5.0)
            frame = pack_query_i8(np.array([5, -7, 9, 11], np.int8))
            out = client.submit_packed(frame)
            # raw JSON bytes, NOT a decoded dict: the front writes them
            # straight to the socket
            assert isinstance(out, bytes)
            assert json.loads(out.decode()) == {"n": 4}
            # and a memoryview frame (the evfront hand-off) works too
            out2 = client.submit_packed(memoryview(frame))
            assert json.loads(out2.decode()) == {"n": 4}
        finally:
            drainer.stop()


@pytest.fixture
def isolated_storage(tmp_home):
    Storage.reset()
    yield
    Storage.reset()


def _resident_service(monkeypatch):
    # mirrors tests/test_device_resident.py's harness: an int8 resident
    # classification deployment whose lane pack/unpack is exact
    import datetime as dt

    from pio_tpu.data import Event
    from pio_tpu.storage import App
    from pio_tpu.workflow import build_engine, run_train, variant_from_dict

    monkeypatch.setenv("PIO_TPU_DEVICE_RESIDENT", "1")
    monkeypatch.setenv("PIO_TPU_BATCH_BUCKETS", "1,2,4")
    monkeypatch.setenv("PIO_TPU_BUCKET_WARMUP", "1")
    app_id = Storage.get_meta_data_apps().insert(App(0, "evfront-test"))
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    rng = np.random.default_rng(7)
    n = 0
    for plan, hot in (("basic", 0), ("premium", 1), ("pro", 2)):
        for _ in range(8):
            attrs = rng.integers(0, 3, size=3)
            attrs[hot] += 6
            props = {f"attr{j}": int(attrs[j]) for j in range(3)}
            props["plan"] = plan
            le.insert(
                Event("$set", "user", f"u{n}", properties=props,
                      event_time=t0 + dt.timedelta(minutes=n)),
                app_id,
            )
            n += 1
    variant = variant_from_dict({
        "id": "evfront-e2e",
        "engineFactory": "templates.classification",
        "datasource": {"params": {"app_name": "evfront-test"}},
        "algorithms": [{"name": "logreg", "params": {}}],
    })
    engine, ep = build_engine(variant)
    ctx = ComputeContext.create(seed=0)
    run_train(engine, ep, variant, ctx=ctx)
    return QueryServerService(variant, ctx=ctx)


class TestPackedHTTPPath:
    @pytest.mark.parametrize("front_name", FRONTS)
    def test_packed_request_parity_vs_json(
        self, monkeypatch, isolated_storage, front_name
    ):
        svc = _resident_service(monkeypatch)
        srv = _make_front(front_name, svc.router)
        try:
            for attrs, want in (
                ((9.0, 1.0, 1.0), "basic"),
                ((1.0, 9.0, 1.0), "premium"),
                ((1.0, 1.0, 9.0), "pro"),
            ):
                body = {"attrs": list(attrs)}
                raw = json.dumps(body).encode()
                [(st, _, out_json)] = _split_responses(_request(
                    srv.port, _post("/queries.json", raw, close=True),
                ))
                assert st == 200
                frame = svc.pack_query_body(body)
                assert frame is not None and frame[:4] == PACKED_MAGIC
                [(st2, _, out_packed)] = _split_responses(_request(
                    srv.port,
                    _post("/queries.json", frame,
                          ctype=PACKED_QUERY_CONTENT_TYPE, close=True),
                ))
                assert st2 == 200
                # exact parity: the packed wire answers byte-identically
                # to the JSON path (both decode to the same label too)
                assert json.loads(out_packed) == json.loads(out_json)
                assert json.loads(out_packed)["label"] == want
            # no lane here, so every packed request took the local
            # fallback; none were invalid
            assert svc._parse_fastpath_total.value("local") == 3.0
            assert svc._parse_fastpath_total.value("invalid") == 0.0
        finally:
            srv.stop()

    @pytest.mark.parametrize("front_name", FRONTS)
    def test_malformed_packed_frame_400(
        self, monkeypatch, isolated_storage, front_name
    ):
        svc = _resident_service(monkeypatch)
        srv = _make_front(front_name, svc.router)
        try:
            [(st, _, body)] = _split_responses(_request(
                srv.port,
                _post("/queries.json", b"\x00Q8\x01\xff\xff",
                      ctype=PACKED_QUERY_CONTENT_TYPE, close=True),
            ))
            assert st == 400
            assert "packed" in json.loads(body)["message"]
            assert svc._parse_fastpath_total.value("invalid") == 1.0
        finally:
            srv.stop()
