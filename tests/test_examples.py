"""Every examples/<t>/engine.json must bind: factory resolves, params
validate (wrong names fail at build time, which is the point)."""

import json
import os

import pytest

import pio_tpu.templates  # noqa: F401
from pio_tpu.workflow import build_engine, variant_from_dict

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)


@pytest.mark.parametrize(
    "name", sorted(os.listdir(EXAMPLES)) if os.path.isdir(EXAMPLES) else []
)
def test_example_engine_json_builds(name):
    if not os.path.isdir(os.path.join(EXAMPLES, name)):
        pytest.skip("not a template dir (e.g. README.md)")
    path = os.path.join(EXAMPLES, name, "engine.json")
    assert os.path.isfile(path), f"{name}/ has no engine.json"
    variant = variant_from_dict(json.load(open(path)))
    engine, ep = build_engine(variant)
    assert ep.algorithm_params_list
