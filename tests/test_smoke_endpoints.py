"""Tier-1 wrapper around scripts/smoke.sh: boots a real query server
over a freshly trained engine and curls every operational endpoint
(/healthz, /readyz, /logs.json, /slo.json, /traces.json, /stats.json,
/metrics) from outside the process — the one test that exercises the
full probe/log/SLO plane the way a load balancer and scrape job would.

The script is also runnable by hand (`bash scripts/smoke.sh`) against a
checkout; keeping it shell means operators can lift the curl commands
straight from it.
"""

import pathlib
import shutil
import subprocess

import pytest

SCRIPT = pathlib.Path(__file__).resolve().parent.parent / "scripts" / "smoke.sh"


@pytest.mark.skipif(shutil.which("bash") is None, reason="needs bash")
@pytest.mark.skipif(shutil.which("curl") is None, reason="needs curl")
def test_smoke_script_passes():
    proc = subprocess.run(
        ["bash", str(SCRIPT)],
        capture_output=True,
        text=True,
        timeout=280,
    )
    assert proc.returncode == 0, (
        f"smoke.sh failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}"
    )
    assert "smoke OK" in proc.stdout
