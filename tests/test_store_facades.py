"""Store facades (pio_tpu/data/store.py) + server TLS + shell wiring.

Reference: ``data/store/{PEventStore,LEventStore}.scala`` facades,
``common/SSLConfiguration.scala``, ``bin/pio-shell`` (SURVEY.md §2.2,
§2.4, §2.5 — paths UNVERIFIED, reference mount was empty).
"""

import datetime as dt
import json
import ssl
import subprocess
import sys
import urllib.request

import pytest

from pio_tpu.data import Event, LEventStore, PEventStore
from pio_tpu.storage import App, Channel, Storage


@pytest.fixture(autouse=True)
def mem_storage(tmp_home, monkeypatch):
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "MEM")
    Storage.reset()
    yield
    Storage.reset()


def T(h):
    return dt.datetime(2026, 3, 1, h, tzinfo=dt.timezone.utc)


@pytest.fixture()
def seeded_app():
    apps = Storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="shop"))
    ch_id = Storage.get_meta_data_channels().insert(
        Channel(id=0, name="mobile", app_id=app_id)
    )
    le = Storage.get_levents()
    for i in range(5):
        le.insert(
            Event(event="rate", entity_type="user", entity_id=f"u{i % 2}",
                  target_entity_type="item", target_entity_id=f"i{i}",
                  properties={"rating": float(i)}, event_time=T(i + 1)),
            app_id,
        )
    le.insert(
        Event(event="view", entity_type="user", entity_id="u0",
              event_time=T(9)),
        app_id, channel_id=ch_id,
    )
    le.insert(
        Event(event="$set", entity_type="item", entity_id="i0",
              properties={"category": "book"}, event_time=T(1)),
        app_id,
    )
    return app_id, ch_id


class TestFacades:
    def test_pevent_find_frame_by_app_name(self, seeded_app):
        frame = PEventStore.find("shop", event_names=["rate"])
        assert len(frame.event) == 5
        assert set(frame.entity_id) == {"u0", "u1"}

    def test_channel_name_resolution(self, seeded_app):
        assert [e.event for e in
                PEventStore.find_events("shop", channel_name="mobile")] == [
                    "view"]
        with pytest.raises(ValueError, match="channel"):
            PEventStore.find("shop", channel_name="nope")
        with pytest.raises(ValueError, match="app"):
            PEventStore.find("ghost")

    def test_aggregate_properties(self, seeded_app):
        props = PEventStore.aggregate_properties("shop", "item")
        assert props["i0"].get("category") == "book"

    def test_levent_find_newest_first(self, seeded_app):
        evs = LEventStore.find("shop", event_names=["rate"], limit=2)
        assert [e.target_entity_id for e in evs] == ["i4", "i3"]

    def test_find_by_entity(self, seeded_app):
        evs = LEventStore.find_by_entity("shop", "user", "u0",
                                         event_names=["rate"])
        assert [e.target_entity_id for e in evs] == ["i4", "i2", "i0"]


class TestServerTLS:
    def test_https_event_server(self, tmp_path, seeded_app, monkeypatch):
        # self-signed cert via the stdlib-adjacent openssl binary
        cert = tmp_path / "cert.pem"
        key = tmp_path / "key.pem"
        proc = subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=localhost"],
            capture_output=True,
        )
        if proc.returncode != 0:
            pytest.skip("openssl unavailable to mint a test cert")
        monkeypatch.setenv("PIO_TPU_SSL_CERTFILE", str(cert))
        monkeypatch.setenv("PIO_TPU_SSL_KEYFILE", str(key))
        from pio_tpu.server import create_event_server

        srv = create_event_server(host="127.0.0.1", port=0)
        assert srv.tls
        srv.start()
        try:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            with urllib.request.urlopen(
                f"https://127.0.0.1:{srv.port}/", context=ctx, timeout=10
            ) as r:
                assert json.loads(r.read())["status"] == "alive"
        finally:
            srv.stop()

    def test_plain_http_without_env(self, seeded_app):
        from pio_tpu.server import create_event_server

        srv = create_event_server(host="127.0.0.1", port=0)
        assert not srv.tls

    def test_explicit_none_forces_plain_http(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PIO_TPU_SSL_CERTFILE", str(tmp_path / "no.pem"))
        from pio_tpu.server.http import JsonHTTPServer, Router

        srv = JsonHTTPServer(Router(), "127.0.0.1", 0, ssl_context=None)
        assert not srv.tls  # None overrides the env (internal endpoints)
        srv._httpd.server_close()

    def test_stalled_handshake_does_not_block_others(
        self, tmp_path, seeded_app, monkeypatch
    ):
        import socket

        cert = tmp_path / "cert.pem"
        key = tmp_path / "key.pem"
        proc = subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=localhost"],
            capture_output=True,
        )
        if proc.returncode != 0:
            pytest.skip("openssl unavailable to mint a test cert")
        monkeypatch.setenv("PIO_TPU_SSL_CERTFILE", str(cert))
        monkeypatch.setenv("PIO_TPU_SSL_KEYFILE", str(key))
        from pio_tpu.server import create_event_server

        srv = create_event_server(host="127.0.0.1", port=0).start()
        stalled = socket.create_connection(("127.0.0.1", srv.port))
        try:
            # the silent connection must not stall the accept loop
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            with urllib.request.urlopen(
                f"https://127.0.0.1:{srv.port}/", context=ctx, timeout=10
            ) as r:
                assert json.loads(r.read())["status"] == "alive"
        finally:
            stalled.close()
            srv.stop()


class TestShell:
    def test_shell_executes_with_preloaded_names(self, tmp_home):
        # pipe a script into the REPL: facades + jnp must be bound
        proc = subprocess.run(
            [sys.executable, "-m", "pio_tpu", "shell"],
            input="print('SUM', int(jnp.arange(4).sum()));"
                  "print('HAS', PEventStore is not None, Event is not None)",
            # a cold jax import in the child takes ~1 min on this host
            # ALONE; a contended single core can triple that
            capture_output=True, text=True, timeout=360,
            env={**__import__('os').environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "SUM 6" in proc.stdout
        assert "HAS True True" in proc.stdout
