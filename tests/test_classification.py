"""Classification template tests: NB/logreg models + end-to-end lifecycle.

Mirrors the reference's scala-parallel-classification quickstart scenario
(SURVEY.md §4): $set user attributes → aggregateProperties → train →
query label.
"""

import datetime as dt

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers engine factories)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.models.logreg import LogRegConfig, train_logreg
from pio_tpu.models.naive_bayes import train_multinomial_nb
from pio_tpu.storage import App, Storage
from pio_tpu.templates.classification import PredictedResult, Query
from pio_tpu.workflow import (
    build_engine,
    load_models_for_instance,
    run_train,
    variant_from_dict,
)


# ------------------------------------------------------------ model level
class TestMultinomialNB:
    def test_separable_counts(self):
        # class 0 heavy on feature 0, class 1 heavy on feature 1
        X = np.array(
            [[8, 1], [9, 0], [7, 2], [1, 9], [0, 8], [2, 7]], np.float32
        )
        y = np.array([0, 0, 0, 1, 1, 1], np.int32)
        model = train_multinomial_nb(X, y, n_classes=2)
        assert model.predict(np.array([[10, 1]], np.float32))[0] == 0
        assert model.predict(np.array([[1, 10]], np.float32))[0] == 1

    def test_priors_reflect_imbalance(self):
        X = np.ones((4, 1), np.float32)
        y = np.array([0, 0, 0, 1], np.int32)
        model = train_multinomial_nb(X, y, n_classes=2)
        assert np.exp(model.log_prior[0]) == pytest.approx(0.75)

    def test_negative_features_rejected(self):
        with pytest.raises(ValueError):
            train_multinomial_nb(
                np.array([[-1.0]], np.float32), np.zeros(1, np.int32), 1
            )


class TestLogReg:
    def test_learns_linear_boundary(self):
        rng = np.random.default_rng(0)
        n = 256
        X = rng.normal(size=(n, 2)).astype(np.float32)
        y = (X[:, 0] + X[:, 1] > 0).astype(np.int32)
        ctx = ComputeContext.create(seed=0)
        model = train_logreg(
            ctx, X, y, n_classes=2,
            config=LogRegConfig(iterations=300, learning_rate=0.3),
        )
        acc = (model.predict(X) == y).mean()
        assert acc > 0.95

    def test_input_dtype_wire_parity(self):
        """Compressed feature wires (bf16 halves the dominant transfer,
        int8 quarters it with weight-folded scales) must learn the same
        boundary as the exact f32 wire — and the int8 model's WEIGHTS
        must apply to raw float features (the scales never leak into
        the serving contract)."""
        rng = np.random.default_rng(1)
        X = rng.normal(size=(512, 8)).astype(np.float32)
        w = rng.normal(size=(8, 3))
        y = np.argmax(X @ w, axis=1).astype(np.int32)
        ctx = ComputeContext.create(seed=0)
        accs = {}
        for dt in ("bfloat16", "float32", "int8"):
            m = train_logreg(
                ctx, X, y, n_classes=3,
                config=LogRegConfig(iterations=200, learning_rate=0.3,
                                    input_dtype=dt),
            )
            # predict() consumes RAW floats in every wire mode
            accs[dt] = (m.predict(X) == y).mean()
        assert accs["float32"] > 0.9
        assert abs(accs["bfloat16"] - accs["float32"]) < 0.05, accs
        assert abs(accs["int8"] - accs["float32"]) < 0.05, accs
        import pytest as _pytest

        with _pytest.raises(ValueError, match="input_dtype"):
            train_logreg(None, X, y, 3,
                         LogRegConfig(input_dtype="fp8"))

    def test_int8_constant_column_safe(self):
        """An all-zero feature column must not divide by zero in the
        quantizer (scale falls back to 1)."""
        rng = np.random.default_rng(5)
        X = rng.normal(size=(128, 4)).astype(np.float32)
        X[:, 2] = 0.0
        y = (X[:, 0] > 0).astype(np.int32)
        m = train_logreg(
            None, X, y, n_classes=2,
            config=LogRegConfig(iterations=150, learning_rate=0.3,
                                input_dtype="int8"),
        )
        assert np.isfinite(m.weights).all()
        assert (m.predict(X) == y).mean() > 0.9

    def test_streamed_wire_matches_monolithic(self, monkeypatch):
        """Chunked double-buffered shipment is a transport change only:
        identical bytes in identical order → bitwise-identical model."""
        rng = np.random.default_rng(2)
        X = rng.normal(size=(1024, 16)).astype(np.float32)
        w = rng.normal(size=(16, 3))
        y = np.argmax(X @ w, axis=1).astype(np.int32)
        for dt in ("float32", "int8"):
            cfg = LogRegConfig(iterations=50, learning_rate=0.2,
                               input_dtype=dt)
            monkeypatch.setenv("PIO_TPU_LOGREG_STREAM_MB", "0")
            mono = train_logreg(None, X, y, 3, cfg)
            # ~64 KiB wire / 0.01 MB chunks → the max 8 spans
            monkeypatch.setenv("PIO_TPU_LOGREG_STREAM_MB", "0.01")
            streamed = train_logreg(None, X, y, 3, cfg)
            np.testing.assert_array_equal(
                mono.weights, streamed.weights, err_msg=dt
            )
            np.testing.assert_array_equal(mono.bias, streamed.bias)

    def test_single_device_path(self):
        X = np.array([[0.0], [1.0], [2.0], [3.0]], np.float32)
        y = np.array([0, 0, 1, 1], np.int32)
        model = train_logreg(
            None, X, y, n_classes=2,
            config=LogRegConfig(iterations=200, learning_rate=0.5),
        )
        assert (model.predict(X) == y).all()

    def test_proba_sums_to_one(self):
        X = np.array([[1.0, 2.0]], np.float32)
        y = np.array([0], np.int32)
        model = train_logreg(
            None, X, y, n_classes=3,
            config=LogRegConfig(iterations=5),
        )
        assert model.predict_proba(X).sum() == pytest.approx(1.0, abs=1e-5)


# ------------------------------------------------------------- end-to-end
@pytest.fixture(autouse=True)
def isolated_storage(tmp_home):
    Storage.reset()
    yield
    Storage.reset()


def _seed_users(app_id: int):
    """Plan is decided by the dominant attribute (deterministic pattern)."""
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    rng = np.random.default_rng(7)
    n = 0
    for plan, hot in (("basic", 0), ("premium", 1), ("pro", 2)):
        for k in range(8):
            attrs = rng.integers(0, 3, size=3)
            attrs[hot] += 6  # dominant attribute determines the plan
            props = {f"attr{j}": int(attrs[j]) for j in range(3)}
            props["plan"] = plan
            le.insert(
                Event(
                    "$set", "user", f"u{n}",
                    properties=props,
                    event_time=t0 + dt.timedelta(minutes=n),
                ),
                app_id,
            )
            n += 1
    # one user missing the label → must be excluded by required= filter
    le.insert(
        Event("$set", "user", "unlabeled", properties={"attr0": 1, "attr1": 1,
                                                       "attr2": 1},
              event_time=t0),
        app_id,
    )


def _variant(algo):
    return variant_from_dict({
        "id": "cls-e2e",
        "engineFactory": "templates.classification",
        "datasource": {"params": {"app_name": "cls-test"}},
        "algorithms": [algo],
    })


class TestClassificationEndToEnd:
    @pytest.mark.parametrize(
        "algo",
        [
            {"name": "naivebayes", "params": {"lambda_": 1.0}},
            {
                "name": "logreg",
                "params": {"iterations": 300, "learning_rate": 0.3},
            },
            {
                # the int8 feature wire through the FULL template
                # lifecycle: train → persist → load → serve on raw
                # float queries (scales must never leak into serving)
                "name": "logreg",
                "params": {"iterations": 300, "learning_rate": 0.3,
                           "input_dtype": "int8"},
            },
        ],
        ids=["naivebayes", "logreg", "logreg-int8"],
    )
    def test_full_lifecycle(self, algo):
        app_id = Storage.get_meta_data_apps().insert(App(0, "cls-test"))
        _seed_users(app_id)

        variant = _variant(algo)
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        instance_id = run_train(engine, ep, variant, ctx=ctx)
        models = load_models_for_instance(instance_id, engine, ep, ctx)
        serving = engine.make_serving(ep)
        pairs = engine.algorithms_with_models(ep, models)

        def serve(q):
            return serving.serve(q, [a.predict(m, q) for a, m in pairs])

        # dominant attr0 → basic, attr1 → premium, attr2 → pro
        cases = [
            (Query(attrs=(9.0, 1.0, 1.0)), "basic"),
            (Query(attrs=(1.0, 9.0, 1.0)), "premium"),
            (Query(attrs=(1.0, 1.0, 9.0)), "pro"),
        ]
        for query, want in cases:
            result = serve(query)
            assert isinstance(result, PredictedResult)
            assert result.label == want

    def test_attr_fields_query_form(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "cls-test"))
        _seed_users(app_id)
        v = _variant({"name": "naivebayes", "params": {}})
        engine, ep = build_engine(v)
        ctx = ComputeContext.create(seed=0)
        instance_id = run_train(engine, ep, v, ctx=ctx)
        models = load_models_for_instance(instance_id, engine, ep, ctx)
        serving = engine.make_serving(ep)
        pairs = engine.algorithms_with_models(ep, models)
        q = Query(attr0=9.0, attr1=1.0, attr2=1.0)
        result = serving.serve(q, [a.predict(m, q) for a, m in pairs])
        assert result.label == "basic"

    def test_wrong_arity_query_raises(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "cls-test"))
        _seed_users(app_id)
        v = _variant({"name": "naivebayes", "params": {}})
        engine, ep = build_engine(v)
        ctx = ComputeContext.create(seed=0)
        instance_id = run_train(engine, ep, v, ctx=ctx)
        models = load_models_for_instance(instance_id, engine, ep, ctx)
        pairs = engine.algorithms_with_models(ep, models)
        with pytest.raises(ValueError):
            [a.predict(m, Query(attrs=(1.0,))) for a, m in pairs]


class TestShippedEvaluation:
    def test_classification_evaluation_sweep(self):
        from pio_tpu.templates.classification import (
            classification_evaluation,
        )
        from pio_tpu.workflow import run_evaluation

        app_id = Storage.get_meta_data_apps().insert(App(0, "cls-eval"))
        _seed_users(app_id)
        ev = classification_evaluation(app_name="cls-eval", eval_k=3)
        result = run_evaluation(
            ev, ev.engine_params_generator, ctx=ComputeContext.create()
        )
        assert result.best_score > 0.8
        insts = Storage.get_meta_data_evaluation_instances().get_all()
        assert insts[0].status == "COMPLETED"


class TestBatchPredict:
    @pytest.mark.parametrize("algo", ["naivebayes", "logreg"])
    def test_batch_matches_loop(self, algo):
        from pio_tpu.workflow import run_train

        app_id = Storage.get_meta_data_apps().insert(App(0, "cls-test"))
        _seed_users(app_id)
        variant = variant_from_dict({
            "id": "cb", "engineFactory": "templates.classification",
            "datasource": {"params": {"app_name": "cls-test"}},
            "algorithms": [{"name": algo, "params": {}}],
        })
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        iid = run_train(engine, ep, variant, ctx=ctx)
        models = load_models_for_instance(iid, engine, ep, ctx)
        a, model = engine.algorithms_with_models(ep, models)[0]
        queries = [
            (i, Query(attrs=(float(6 + i % 3), float(i % 2), 0.0)))
            for i in range(12)
        ]
        loop = {i: a.predict(model, q) for i, q in queries}
        bat = dict(a.batch_predict(model, queries))
        assert {i: r.label for i, r in loop.items()} == {
            i: r.label for i, r in bat.items()
        }
