"""Partitioned, replicated event log (ISSUE 9): CRC framing + torn-tail
repair, segment chains, the entity-id partition router, follower
replication with durability-gated acks, SIGKILL crash consistency at
every ``PIO_TPU_DURABILITY`` level, longest-verified-prefix failover,
snapshot compaction (byte-identical to full-history replay, loud
fallbacks), the ``/storage.json`` topology endpoint, breaker shedding
for a dead partition, and the per-reason worker respawn budgets."""

import datetime as dt
import json
import os
import socket
import subprocess
import sys
import textwrap
import time
import urllib.error
import urllib.request

import pytest

from pio_tpu import faults
from pio_tpu.data.datamap import DataMap
from pio_tpu.data.event import Event
from pio_tpu.faults.registry import CRASH_EXIT_CODE, ENV_VAR
from pio_tpu.obs import monotonic_s
from pio_tpu.storage.base import StorageError
from pio_tpu.storage.partlog import (
    PartitionedEventLog, compaction, failover, framing, partition_of,
    replication,
)
from pio_tpu.storage.partlog.segments import SegmentLog


@pytest.fixture(autouse=True)
def clean_faults(monkeypatch):
    monkeypatch.delenv(ENV_VAR, raising=False)
    faults.uninstall()
    yield
    faults.uninstall()


def T(h):
    return dt.datetime(2026, 3, 1, h, tzinfo=dt.timezone.utc)


def ev(name, t, eid="u1", etype="user", target=None, props=None):
    return Event(
        name, etype, eid,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=props or {},
        event_time=t,
    )


# ------------------------------------------------------------------ framing
class TestFraming:
    def test_roundtrip(self, tmp_path):
        data = b"".join(framing.frame(f"p{i}".encode()) for i in range(5))
        payloads, verified, total = framing.scan(data, origin="mem")
        assert payloads == [f"p{i}".encode() for i in range(5)]
        assert verified == total == len(data)

    def test_torn_tail_is_tolerated(self):
        data = framing.frame(b"good") + framing.frame(b"torn")[:-3]
        payloads, verified, total = framing.scan(data, origin="mem")
        assert payloads == [b"good"]
        assert verified == len(framing.frame(b"good")) and total == len(data)

    def test_mid_file_corruption_raises(self):
        a, b = framing.frame(b"aaaa"), framing.frame(b"bbbb")
        bad = bytearray(a + b)
        bad[6] ^= 0xFF  # corrupt frame 0's payload; frame 1 follows whole
        with pytest.raises(StorageError, match="not a torn tail"):
            framing.scan(bytes(bad), origin="mem")

    def test_repair_truncates_loudly(self, tmp_path, caplog):
        p = tmp_path / "seg.log"
        p.write_bytes(framing.frame(b"keep") + b"\x99\x98garbage")
        with caplog.at_level("WARNING", logger="pio_tpu.partlog"):
            dropped = framing.repair(str(p))
        assert dropped == len(b"\x99\x98garbage")
        assert "truncating torn tail" in caplog.text
        assert p.read_bytes() == framing.frame(b"keep")
        assert framing.repair(str(p)) == 0  # already clean: silent no-op

    def test_verified_prefix_of_missing_file(self, tmp_path):
        assert framing.verified_prefix(str(tmp_path / "nope")) == 0


# ----------------------------------------------------------------- segments
class TestSegmentLog:
    def test_append_offsets_and_sealing(self, tmp_path):
        s = SegmentLog(str(tmp_path / "p"), partition=0, seg_bytes=64)
        offs = [s.append(framing.frame(bytes(24))) for _ in range(4)]
        assert offs[0][0] == 0 and all(
            a[1] == b[0] for a, b in zip(offs, offs[1:])
        )
        segs = s.segments()
        assert len(segs) >= 2  # 32-byte frames against a 64-byte roll
        assert [g["start"] for g in segs] == sorted(
            g["start"] for g in segs
        )
        assert sum(g["bytes"] for g in segs) == s.committed
        assert len(s.payloads()) == 4
        s.close()

    def test_read_range_spans_segments(self, tmp_path):
        s = SegmentLog(str(tmp_path / "p"), partition=0, seg_bytes=40)
        whole = b""
        for i in range(6):
            f = framing.frame(f"payload-{i}".encode())
            s.append(f)
            whole += f
        assert s.read_range(0, s.committed) == whole
        assert s.read_range(13, 57) == whole[13:57]
        assert s.read_range(0, 10 ** 9) == whole  # end clamps to committed
        s.close()

    def test_reopen_repairs_torn_tail(self, tmp_path):
        pdir = tmp_path / "p"
        s = SegmentLog(str(pdir), partition=0)
        s.append(framing.frame(b"acked"))
        s.close()
        # simulate a crash mid-append: raw torn bytes past the last frame
        (pdir / "seg-00000001.log").open("ab").write(b"\x07\x00\x00")
        s2 = SegmentLog(str(pdir), partition=0)
        assert s2.payloads() == [b"acked"]
        s2.close()

    def test_injected_torn_write_heals_before_next_append(self, tmp_path):
        s = SegmentLog(str(tmp_path / "p"), partition=0)
        s.append(framing.frame(b"first"))
        faults.install("partlog.append.before_write=torn_write:once")
        with pytest.raises(StorageError, match="torn write"):
            s.append(framing.frame(b"wounded"))
        faults.uninstall()
        # the torn bytes are on disk past committed; the next append
        # must repair them away so the new record scans
        s.append(framing.frame(b"second"))
        assert s.payloads() == [b"first", b"second"]
        s.close()


# ------------------------------------------------------------------ routing
class TestRouter:
    def test_stable_and_spread(self):
        ids = [f"user-{i}" for i in range(200)]
        first = [partition_of(i, 4) for i in ids]
        assert first == [partition_of(i, 4) for i in ids]
        assert set(first) == {0, 1, 2, 3}  # every partition takes load

    def test_same_entity_same_partition(self, tmp_path):
        log = PartitionedEventLog(str(tmp_path / "pl"), partitions=4)
        for h in range(1, 9):
            log.insert(ev("rate", T(h), eid="sticky"), 1)
        k = partition_of("sticky", 4)
        with log._view.lock:
            assert all(
                row[0] == k
                for row in log._view.buckets[(1, None)].values()
            )
        log.close()

    def test_manifest_wins_over_env(self, tmp_path, monkeypatch):
        root = str(tmp_path / "pl")
        PartitionedEventLog(root, partitions=3).close()
        monkeypatch.setenv("PIO_TPU_PARTLOG_PARTITIONS", "8")
        reopened = PartitionedEventLog(root)
        assert reopened.partitions == 3  # repartitioning would strand keys
        reopened.close()

    def test_reopen_replays_view(self, tmp_path):
        root = str(tmp_path / "pl")
        log = PartitionedEventLog(root, partitions=3)
        ids = [
            log.insert(ev("rate", T(h), eid=f"u{h}"), 1)
            for h in range(1, 6)
        ]
        assert log.delete(ids[0], 1)
        log.close()
        again = PartitionedEventLog(root)
        assert {e.event_id for e in again.find(1)} == set(ids[1:])
        again.close()

    def test_post_remove_writes_survive_reopen(self, tmp_path):
        """A channel purge fans one rm record into every partition, but
        replay walks partitions SEQUENTIALLY: each rm must clear only
        its own partition's pre-purge entries, or events acked after
        the purge that routed to a lower-numbered partition get
        replayed first and then wiped by a later partition's rm."""
        root = str(tmp_path / "pl")
        log = PartitionedEventLog(root, partitions=4)
        for h in range(1, 6):
            log.insert(ev("rate", T(h), eid=f"old{h}"), 1)
        assert log.remove(1)
        # new1..new8 spread over all 4 partitions (verified routing)
        ids = [
            log.insert(ev("rate", T(h), eid=f"new{h}"), 1)
            for h in range(1, 9)
        ]
        assert {e.event_id for e in log.find(1)} == set(ids)
        log.close()
        again = PartitionedEventLog(root)
        assert {e.event_id for e in again.find(1)} == set(ids)
        again.close()

    def test_batch_writes_ride_the_committer(self, tmp_path):
        """insert_batch and delete_bulk must go through the partition's
        GroupCommitter (one group payload per partition touched), never
        flush directly — a direct flush could interleave with a
        committer-led flush on the same partition, letting segment
        order and view order diverge."""
        log = PartitionedEventLog(str(tmp_path / "pl"), partitions=2)
        submitted = []
        for k, gc in enumerate(log._committers):
            gc.submit = (
                lambda payload, _k=k, _orig=gc.submit:
                submitted.append((_k, len(payload))) or _orig(payload)
            )
        events = [ev("rate", T(h), eid=f"u{h}") for h in range(1, 9)]
        ids = log.insert_batch(events, 1)
        assert len(ids) == 8
        assert sum(n for _, n in submitted) == 8
        assert {k for k, _ in submitted} == {
            partition_of(f"u{h}", 2) for h in range(1, 9)
        }
        submitted.clear()
        log.delete_bulk(ids[:3], 1)
        assert sum(n for _, n in submitted) == 3
        assert len(log.find(1)) == 5
        log.close()


# -------------------------------------------------------------- replication
class TestReplication:
    def test_follower_mirrors_leader_stream(self, tmp_path, monkeypatch):
        froot = str(tmp_path / "follower")
        f = replication.FollowerServer(froot)
        monkeypatch.setenv(
            "PIO_TPU_PARTLOG_REPLICAS", f"127.0.0.1:{f.port}"
        )
        monkeypatch.setenv("PIO_TPU_DURABILITY", "commit")
        log = PartitionedEventLog(str(tmp_path / "leader"), partitions=2)
        for h in range(1, 7):
            log.insert(ev("rate", T(h), eid=f"u{h}"), 1)
        # commit durability: insert returned ⇒ the follower acked, so
        # its mirror must already hold every partition's full stream
        for k in range(2):
            mirror = os.path.join(froot, f"p{k:03d}.repl")
            want = log.read_range(k, 0, log.committed(k))
            assert framing.verified_prefix(mirror) == len(want)
            with open(mirror, "rb") as fh:
                assert fh.read(len(want)) == want
        log.close()
        f.stop()

    def test_ack_timeout_fails_fast(self, tmp_path, monkeypatch):
        # a replica address nobody answers: commit-durability inserts
        # must fail with a NON-transient error (fast path to the
        # breaker), not burn the retry budget
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        monkeypatch.setenv(
            "PIO_TPU_PARTLOG_REPLICAS", f"127.0.0.1:{dead_port}"
        )
        monkeypatch.setenv("PIO_TPU_REPL_ACK_TIMEOUT_S", "0.2")
        monkeypatch.setenv("PIO_TPU_REPL_CONNECT_DEADLINE_S", "0.2")
        monkeypatch.setenv("PIO_TPU_DURABILITY", "commit")
        log = PartitionedEventLog(str(tmp_path / "leader"), partitions=2)
        from pio_tpu.storage.retry import is_transient

        t0 = monotonic_s()
        with pytest.raises(StorageError, match="replication ack timeout") as ei:
            log.insert(ev("rate", T(1)), 1)
        assert not is_transient(ei.value)
        assert monotonic_s() - t0 < 5.0
        log.close()

    def test_ack_timeout_does_not_duplicate_appends(
        self, tmp_path, monkeypatch
    ):
        """An ack timeout fires AFTER the blob hit the leader's segment
        log: the flush must report it via PartialFlushOutcome so the
        committer fails the whole batch in ONE timeout — a generic
        raise would trigger the solo-retry path, re-appending every
        already-persisted payload and waiting the timeout per payload."""
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        monkeypatch.setenv(
            "PIO_TPU_PARTLOG_REPLICAS", f"127.0.0.1:{dead_port}"
        )
        monkeypatch.setenv("PIO_TPU_REPL_ACK_TIMEOUT_S", "0.2")
        monkeypatch.setenv("PIO_TPU_REPL_CONNECT_DEADLINE_S", "0.2")
        monkeypatch.setenv("PIO_TPU_DURABILITY", "commit")
        log = PartitionedEventLog(str(tmp_path / "leader"), partitions=1)
        events = [ev("rate", T(h), eid=f"u{h}") for h in range(1, 7)]
        t0 = monotonic_s()
        with pytest.raises(StorageError, match="replication ack timeout"):
            log.insert_batch(events, 1)
        # one timeout for the whole batch, not (B+1) solo re-waits
        assert monotonic_s() - t0 < 2.0
        # each record persisted exactly once — no solo re-appends
        assert len(log._segs[0].payloads()) == 6
        # persisted-but-unacked: live view matches what replay serves
        assert len(log.find(1)) == 6
        log.close()
        again = PartitionedEventLog(str(tmp_path / "leader"))
        assert len(again.find(1)) == 6
        again.close()

    def test_min_acks_above_replica_count_raises(
        self, tmp_path, monkeypatch
    ):
        # silently capping min_acks to the replica count would weaken
        # the durability guarantee the operator asked for — misconfig
        # must fail construction loudly (durability.mode() policy)
        monkeypatch.setenv("PIO_TPU_PARTLOG_REPLICAS", "127.0.0.1:9")
        monkeypatch.setenv("PIO_TPU_REPL_MIN_ACKS", "3")
        with pytest.raises(StorageError, match="PIO_TPU_REPL_MIN_ACKS"):
            PartitionedEventLog(str(tmp_path / "leader"), partitions=2)

    def test_reconnect_catches_up(self, tmp_path, monkeypatch):
        """A follower that was down during the writes reconnects and
        pulls the whole backlog (jittered-deadline reconnect path)."""
        froot = str(tmp_path / "follower")
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()  # reserve then release: follower starts here LATER
        monkeypatch.setenv("PIO_TPU_PARTLOG_REPLICAS", f"127.0.0.1:{port}")
        monkeypatch.setenv("PIO_TPU_DURABILITY", "batch")  # no ack gate
        monkeypatch.setenv("PIO_TPU_REPL_CONNECT_DEADLINE_S", "15")
        log = PartitionedEventLog(str(tmp_path / "leader"), partitions=2)
        for h in range(1, 7):
            log.insert(ev("rate", T(h), eid=f"u{h}"), 1)
        f = replication.FollowerServer(
            froot, port=port
        )  # comes up late; the link's retrying() reconnect finds it
        want = {k: log.committed(k) for k in range(2)}
        deadline = monotonic_s() + 20
        while monotonic_s() < deadline:
            got = {
                k: framing.verified_prefix(
                    os.path.join(froot, f"p{k:03d}.repl")
                )
                for k in range(2)
            }
            if got == want:
                break
            time.sleep(0.05)
        assert got == want, f"follower never caught up: {got} != {want}"
        log.close()
        f.stop()


# --------------------------------------------- crash consistency + failover
_CRASH_WRITER = textwrap.dedent("""
    import datetime as dt
    import os
    import sys

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root, ackfile = sys.argv[1], sys.argv[2]

    from pio_tpu.data.event import Event
    from pio_tpu.storage.partlog import PartitionedEventLog

    b = PartitionedEventLog(root)
    t = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    ack = open(ackfile, "w")
    for i in range(12):
        eid = b.insert(
            Event(event="e", entity_type="u", entity_id=f"u{i}",
                  event_time=t),
            1,
        )
        # the ack protocol: an id reaches this file only AFTER insert
        # returned (the 201 analog), fsynced so the parent can trust it
        ack.write(eid + "\\n")
        ack.flush()
        os.fsync(ack.fileno())

    from pio_tpu import faults
    faults.install("groupcommit.flush.partlog*=crash:once")
    b.insert(
        Event(event="e", entity_type="u", entity_id="boom", event_time=t),
        1,
    )
    print("UNREACHABLE")
""")


def _run_writer(script, *argv, env_extra=None):
    env = dict(os.environ)
    env.pop(ENV_VAR, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-c", script, *argv],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


class TestCrashFailover:
    @pytest.mark.parametrize("level", ["commit", "batch", "os"])
    def test_sigkill_leader_mid_commit_with_two_followers(
        self, tmp_path, level
    ):
        """The chaos drill, per durability level: the leader process
        dies (os._exit, no unwinding) inside a partition group-commit
        flush with two live followers. A follower with the longest
        verified prefix is promoted; at ``commit`` durability the
        promoted log must serve EVERY acked write (the ack was gated on
        follower fsync); at every level the promoted root opens clean
        and keeps accepting writes."""
        froot1 = str(tmp_path / "f1")
        froot2 = str(tmp_path / "f2")
        f1 = replication.FollowerServer(froot1)
        f2 = replication.FollowerServer(froot2)
        root = str(tmp_path / "leader")
        ackfile = str(tmp_path / "acks")
        try:
            proc = _run_writer(
                _CRASH_WRITER, root, ackfile,
                env_extra={
                    "PIO_TPU_DURABILITY": level,
                    "PIO_TPU_PARTLOG_PARTITIONS": "3",
                    "PIO_TPU_PARTLOG_REPLICAS":
                        f"127.0.0.1:{f1.port},127.0.0.1:{f2.port}",
                },
            )
        finally:
            f1.stop()
            f2.stop()
        assert proc.returncode == CRASH_EXIT_CODE, proc.stderr
        assert "injected crash" in proc.stderr
        assert "UNREACHABLE" not in proc.stdout
        with open(ackfile) as f:
            acked = [line.strip() for line in f if line.strip()]
        assert len(acked) == 12

        dest = str(tmp_path / "promoted")
        res = failover.promote([froot1, froot2], dest)
        assert res["partitions"] == 3
        b = PartitionedEventLog(dest)
        got = {e.event_id for e in b.find(1)}
        if level == "commit":
            assert set(acked) <= got, (
                f"lost acked events: {set(acked) - got}"
            )
            assert "boom" not in {e.entity_id for e in b.find(1)}
        # at every level the promoted log recovered clean and serves
        n = len(b.find(1))
        b.insert(ev("e", T(9), eid="after-failover"), 1)
        assert len(b.find(1)) == n + 1
        b.close()


class TestElection:
    def _mk_follower_root(self, path, streams, torn=b""):
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "MANIFEST.json"), "w") as f:
            json.dump({"version": 1, "partitions": len(streams)}, f)
        for k, payloads in enumerate(streams):
            with open(os.path.join(path, f"p{k:03d}.repl"), "wb") as f:
                for p in payloads:
                    f.write(framing.frame(p))
                f.write(torn)

    def test_longest_verified_prefix_wins_per_partition(self, tmp_path):
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        # a leads on partition 0; b leads on partition 1 — election is
        # PER PARTITION, so each winner is chosen independently
        self._mk_follower_root(a, [[b"x", b"y"], [b"q"]])
        self._mk_follower_root(b, [[b"x"], [b"q", b"r", b"s"]])
        out = failover.elect([a, b])
        assert out[0]["winner"] == a
        assert out[1]["winner"] == b
        assert out[0]["position"] == len(framing.frame(b"x") * 2)
        assert set(out[0]["candidates"]) == {a, b}

    def test_torn_tail_never_scores_and_promote_drops_it(
        self, tmp_path, caplog
    ):
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        # b has MORE raw bytes but its tail is torn — a's fully-verified
        # stream must win, and promotion from a torn winner truncates
        self._mk_follower_root(a, [[b"x", b"y"]])
        self._mk_follower_root(b, [[b"x"]], torn=framing.frame(b"t")[:-2])
        out = failover.elect([a, b])
        assert out[0]["winner"] == a
        dest = str(tmp_path / "dest")
        with caplog.at_level("WARNING", logger="pio_tpu.partlog"):
            failover.promote([b], dest)  # only the torn candidate left
        assert "torn bytes" in caplog.text
        seg = os.path.join(dest, "p000", "seg-00000001.log")
        assert open(seg, "rb").read() == framing.frame(b"x")

    def test_no_manifest_anywhere_raises(self, tmp_path):
        with pytest.raises(StorageError, match="MANIFEST"):
            failover.elect([str(tmp_path / "empty")])

    def test_promote_refuses_nonempty_dest(self, tmp_path):
        # a prior incarnation's files (an older seg-00000002.log, a
        # snapshot) would mix into the promoted chain — refuse loudly
        a = str(tmp_path / "a")
        self._mk_follower_root(a, [[b"x"]])
        dest = str(tmp_path / "dest")
        os.makedirs(os.path.join(dest, "p000"))
        with open(
            os.path.join(dest, "p000", "seg-00000002.log"), "wb"
        ) as f:
            f.write(framing.frame(b"stale"))
        with pytest.raises(StorageError, match="not empty"):
            failover.promote([a], dest)
        # a pre-created but EMPTY dest is fine
        dest2 = str(tmp_path / "dest2")
        os.makedirs(dest2)
        res = failover.promote([a], dest2)
        assert res["partitions"] == 1


# --------------------------------------------------------------- compaction
class TestCompaction:
    def _fill(self, log):
        log.insert(ev("$set", T(1), "u1", props={"a": 1, "plan": "free"}), 1)
        log.insert(ev("$set", T(2), "u1", props={"plan": "pro"}), 1)
        log.insert(ev("$unset", T(3), "u1", props={"a": None}), 1)
        log.insert(ev("$set", T(1), "u2", props={"b": 2}), 1)
        log.insert(ev("$delete", T(2), "u2"), 1)
        log.insert(ev("$set", T(1), "u3", props={"c": 3}), 1)
        log.insert(ev("rate", T(4), "u1", target="i1"), 1)

    @staticmethod
    def _dump(agg):
        return {
            k: (v.to_dict(), v.first_updated, v.last_updated)
            for k, v in sorted(agg.items())
        }

    def test_snapshot_read_identical_to_full_replay(self, tmp_path):
        log = PartitionedEventLog(str(tmp_path / "pl"), partitions=3)
        self._fill(log)
        before = log.aggregate_properties(1, "user")
        log.compact()
        topo = log.topology()
        assert all(
            p["snapshot_watermark"] == p["records"]
            for p in topo["partition_detail"] if p["records"]
        )
        after = log.aggregate_properties(1, "user")
        assert self._dump(before) == self._dump(after)
        # cold reopen reads the snapshot from disk, same answer
        log.close()
        again = PartitionedEventLog(str(tmp_path / "pl"))
        assert self._dump(again.aggregate_properties(1, "user")) == \
            self._dump(before)
        again.close()

    def test_resume_fold_past_watermark(self, tmp_path):
        log = PartitionedEventLog(str(tmp_path / "pl"), partitions=3)
        self._fill(log)
        log.compact()
        log.insert(ev("$set", T(5), "u1", props={"tier": "gold"}), 1)
        log.insert(ev("$set", T(5), "u9", props={"new": True}), 1)
        agg = log.aggregate_properties(1, "user")
        assert agg["u1"].to_dict() == {"plan": "pro", "tier": "gold"}
        assert agg["u9"].to_dict() == {"new": True}  # born post-watermark
        log.close()

    def test_checksum_fallback_is_loud_and_exact(self, tmp_path, caplog):
        log = PartitionedEventLog(str(tmp_path / "pl"), partitions=2)
        self._fill(log)
        want = self._dump(log.aggregate_properties(1, "user"))
        log.compact()
        fell = compaction._FALLBACKS.value("checksum")
        # flip a byte inside every partition's snapshot body
        for k in range(2):
            p = os.path.join(
                str(tmp_path / "pl"), f"p{k:03d}", "snapshot.json"
            )
            raw = bytearray(open(p, "rb").read())
            raw[len(raw) // 2] ^= 0xFF
            open(p, "wb").write(bytes(raw))
        log._snapshots.clear()
        with caplog.at_level("WARNING", logger="pio_tpu.partlog"):
            got = self._dump(log.aggregate_properties(1, "user"))
        assert got == want  # fallback is the exact full fold
        assert "sha256" in caplog.text
        assert compaction._FALLBACKS.value("checksum") > fell
        log.close()

    def test_rewritten_history_falls_back(self, tmp_path):
        log = PartitionedEventLog(str(tmp_path / "pl"), partitions=2)
        self._fill(log)
        log.compact()
        # delete a PRE-watermark $set: the snapshot's folded state for
        # u1 is now stale and its event count no longer matches
        doomed = [
            e for e in log.find(1, entity_id="u1", event_names=["$set"])
            if e.properties.get("plan") == "pro"
        ]
        assert log.delete(doomed[0].event_id, 1)
        fell = compaction._FALLBACKS.value("history_rewritten")
        agg = log.aggregate_properties(1, "user")
        assert agg["u1"].to_dict() == {"plan": "free"}  # re-folded truth
        assert compaction._FALLBACKS.value("history_rewritten") > fell
        log.close()

    def test_out_of_order_suffix_falls_back(self, tmp_path):
        log = PartitionedEventLog(str(tmp_path / "pl"), partitions=2)
        log.insert(ev("$set", T(5), "u1", props={"plan": "pro"}), 1)
        log.compact()
        # a suffix event OLDER than the folded max: resuming would fold
        # it after the snapshot state — the exact order folds it before
        log.insert(ev("$set", T(2), "u1", props={"plan": "free"}), 1)
        fell = compaction._FALLBACKS.value("out_of_order")
        agg = log.aggregate_properties(1, "user")
        assert agg["u1"].to_dict() == {"plan": "pro"}  # T(5) still wins
        assert compaction._FALLBACKS.value("out_of_order") > fell
        log.close()

    def test_time_windowed_reads_bypass_snapshot(self, tmp_path):
        log = PartitionedEventLog(str(tmp_path / "pl"), partitions=2)
        self._fill(log)
        log.compact()
        agg = log.aggregate_properties(1, "user", until_time=T(2))
        assert agg["u1"].to_dict() == {"a": 1, "plan": "free"}
        log.close()


# -------------------------------------------------- /storage.json + breaker
def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            return (resp.status, json.loads(resp.read() or b"null"),
                    {k.lower(): v for k, v in resp.headers.items()})
    except urllib.error.HTTPError as e:
        return (e.code, json.loads(e.read() or b"null"),
                {k.lower(): v for k, v in e.headers.items()})


@pytest.fixture()
def partlog_server_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_TPU_HOME", str(tmp_path))
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "PL")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_PL_TYPE", "partlog")
    monkeypatch.setenv(
        "PIO_STORAGE_SOURCES_PL_PATH", str(tmp_path / "partlog")
    )
    monkeypatch.setenv("PIO_TPU_PARTLOG_PARTITIONS", "3")
    from pio_tpu.storage import Storage

    Storage.reset()
    yield monkeypatch
    Storage.reset()


class TestStorageEndpoint:
    def test_partlog_topology(self, partlog_server_env):
        from pio_tpu.server import create_event_server
        from pio_tpu.storage import AccessKey, App, Storage

        app_id = Storage.get_meta_data_apps().insert(App(0, "topo"))
        key = Storage.get_meta_data_access_keys().insert(
            AccessKey("", app_id)
        )
        server = create_event_server(host="127.0.0.1", port=0).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            e = {"event": "rate", "entityType": "user", "entityId": "u1",
                 "eventTime": "2026-03-01T10:00:00Z"}
            assert _http(
                "POST", f"{url}/events.json?accessKey={key}", e
            )[0] == 201
            status, topo, _ = _http("GET", f"{url}/storage.json")
            assert status == 200
            assert topo["backend"] == "partlog"
            assert topo["role"] == "leader" and topo["partitions"] == 3
            assert len(topo["partition_detail"]) == 3
            assert sum(
                p["records"] for p in topo["partition_detail"]
            ) == 1
            assert topo["replication"] is None  # no replicas configured
        finally:
            server.stop()

    def test_non_partlog_backend_reports_type(self, tmp_home, monkeypatch):
        from pio_tpu.server import create_event_server
        from pio_tpu.storage import Storage

        monkeypatch.setenv(
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "MEM"
        )
        monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
        monkeypatch.setenv(
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM"
        )
        Storage.reset()
        server = create_event_server(host="127.0.0.1", port=0).start()
        try:
            status, body, _ = _http(
                "GET", f"http://127.0.0.1:{server.port}/storage.json"
            )
            assert status == 200
            assert body == {"backend": "MemLEvents", "topology": None}
        finally:
            server.stop()
            Storage.reset()


class TestBreakerShedsDeadPartition:
    def test_dead_replica_opens_breaker_503(self, partlog_server_env):
        """Satellite 2: commit-durability inserts against a replica
        that never acks fail fast (non-transient ack timeout), trip the
        storage breaker, and subsequent writes shed 503 + Retry-After
        with the shed counted against the SLO budget."""
        mp = partlog_server_env
        mp.setenv(
            "PIO_TPU_PARTLOG_REPLICAS", f"127.0.0.1:{_free_port()}"
        )
        mp.setenv("PIO_TPU_REPL_ACK_TIMEOUT_S", "0.2")
        mp.setenv("PIO_TPU_REPL_CONNECT_DEADLINE_S", "0.2")
        mp.setenv("PIO_TPU_DURABILITY", "commit")
        from pio_tpu.server import create_event_server
        from pio_tpu.storage import AccessKey, App, Storage

        Storage.reset()
        app_id = Storage.get_meta_data_apps().insert(App(0, "breaker"))
        key = Storage.get_meta_data_access_keys().insert(
            AccessKey("", app_id)
        )
        server = create_event_server(
            host="127.0.0.1", port=0,
            qos="rps=1000,fail_rate=0.5,fail_window=4,"
                "cooldown=60s,probes=1",
        ).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            e = {"event": "rate", "entityType": "user", "entityId": "u1",
                 "eventTime": "2026-03-01T10:00:00Z"}
            for _ in range(4):
                status, _, _ = _http(
                    "POST", f"{url}/events.json?accessKey={key}", e
                )
                assert status == 500  # ack timeout surfaces, not hangs
            # breaker open: fail fast BEFORE storage is touched again
            status, body, headers = _http(
                "POST", f"{url}/events.json?accessKey={key}", e
            )
            assert status == 503
            assert "breaker" in body["message"]
            assert int(headers["retry-after"]) >= 1
            snap = _http("GET", f"{url}/qos.json")[1]
            assert snap["breakers"]["storage"]["state"] == "open"
            assert snap["shed"]["breaker"] >= 1
        finally:
            server.stop()


# ------------------------------------------- worker pool per-reason budgets
class TestRespawnBudgetSplit:
    def _shell(self, n=1):
        from pio_tpu.obs import REGISTRY
        from pio_tpu.server.worker_pool import (
            _MAX_RESPAWNS_BY_REASON, ServingPool,
        )

        pool = ServingPool.__new__(ServingPool)  # no spawn
        pool.n_workers = n
        pool._respawns = [
            {r: 0 for r in _MAX_RESPAWNS_BY_REASON} for _ in range(n)
        ]
        pool._retired = [False] * n
        pool._respawn_due = [0.0] * n
        pool._spawned_at = [0.0] * n
        pool._kill_reason = [None] * n
        pool._respawn_counter = REGISTRY.counter(
            "pio_tpu_worker_respawn_total", "", ("reason",)
        )
        return pool

    def test_unhealthy_kills_do_not_burn_crash_budget(self):
        from pio_tpu.server.worker_pool import _MAX_RESPAWNS_BY_REASON

        pool = self._shell()
        for _ in range(_MAX_RESPAWNS_BY_REASON["unhealthy"]):
            pool._kill_reason[0] = "unhealthy"
            pool._account_death(0, -9, now=100.0)
            assert pool._respawn_due[0] > 0.0
            pool._respawn_due[0] = 0.0
        assert pool._respawns[0]["crash"] == 0
        assert not pool._retired[0]
        # the crash budget is untouched: a real crash still respawns
        pool._account_death(0, 1, now=100.0)
        assert pool._respawns[0]["crash"] == 1
        assert pool._respawn_due[0] > 0.0

    def test_each_reason_retires_on_its_own_budget(self):
        from pio_tpu.server.worker_pool import _MAX_RESPAWNS_BY_REASON

        pool = self._shell()
        for _ in range(_MAX_RESPAWNS_BY_REASON["crash"]):
            pool._account_death(0, 1, now=50.0)
            pool._respawn_due[0] = 0.0
        assert not pool._retired[0]
        pool._account_death(0, 1, now=50.0)  # budget spent: retire
        assert pool._retired[0]
        assert pool._respawn_due[0] == 0.0
        # retired is terminal — even an unhealthy death stays down
        pool._kill_reason[0] = "unhealthy"
        pool._account_death(0, -9, now=50.0)
        assert pool._respawn_due[0] == 0.0

    def test_long_uptime_resets_every_reason(self):
        pool = self._shell()
        pool._kill_reason[0] = "unhealthy"
        pool._account_death(0, -9, now=10.0)
        pool._account_death(0, 1, now=10.0)
        assert pool._respawns[0] == {"crash": 1, "unhealthy": 1}
        pool._respawn_due[0] = 0.0
        pool._spawned_at[0] = 10.0
        pool._account_death(0, 1, now=10.0 + 61.0)  # served 61s: not a loop
        assert pool._respawns[0] == {"crash": 1, "unhealthy": 0}

    def test_backoff_tracks_per_reason_streak(self):
        from pio_tpu.server.worker_pool import _RESPAWN_BACKOFF_BASE_S

        pool = self._shell()
        pool._account_death(0, 1, now=100.0)
        pool._respawn_due[0] = 0.0
        pool._account_death(0, 1, now=100.0)
        crash_delay_2 = pool._respawn_due[0] - 100.0
        assert crash_delay_2 == pytest.approx(_RESPAWN_BACKOFF_BASE_S * 2)
        pool._respawn_due[0] = 0.0
        # first unhealthy death: ITS streak is 1 → base delay, not the
        # doubled cool-down the crash streak earned
        pool._kill_reason[0] = "unhealthy"
        pool._account_death(0, -9, now=100.0)
        assert pool._respawn_due[0] - 100.0 == pytest.approx(
            _RESPAWN_BACKOFF_BASE_S
        )
