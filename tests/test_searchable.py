"""Searchable (ES-analog) backend — FTS5 capabilities beyond the base SPI.

The relational conformance runs in tests/test_storage.py (the backend is
one of the parameterized fixtures there); this file covers what makes it
the Elasticsearch slot: BM25 full-text search over events, apps, and run
metadata, index consistency through every write path (triggers, not
Python), and adopting a pre-existing plain-sqlite file.
"""

import datetime as dt

import pytest

from pio_tpu.data.event import Event
from pio_tpu.storage.records import App, EngineInstance, EvaluationInstance
from pio_tpu.storage.searchable import (
    SearchableApps,
    SearchableClient,
    SearchableEngineInstances,
    SearchableEvaluationInstances,
    SearchableEvents,
    SearchError,
)
from pio_tpu.storage.registry import Storage


def T(h, m=0):
    return dt.datetime(2026, 3, 1, h, m, tzinfo=dt.timezone.utc)


def ev(name, t, eid="u1", props=None, target=None):
    return Event(
        name, "user", eid,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=props or {}, event_time=t,
    )


@pytest.fixture()
def client(tmp_path):
    return SearchableClient(str(tmp_path / "search.db"))


class TestEventSearch:
    def test_match_terms_and_properties(self, client):
        events = SearchableEvents(client)
        events.insert(ev("rate", T(1), props={"genre": "scifi thriller"}), 7)
        events.insert(ev("rate", T(2), props={"genre": "romance"}), 7)
        events.insert(ev("buy", T(3), target="i9"), 7)

        got = events.search(7, "scifi")
        assert len(got) == 1 and got[0].properties["genre"].startswith("scifi")
        # property KEYS are terms too (JSON text is tokenized)
        assert len(events.search(7, "genre")) == 2
        # entity/target ids are searchable
        assert events.search(7, "i9")[0].event == "buy"

    def test_boolean_and_prefix_queries(self, client):
        events = SearchableEvents(client)
        events.insert(ev("rate", T(1), props={"tag": "alpha beta"}), 1)
        events.insert(ev("rate", T(2), props={"tag": "alpha gamma"}), 1)
        assert len(events.search(1, "alpha AND gamma")) == 1
        assert len(events.search(1, "alpha NOT gamma")) == 1
        assert len(events.search(1, "gam*")) == 1

    def test_scoped_by_app_and_channel(self, client):
        events = SearchableEvents(client)
        events.insert(ev("rate", T(1), props={"k": "needle"}), 1)
        events.insert(ev("rate", T(1), props={"k": "needle"}), 2)
        events.insert(ev("rate", T(1), props={"k": "needle"}), 1,
                      channel_id=5)
        assert len(events.search(1, "needle")) == 1
        assert len(events.search(1, "needle", channel_id=5)) == 1
        assert len(events.search(2, "needle")) == 1
        assert len(events.search(3, "needle")) == 0

    def test_index_follows_delete_and_upsert(self, client):
        events = SearchableEvents(client)
        eid = events.insert(ev("rate", T(1), props={"k": "original"}), 1)
        assert len(events.search(1, "original")) == 1
        # upsert same id: old body must leave the index (REPLACE path)
        events.insert(
            Event("rate", "user", "u1", properties={"k": "replaced"},
                  event_time=T(2), event_id=eid), 1,
        )
        assert len(events.search(1, "original")) == 0
        assert len(events.search(1, "replaced")) == 1
        events.delete(eid, 1)
        assert len(events.search(1, "replaced")) == 0

    def test_index_follows_bulk_remove(self, client):
        events = SearchableEvents(client)
        for k in range(4):
            events.insert(ev("rate", T(k + 1), props={"k": "bulk"}), 1)
        events.remove(1)
        assert len(events.search(1, "bulk")) == 0

    def test_limit_and_rank_order(self, client):
        events = SearchableEvents(client)
        # one strongly-matching doc (term twice) and weaker ones
        events.insert(ev("rate", T(1), props={"a": "zed zed"}), 1)
        for k in range(3):
            events.insert(
                ev("rate", T(k + 2), props={"a": "zed filler extra"}), 1
            )
        got = events.search(1, "zed", limit=2)
        assert len(got) == 2
        assert got[0].properties["a"] == "zed zed"  # best BM25 first

    def test_bad_query_raises_search_error(self, client):
        events = SearchableEvents(client)
        events.insert(ev("rate", T(1)), 1)
        with pytest.raises(SearchError):
            events.search(1, 'AND AND (')
        # ES-style field:term naming a non-column is a bad query too
        with pytest.raises(SearchError):
            events.search(1, "status:FAILED")

    def test_rebuild_index_recovers_from_vacuum(self, tmp_path, client):
        """VACUUM may renumber the implicit rowids the FTS index is keyed
        on (counts still match, so the adoption guard can't see it);
        rebuild_index() is the documented recovery."""
        events = SearchableEvents(client)
        events.insert(ev("rate", T(1), props={"genre": "scifi"}), 7)
        events.insert(ev("buy", T(2), props={"genre": "romance"}), 7)
        eid = events.insert(ev("view", T(3), props={"genre": "western"}), 7)
        events.delete(eid, 7)  # leave a rowid hole for VACUUM to compact
        client.conn().commit()
        client.conn().execute("VACUUM")
        client.rebuild_index()
        got = events.search(7, "romance")
        assert len(got) == 1 and got[0].event == "buy"
        assert len(events.search(7, "western")) == 0
        assert len(events.search(7, "scifi")) == 1

    def test_sidechannel_writes_resync_on_open(self, tmp_path):
        """Rows deleted through a PLAIN sqlite client (no triggers) are
        purged from the index at the next searchable open — the two-way
        backfill converges instead of rescanning forever."""
        from pio_tpu.storage.sqlite import SQLiteClient, SQLiteEvents

        path = str(tmp_path / "side.db")
        sc = SearchableClient(path)
        events = SearchableEvents(sc)
        eid = events.insert(ev("rate", T(1), props={"k": "ghost"}), 1)
        events.insert(ev("rate", T(2), props={"k": "keeper"}), 1)
        sc.close()
        plain = SQLiteEvents(SQLiteClient(path))  # bypasses the triggers
        plain.delete(eid, 1)
        plain._c.close()
        events2 = SearchableEvents(SearchableClient(path))
        assert len(events2.search(1, "ghost")) == 0  # stale row purged
        assert len(events2.search(1, "keeper")) == 1


class TestMetaSearch:
    def test_apps(self, client):
        apps = SearchableApps(client)
        apps.insert(App(0, "shop", description="retail storefront events"))
        apps.insert(App(0, "news", description="article clicks"))
        assert apps.search("storefront")[0].name == "shop"
        assert apps.search("missingterm") == []

    def test_engine_instances(self, client):
        insts = SearchableEngineInstances(client)
        now = T(1)
        iid = insts.insert(EngineInstance(
            id="", status="COMPLETED", start_time=now, end_time=now,
            engine_id="reco", engine_version="1", engine_variant="v",
            engine_factory="templates.recommendation",
            algorithms_params='[{"name": "als", "rank": 16}]',
        ))
        insts.insert(EngineInstance(
            id="", status="FAILED", start_time=now, end_time=now,
            engine_id="cls", engine_version="1", engine_variant="v",
            engine_factory="templates.classification",
        ))
        got = insts.search("recommendation")
        assert [i.id for i in got] == [iid]
        # params JSON is searchable; so is status
        assert insts.search("als")[0].id == iid
        assert insts.search("FAILED")[0].engine_id == "cls"
        # index follows update()
        rec = insts.get(iid)
        import dataclasses

        insts.update(dataclasses.replace(rec, status="DELETED"))
        assert insts.search("recommendation AND DELETED")[0].id == iid

    def test_evaluation_instances(self, client):
        evals = SearchableEvaluationInstances(client)
        now = T(2)
        iid = evals.insert(EvaluationInstance(
            id="", status="EVALCOMPLETED", start_time=now, end_time=now,
            evaluation_class="PrecisionEval",
            evaluator_results="precision at ten 0.42",
        ))
        assert evals.search("precision")[0].id == iid


class TestAdoptionAndRegistry:
    def test_adopts_plain_sqlite_file(self, tmp_path):
        """Opening an existing plain-sqlite db backfills the FTS index."""
        from pio_tpu.storage.sqlite import SQLiteClient, SQLiteEvents

        path = str(tmp_path / "adopt.db")
        plain = SQLiteEvents(SQLiteClient(path))
        plain.insert(ev("rate", T(1), props={"k": "preexisting"}), 1)
        plain._c.close()

        events = SearchableEvents(SearchableClient(path))
        assert len(events.search(1, "preexisting")) == 1

    def test_upgrade_surface_sees_searchable(self, tmp_home, monkeypatch):
        """`pio upgrade` (Storage.sqlite_clients) must migrate the
        ES-analog's db too — it rides the same schema ladder."""
        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "ES")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_ES_TYPE", "searchable")
        Storage.reset()
        try:
            clients = Storage.sqlite_clients()
            assert "METADATA" in clients
            assert isinstance(clients["METADATA"], SearchableClient)
        finally:
            Storage.reset()

    def test_upgrade_verb_rebuilds_search_index(self, tmp_home,
                                                monkeypatch, capsys):
        """`pio upgrade --rebuild-search-index` is the CLI recovery path
        after an out-of-band VACUUM."""
        from pio_tpu.tools.cli import main as cli_main

        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "ES")
        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "ES")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_ES_TYPE", "searchable")
        Storage.reset()
        try:
            events = Storage.get_levents()
            events.insert(ev("rate", T(1), props={"k": "needle"}), 3)
            rc = cli_main(["upgrade", "--rebuild-search-index"])
            assert rc == 0
            out = capsys.readouterr().out
            assert "FTS index rebuilt" in out
            # rebuilt index still serves correct search results
            assert len(events.search(3, "needle")) == 1
        finally:
            Storage.reset()

    def test_concurrent_adoption_race_is_safe(self, tmp_path):
        """Two clients adopting the same plain file must not collide on
        duplicate FTS rowids (INSERT OR IGNORE backfill)."""
        from pio_tpu.storage.sqlite import SQLiteClient, SQLiteEvents

        path = str(tmp_path / "race.db")
        plain = SQLiteEvents(SQLiteClient(path))
        plain.insert(ev("rate", T(1), props={"k": "racer"}), 1)
        plain._c.close()
        a = SearchableClient(path)
        b = SearchableClient(path)  # second adoption: backfill is a no-op
        assert len(SearchableEvents(b).search(1, "racer")) == 1
        a.close()
        b.close()

    def test_registry_env_wiring_and_alias(self, tmp_home, monkeypatch):
        """TYPE=elasticsearch selects the analog; all three repos served."""
        for repo in ("METADATA", "EVENTDATA", "MODELDATA"):
            monkeypatch.setenv(
                f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE", "ES"
            )
        monkeypatch.setenv("PIO_STORAGE_SOURCES_ES_TYPE", "elasticsearch")
        monkeypatch.setenv(
            "PIO_STORAGE_SOURCES_ES_PATH", str(tmp_home / "es.db")
        )
        Storage.reset()
        try:
            le = Storage.get_levents()
            le.insert(ev("rate", T(1), props={"k": "wired"}), 3)
            assert len(le.search(3, "wired")) == 1
            apps = Storage.get_meta_data_apps()
            apps.insert(App(0, "esapp", description="searchable wiring"))
            assert apps.search("wiring")[0].name == "esapp"
            # PEvents + Models ride the same file
            assert len(Storage.get_pevents().find(3)) == 1
            from pio_tpu.storage.records import Model

            Storage.get_model_data_models().insert(Model("m1", b"blob"))
            assert Storage.get_model_data_models().get("m1").models == b"blob"
            checks = Storage.verify_all_data_objects()
            assert all(checks.values()), checks
        finally:
            Storage.reset()
