"""Contract extraction + drift rules (ISSUE 20): bad/clean fixture
pairs per rule, the real-tree guards (the /fleet.json producer must
cover every scraper read; the knob registry must round-trip every
swept reader), and the ``--dump-contracts`` CLI surface.
"""

import json
import textwrap

import pytest

from pio_tpu.analysis.contracts import get_contracts
from pio_tpu.analysis.core import (
    Finding,
    LintContext,
    collect_files,
    parse_module,
    run_lint,
)
from pio_tpu.utils.knobs import KNOBS, Knob


def lint_files(tmp_path, files, *, rules, knob_registry=None,
               repo_root=None):
    paths = []
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        paths.append(str(p))
    return run_lint(paths, rule_ids=rules,
                    knob_registry=knob_registry,
                    repo_root=repo_root or str(tmp_path))


# ------------------------------------------------------- endpoint-drift
_PRODUCER = """
    # pio: endpoint=/thing.json
    def build():
        return {"alpha": 1, "beta": {"gamma": 2}}
    """


class TestEndpointDrift:
    def test_missing_key_is_a_finding_with_suggestion(self, tmp_path):
        findings = lint_files(tmp_path, {
            "prod.py": _PRODUCER,
            "cons.py": """
                def scrape(http):
                    pay = http("http://h:1/thing.json")
                    return pay["delta"]
                """,
        }, rules=["endpoint-drift"])
        assert len(findings) == 1
        f = findings[0]
        assert f.rule == "endpoint-drift" and f.path.endswith("cons.py")
        assert "'delta'" in f.message
        assert "prod.py" in f.message          # names the producer
        assert "closest produced key" in f.message

    def test_produced_keys_read_clean(self, tmp_path):
        findings = lint_files(tmp_path, {
            "prod.py": _PRODUCER,
            "cons.py": """
                def scrape(http):
                    pay = http("http://h:1/thing.json")
                    return pay["alpha"], pay["beta"]["gamma"]
                """,
        }, rules=["endpoint-drift"])
        assert findings == []

    def test_consumes_marker_seeds_the_parameter(self, tmp_path):
        findings = lint_files(tmp_path, {
            "prod.py": _PRODUCER,
            "cons.py": """
                # pio: consumes=/thing.json
                def ingest(payload):
                    return payload["omega"]
                """,
        }, rules=["endpoint-drift"])
        assert len(findings) == 1
        assert "'omega'" in findings[0].message

    def test_wildcard_producer_grants_unknown_keys(self, tmp_path):
        findings = lint_files(tmp_path, {
            "prod.py": """
                # pio: endpoint=/dyn.json
                def build(names):
                    return {n: 0 for n in names}
                """,
            "cons.py": """
                def scrape(http):
                    pay = http("http://h:1/dyn.json")
                    return pay["anything"]
                """,
        }, rules=["endpoint-drift"])
        assert findings == []


# --------------------------------------------------------- header-drift
class TestHeaderDrift:
    def test_consume_only_header_is_a_finding(self, tmp_path):
        findings = lint_files(tmp_path, {
            "handler.py": """
                def handler(req):
                    return req.get("X-Pio-Widget-Count")
                """,
        }, rules=["header-drift"])
        assert len(findings) == 1
        assert "never produced" in findings[0].message

    def test_produce_only_header_is_a_finding(self, tmp_path):
        findings = lint_files(tmp_path, {
            "emit.py": """
                def emit(resp):
                    resp.send_header("X-Pio-Widget-Count", "3")
                """,
        }, rules=["header-drift"])
        assert len(findings) == 1
        assert "never consumed" in findings[0].message

    def test_both_sides_clean(self, tmp_path):
        findings = lint_files(tmp_path, {
            "emit.py": """
                def emit(resp):
                    resp.send_header("X-Pio-Widget-Count", "3")
                """,
            "handler.py": """
                def handler(req):
                    return req.get("X-Pio-Widget-Count")
                """,
        }, rules=["header-drift"])
        assert findings == []


# --------------------------------------------------- knob-default-drift
_FIXTURE_REGISTRY = {
    "PIO_TPU_WIDGETS": Knob("PIO_TPU_WIDGETS", "int", 4, "fixture"),
}


class TestKnobDefaultDrift:
    def test_bypass_with_disagreeing_default(self, tmp_path):
        findings = lint_files(tmp_path, {
            "reader.py": """
                from pio_tpu.utils.envutil import env_int

                def n():
                    return env_int("PIO_TPU_WIDGETS", 9)
                """,
        }, rules=["knob-default-drift"],
            knob_registry=_FIXTURE_REGISTRY)
        assert len(findings) == 1
        msg = findings[0].message
        assert "bypasses the knob registry" in msg
        assert "9" in msg and "4" in msg      # both defaults named

    def test_undeclared_name_is_a_finding(self, tmp_path):
        findings = lint_files(tmp_path, {
            "reader.py": """
                import os

                def n():
                    return os.environ.get("PIO_TPU_MYSTERY", "x")
                """,
        }, rules=["knob-default-drift"],
            knob_registry=_FIXTURE_REGISTRY)
        assert len(findings) == 1
        assert "undeclared" in findings[0].message

    def test_registry_read_is_clean(self, tmp_path):
        findings = lint_files(tmp_path, {
            "reader.py": """
                from pio_tpu.utils import knobs

                def n():
                    return knobs.knob_int("PIO_TPU_WIDGETS")
                """,
        }, rules=["knob-default-drift"],
            knob_registry=_FIXTURE_REGISTRY)
        assert findings == []

    def test_registry_read_of_undeclared_name(self, tmp_path):
        findings = lint_files(tmp_path, {
            "reader.py": """
                from pio_tpu.utils import knobs

                def n():
                    return knobs.knob_int("PIO_TPU_NOT_DECLARED")
                """,
        }, rules=["knob-default-drift"],
            knob_registry=_FIXTURE_REGISTRY)
        assert len(findings) == 1
        assert "never declared" in findings[0].message


# ------------------------------------------------------- knob-doc-drift
def _doc_repo(tmp_path, row):
    (tmp_path / "docs").mkdir(exist_ok=True)
    (tmp_path / "docs" / "operations.md").write_text(
        "# ops\n\n| Knob | Kind | Default | What it does |\n"
        "|---|---|---|---|\n" + row + "\n"
    )


class TestKnobDocDrift:
    def test_wrong_documented_default(self, tmp_path):
        _doc_repo(tmp_path,
                  "| `PIO_TPU_WIDGETS` | int | `9` | fixture |")
        findings = lint_files(tmp_path, {"mod.py": "x = 1\n"},
                              rules=["knob-doc-drift"],
                              knob_registry=_FIXTURE_REGISTRY)
        assert len(findings) == 1
        assert "documented default `9` disagrees" in findings[0].message

    def test_missing_and_stale_rows(self, tmp_path):
        _doc_repo(tmp_path,
                  "| `PIO_TPU_GONE` | int | `1` | removed long ago |")
        findings = lint_files(tmp_path, {"mod.py": "x = 1\n"},
                              rules=["knob-doc-drift"],
                              knob_registry=_FIXTURE_REGISTRY)
        msgs = "\n".join(f.message for f in findings)
        assert len(findings) == 2
        assert "missing from the docs" in msgs      # PIO_TPU_WIDGETS
        assert "does not exist in the registry" in msgs  # PIO_TPU_GONE

    def test_matching_table_is_clean(self, tmp_path):
        _doc_repo(tmp_path,
                  "| `PIO_TPU_WIDGETS` | int | `4` | fixture |")
        findings = lint_files(tmp_path, {"mod.py": "x = 1\n"},
                              rules=["knob-doc-drift"],
                              knob_registry=_FIXTURE_REGISTRY)
        assert findings == []


# --------------------------------------------------- failpoint-coverage
_FAILPOINT_MOD = """
    from pio_tpu.faults import failpoint

    def work():
        failpoint("fixture.widget.spin")
    """


class TestFailpointCoverage:
    def test_unarmed_failpoint_is_a_finding(self, tmp_path):
        findings = lint_files(tmp_path, {
            "widget.py": _FAILPOINT_MOD,
            "test_widget.py": """
                def test_nothing():
                    assert True
                """,
        }, rules=["failpoint-coverage"])
        assert len(findings) == 1
        assert "fixture.widget.spin" in findings[0].message
        assert "never armed" in findings[0].message

    def test_armed_by_test_string_is_clean(self, tmp_path):
        findings = lint_files(tmp_path, {
            "widget.py": _FAILPOINT_MOD,
            "test_widget.py": """
                def test_chaos(faults):
                    faults.install("fixture.widget.spin=error")
                """,
        }, rules=["failpoint-coverage"])
        assert findings == []

    def test_production_slice_proves_nothing(self, tmp_path):
        # no test modules in view → absence of arming is not evidence
        findings = lint_files(tmp_path, {
            "widget.py": _FAILPOINT_MOD,
        }, rules=["failpoint-coverage"])
        assert findings == []


# ------------------------------------------------------ real-tree guards
@pytest.fixture(scope="module")
def tree_contracts():
    files = collect_files(["pio_tpu", "tests"])
    mods = [m for m in (parse_module(f) for f in files)
            if not isinstance(m, Finding)]
    return get_contracts(mods, LintContext())


class TestRealTreeGuards:
    def test_fleet_producer_covers_every_scraper_read(
            self, tree_contracts):
        c = tree_contracts
        keys = c.keys.get("/fleet.json", set())
        assert len(keys) > 20, "fleet payload key tree looks truncated"
        reads = [r for r in c.reads if r.endpoint == "/fleet.json"]
        assert reads, "no /fleet.json consumer chains extracted"
        for r in reads:
            for seg in r.key.split("."):
                assert seg in keys or "*" in keys, (
                    f"{r.path}:{r.line} reads {r.key!r} but the fleet "
                    f"producer never writes {seg!r}"
                )

    def test_registry_round_trips_every_swept_reader(
            self, tree_contracts):
        for site in tree_contracts.knob_reads:
            if site.is_test or site.via != "registry":
                continue
            assert site.name in KNOBS, (
                f"{site.path}:{site.line} reads {site.name} through "
                f"the registry helpers but the registry never "
                f"declares it"
            )

    def test_every_knob_has_exactly_one_canonical_default(self):
        # frozen dataclass + one declaration tuple: names are unique
        names = [k for k in KNOBS]
        assert len(names) == len(set(names))
        for knob in KNOBS.values():
            assert knob.kind in ("int", "float", "str")
            assert isinstance(knob.doc, str) and knob.doc

    def test_headers_all_flow_both_ways(self, tree_contracts):
        produced = {h.header for h in tree_contracts.headers
                    if h.role == "write"}
        consumed = {h.header for h in tree_contracts.headers
                    if h.role == "read"}
        # the forwarding prefix constant declares, it doesn't flow
        assert consumed - {"x-pio-"} <= produced


# ------------------------------------------------------------------- CLI
class TestDumpContractsCLI:
    def test_dump_contracts_payload(self, capsys):
        from pio_tpu.tools.cli import build_parser

        args = build_parser().parse_args(
            ["lint", "--dump-contracts", "pio_tpu/utils"]
        )
        assert args.fn(args) == 0
        payload = json.loads(capsys.readouterr().out)
        assert set(payload) == {"endpoints", "headers", "knobs"}
        # the registry is always joined in, even over a narrow slice
        assert "PIO_TPU_HTTP_FRONT" in payload["knobs"]
        assert payload["knobs"]["PIO_TPU_HTTP_FRONT"]["default"] == \
            "threaded"
