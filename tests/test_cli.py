"""CLI tests (reference console arg-parsing tier + quickstart flow pieces).

Run commands in-process via main(argv) against isolated storage.
"""

import datetime as dt
import json

import pytest

import pio_tpu.templates  # noqa: F401
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.storage import Storage
from pio_tpu.tools.cli import main


@pytest.fixture(autouse=True)
def isolated(tmp_home):
    Storage.reset()
    yield
    Storage.reset()


def run(capsys, *argv):
    code = main(list(argv))
    out = capsys.readouterr()
    return code, out.out, out.err


class TestAppVerbs:
    def test_app_lifecycle(self, capsys):
        code, out, _ = run(capsys, "app", "new", "shop")
        assert code == 0 and "Access key:" in out
        key = out.split("Access key:")[1].strip()

        code, out, _ = run(capsys, "app", "list")
        assert "name=shop" in out and key in out

        code, out, _ = run(capsys, "accesskey", "new", "shop", "--events", "rate,buy")
        assert code == 0

        code, out, _ = run(capsys, "accesskey", "list", "shop")
        assert out.count("key=") == 2 and "events=rate,buy" in out

        code, out, _ = run(capsys, "app", "channel-new", "shop", "mobile")
        assert code == 0

        code, out, err = run(capsys, "app", "channel-new", "shop", "bad name")
        assert code == 1 and "channel" in err

        code, _, _ = run(capsys, "app", "delete", "shop")
        assert code == 0
        code, out, _ = run(capsys, "app", "list")
        assert "shop" not in out

    def test_duplicate_app(self, capsys):
        run(capsys, "app", "new", "shop")
        code, _, err = run(capsys, "app", "new", "shop")
        assert code == 1 and "already exists" in err

    def test_data_delete(self, capsys):
        run(capsys, "app", "new", "shop")
        app = Storage.get_meta_data_apps().get_by_name("shop")
        Storage.get_levents().insert(Event("rate", "user", "u1"), app.id)
        assert len(Storage.get_levents().find(app.id)) == 1
        code, _, _ = run(capsys, "app", "data-delete", "shop")
        assert code == 0
        assert Storage.get_levents().find(app.id) == []


class TestStatusVersion:
    def test_version(self, capsys):
        code, out, _ = run(capsys, "version")
        assert code == 0 and out.strip()

    def test_status(self, capsys):
        code, out, _ = run(capsys, "status")
        assert code == 0
        assert "sanity check passed" in out
        assert out.count("OK ") >= 7


class TestTrainDeployFlow:
    def _seed(self, capsys, tmp_path):
        run(capsys, "app", "new", "cli-test")
        app = Storage.get_meta_data_apps().get_by_name("cli-test")
        lines = []
        t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
        for u in range(8):
            for i in range(6):
                rating = 5.0 if (u < 4) == (i < 3) else 1.0
                lines.append(json.dumps({
                    "event": "rate", "entityType": "user", "entityId": f"u{u}",
                    "targetEntityType": "item", "targetEntityId": f"i{i}",
                    "properties": {"rating": rating},
                    "eventTime": t0.isoformat(),
                }))
        events_file = tmp_path / "events.jsonl"
        events_file.write_text("\n".join(lines) + "\nnot json\n")
        engine_json = tmp_path / "engine.json"
        engine_json.write_text(json.dumps({
            "id": "cli-rec",
            "engineFactory": "templates.recommendation",
            "datasource": {"params": {"app_name": "cli-test"}},
            "algorithms": [{"name": "als", "params":
                            {"rank": 4, "num_iterations": 6, "lambda_": 0.1}}],
        }))
        return app, events_file, engine_json

    def test_import_train_batchpredict_export(self, capsys, tmp_path):
        app, events_file, engine_json = self._seed(capsys, tmp_path)

        code, out, _ = run(capsys, "import", "--app", "cli-test",
                           "--input", str(events_file))
        assert code == 1  # one bad line
        assert "Imported 48 events (1 failed)" in out

        code, out, _ = run(capsys, "train", "--engine-json", str(engine_json))
        assert code == 0 and "Training completed" in out

        queries = tmp_path / "queries.jsonl"
        queries.write_text(
            json.dumps({"user": "u1", "num": 2}) + "\n"
            + json.dumps({"user": "ghost"}) + "\n"
            + "{bad json\n"
        )
        out_file = tmp_path / "preds.jsonl"
        code, out, _ = run(
            capsys, "batchpredict", "--engine-json", str(engine_json),
            "--input", str(queries), "--output", str(out_file),
        )
        assert code == 0 and "2 queries" in out
        lines = [json.loads(l) for l in out_file.read_text().splitlines()]
        assert len(lines[0]["prediction"]["itemScores"]) == 2
        assert lines[1]["prediction"]["itemScores"] == []
        assert "error" in lines[2]

        export_file = tmp_path / "export.jsonl"
        code, out, _ = run(capsys, "export", "--app", "cli-test",
                           "--output", str(export_file))
        assert code == 0 and "Exported 48" in out
        assert len(export_file.read_text().splitlines()) == 48

    def test_train_stop_after_read(self, capsys, tmp_path):
        app, events_file, engine_json = self._seed(capsys, tmp_path)
        run(capsys, "import", "--app", "cli-test", "--input", str(events_file))
        code, out, _ = run(capsys, "train", "--engine-json", str(engine_json),
                           "--stop-after-read")
        assert code == 0

    def test_train_missing_engine_json(self, capsys):
        with pytest.raises(Exception):
            run(capsys, "train", "--engine-json", "/nope/engine.json")

    def test_undeploy_unreachable(self, capsys):
        code, _, err = run(capsys, "undeploy", "--port", "59999")
        assert code == 1 and "cannot reach" in err


class TestRunVerb:
    def test_run_calls_target_with_args(self, tmp_path, monkeypatch):
        import sys

        mod = tmp_path / "userjob.py"
        mod.write_text(
            "def main(argv):\n"
            "    print('JOB', argv)\n"
            "    return 0 if argv == ['a', 'b'] else 3\n"
            "def noargs():\n"
            "    print('NOARGS')\n"
        )
        monkeypatch.syspath_prepend(str(tmp_path))
        sys.modules.pop("userjob", None)
        from pio_tpu.tools.cli import main

        assert main(["run", "userjob:main", "a", "b"]) == 0
        assert main(["run", "userjob:main", "x"]) == 3
        assert main(["run", "userjob:noargs"]) == 0
        # flag-like passthrough needs no -- separator (REMAINDER)
        assert main(["run", "userjob:main", "--flag", "v"]) == 3
        # args to a no-arg target is an error, not silent discard
        assert main(["run", "userjob:noargs", "oops"]) == 1

    def test_run_rejects_non_callable(self, tmp_path, monkeypatch):
        import sys

        (tmp_path / "userdata.py").write_text("VALUE = 7\n")
        monkeypatch.syspath_prepend(str(tmp_path))
        sys.modules.pop("userdata", None)
        from pio_tpu.tools.cli import main

        assert main(["run", "userdata:VALUE"]) == 1


def test_deploy_workers_flags_parse():
    """`deploy --workers N --device-worker` must parse (the pool branch
    of cmd_deploy keys off these; pool behavior itself is covered by
    tests/test_worker_pool.py)."""
    from pio_tpu.tools.cli import build_parser

    p = build_parser()
    args = p.parse_args(
        ["deploy", "--workers", "4", "--device-worker", "--port", "8123"]
    )
    assert args.workers == 4 and args.device_worker is True
    assert args.port == 8123
    # default stays single-process
    args = p.parse_args(["deploy"])
    assert args.workers == 1 and args.device_worker is False
