"""Content-addressed blob Models store (pio_tpu/storage/blobstore.py).

The Models-trait conformance runs in tests/test_storage.py (the backend is
a parameterized fixture there); this file covers the content-addressing
semantics that make it the HDFS/S3 slot: dedupe, digest verification,
ref-count garbage collection, and the URI-scheme registry.
"""

import pytest

from pio_tpu.storage.base import StorageError
from pio_tpu.storage.blobstore import (
    BlobModels,
    FileBlobBackend,
    open_blob_backend,
    register_blob_scheme,
)
from pio_tpu.storage.records import Model


@pytest.fixture()
def store(tmp_path):
    return BlobModels(FileBlobBackend(str(tmp_path / "blobs")))


def test_identical_models_dedupe(store, tmp_path):
    store.insert(Model("a", b"same-bytes"))
    store.insert(Model("b", b"same-bytes"))
    backend = store._b
    objects = [k for k in backend.list("objects")]
    assert len(objects) == 1  # one blob, two refs
    assert store.get("a").models == b"same-bytes"
    assert store.get("b").models == b"same-bytes"


def test_reinsert_replaces_pointer(store):
    store.insert(Model("m", b"v1"))
    store.insert(Model("m", b"v2"))
    assert store.get("m").models == b"v2"


def test_gc_keeps_shared_blob(store):
    store.insert(Model("a", b"shared"))
    store.insert(Model("b", b"shared"))
    assert store.delete("a")
    assert store.get("b").models == b"shared"  # blob survives b's ref
    assert store.delete("b")
    assert store._b.list("objects") == []  # last ref gone → object gc'd


def test_delete_missing_is_false(store):
    assert store.delete("nope") is False


def test_slash_and_underscore_ids_do_not_collide(store):
    store.insert(Model("a/b", b"slash"))
    store.insert(Model("a_b", b"under"))
    assert store.get("a/b").models == b"slash"
    assert store.get("a_b").models == b"under"


def test_overwrite_gcs_old_object(store):
    store.insert(Model("m", b"v1"))
    store.insert(Model("m", b"v2"))
    assert len(store._b.list("objects")) == 1  # v1's blob reclaimed
    store.delete("m")
    assert store._b.list("objects") == []


def test_corrupt_object_detected(store, tmp_path):
    store.insert(Model("m", b"payload"))
    # flip bytes in the stored object behind the store's back
    (obj,) = store._b.list("objects")
    store._b.put(obj, b"tampered")
    with pytest.raises(StorageError, match="digest mismatch"):
        store.get("m")


def test_missing_object_detected(store):
    store.insert(Model("m", b"payload"))
    (obj,) = store._b.list("objects")
    store._b.delete(obj)
    with pytest.raises(StorageError, match="missing"):
        store.get("m")


def test_key_escape_rejected(tmp_path):
    b = FileBlobBackend(str(tmp_path / "root"))
    with pytest.raises(StorageError, match="escapes"):
        b.put("../outside", b"x")


def test_uri_scheme_registry(tmp_path):
    # file:// and bare paths resolve to the file backend
    m = BlobModels(open_blob_backend("file://" + str(tmp_path / "b1")))
    m.insert(Model("x", b"1"))
    assert m.get("x").models == b"1"
    m2 = BlobModels(open_blob_backend(str(tmp_path / "b2")))
    m2.insert(Model("y", b"2"))
    assert m2.get("y").models == b"2"
    # unregistered scheme: actionable error
    with pytest.raises(StorageError, match="no blob backend registered"):
        open_blob_backend("gs://bucket/prefix")
    # a third-party scheme plugs in without touching BlobModels
    register_blob_scheme(
        "memtest", lambda loc: FileBlobBackend(str(tmp_path / "m" / loc))
    )
    m3 = BlobModels(open_blob_backend("memtest://ns1"))
    m3.insert(Model("z", b"3"))
    assert m3.get("z").models == b"3"


@pytest.fixture()
def blob_daemon(tmp_path):
    """In-process blob daemon on a loopback port."""
    from pio_tpu.server.blob_server import create_blob_server

    server = create_blob_server(
        str(tmp_path / "served"), host="127.0.0.1", port=0
    )
    server.start()
    yield f"http://127.0.0.1:{server.port}"
    server.stop()


class TestHTTPBlobScheme:
    """The in-tree REMOTE backend: model bytes cross a real socket."""

    def test_backend_roundtrip_over_socket(self, blob_daemon):
        b = open_blob_backend(blob_daemon)
        assert b.get("objects/aa/deadbeef") is None
        assert not b.exists("objects/aa/deadbeef")
        payload = bytes(range(256)) * 17  # binary, non-UTF8
        b.put("objects/aa/deadbeef", payload)
        assert b.exists("objects/aa/deadbeef")
        assert b.get("objects/aa/deadbeef") == payload
        b.put("refs/m%2Fslash", b"deadbeef")  # %-escaped key survives
        assert b.get("refs/m%2Fslash") == b"deadbeef"
        assert sorted(b.list("")) == [
            "objects/aa/deadbeef", "refs/m%2Fslash"
        ]
        assert b.list("refs") == ["refs/m%2Fslash"]
        assert b.delete("objects/aa/deadbeef")
        assert not b.delete("objects/aa/deadbeef")
        assert b.get("objects/aa/deadbeef") is None

    def test_models_trait_over_http(self, blob_daemon):
        """Full BlobModels semantics (dedupe, digest verify, gc) with the
        object store behind a socket."""
        m = BlobModels(open_blob_backend(blob_daemon))
        m.insert(Model("inst/1", b"weights-v1"))
        m.insert(Model("other", b"weights-v1"))  # dedupe across the wire
        assert m.get("inst/1").models == b"weights-v1"
        backend = m._b
        assert len(backend.list("objects")) == 1
        m.insert(Model("inst/1", b"weights-v2"))  # overwrite + gc check
        assert m.get("inst/1").models == b"weights-v2"
        assert len(backend.list("objects")) == 2  # v1 still ref'd by other
        assert m.delete("other")
        assert len(backend.list("objects")) == 1  # v1 gc'd
        assert m.get("other") is None

    def test_access_key_required(self, tmp_path):
        from pio_tpu.server.blob_server import create_blob_server

        server = create_blob_server(
            str(tmp_path / "s"), host="127.0.0.1", port=0,
            access_key="sekrit",
        )
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            with pytest.raises(StorageError, match="HTTP 401"):
                open_blob_backend(url).put("k", b"x")
            b = open_blob_backend(f"{url}?accessKey=sekrit")
            b.put("k", b"x")
            assert b.get("k") == b"x"
        finally:
            server.stop()

    def test_keepalive_connection_framing(self, blob_daemon):
        """Many requests on ONE persistent HTTP/1.1 connection — a HEAD
        response that wrote body bytes (or unflushed buffered output)
        would desync every subsequent response on the socket."""
        import http.client
        from urllib.parse import urlsplit

        host, port = urlsplit(blob_daemon).netloc.split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        try:
            payload = b"\x00\xffkeepalive"
            conn.request("PUT", "/blobs/objects/k1", body=payload,
                         headers={"Content-Type":
                                  "application/octet-stream"})
            r = conn.getresponse()
            r.read()
            assert r.status == 201
            for _ in range(3):  # HEAD hit + miss, then a real GET
                conn.request("HEAD", "/blobs/objects/k1")
                r = conn.getresponse()
                assert r.read() == b"" and r.status == 200
                conn.request("HEAD", "/blobs/objects/absent")
                r = conn.getresponse()
                assert r.read() == b"" and r.status == 404
                conn.request("GET", "/blobs/objects/k1")
                r = conn.getresponse()
                assert r.status == 200 and r.read() == payload
        finally:
            conn.close()

    def test_large_blob_streams_exact_bytes(self, blob_daemon):
        """Multi-MB GET rides the FileResponse streaming path (constant
        memory); framing must stay exact on a keep-alive connection."""
        import hashlib
        import http.client
        from urllib.parse import urlsplit

        payload = bytes(range(256)) * 32768  # 8 MiB, binary
        b = open_blob_backend(blob_daemon)
        b.put("objects/big", payload)
        host, port = urlsplit(blob_daemon).netloc.split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=30)
        try:
            for _ in range(2):  # twice on one connection: framing holds
                conn.request("GET", "/blobs/objects/big")
                r = conn.getresponse()
                got = r.read()
                assert r.status == 200
                assert int(r.headers["Content-Length"]) == len(payload)
                assert hashlib.sha256(got).hexdigest() == \
                    hashlib.sha256(payload).hexdigest()
        finally:
            conn.close()

    def test_large_put_spools_exact_bytes(self, blob_daemon):
        """A PUT bigger than the in-memory spool threshold streams
        through a temp file (never fully buffered) and must land
        byte-identical."""
        import hashlib

        payload = bytes(range(256)) * 65536  # 16 MiB > 8 MiB spool cutoff
        b = open_blob_backend(blob_daemon)
        b.put("objects/hugeput", payload)
        got = b.get("objects/hugeput")
        assert len(got) == len(payload)
        assert hashlib.sha256(got).hexdigest() == \
            hashlib.sha256(payload).hexdigest()

    def test_oversize_body_rejected_413(self, blob_daemon, monkeypatch):
        import http.client
        from urllib.parse import urlsplit

        import pio_tpu.server.http as http_mod

        monkeypatch.setattr(http_mod, "MAX_BODY_MB", 1.0)
        host, port = urlsplit(blob_daemon).netloc.split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        try:
            # the server rejects on Content-Length alone and closes
            # without draining; a reset mid-upload is also a rejection
            conn.request(
                "PUT", "/blobs/objects/toolarge", body=b"x" * (2 << 20),
                headers={"Content-Type": "application/octet-stream"},
            )
            r = conn.getresponse()
            assert r.status == 413
            r.read()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            conn.close()
        # either way, nothing may have been stored
        b = open_blob_backend(blob_daemon)
        assert not b.exists("objects/toolarge")

    def test_truncated_put_rejected(self, blob_daemon):
        """A client dying mid-PUT (Content-Length > bytes sent) must not
        store a truncated artifact over a complete one."""
        import socket
        from urllib.parse import urlsplit

        b = open_blob_backend(blob_daemon)
        b.put("objects/tr", b"complete-artifact")
        host, port = urlsplit(blob_daemon).netloc.split(":")
        s = socket.create_connection((host, int(port)), timeout=10)
        try:
            s.sendall(
                b"PUT /blobs/objects/tr HTTP/1.1\r\n"
                b"Host: x\r\nContent-Type: application/octet-stream\r\n"
                b"Content-Length: 1000\r\n\r\n" + b"short"
            )
            s.shutdown(socket.SHUT_WR)  # die mid-body
            resp = s.recv(4096)
            assert b"400" in resp.split(b"\r\n", 1)[0], resp
        finally:
            s.close()
        assert b.get("objects/tr") == b"complete-artifact"

    def test_unauthenticated_put_rejected_before_body(self, tmp_path):
        """With an access key set, a bad-key octet-stream PUT is refused
        pre-body (the connection closes without the body being read)."""
        import socket

        from pio_tpu.server.blob_server import create_blob_server

        server = create_blob_server(
            str(tmp_path / "s"), host="127.0.0.1", port=0,
            access_key="sekrit",
        )
        server.start()
        try:
            s = socket.create_connection(
                ("127.0.0.1", server.port), timeout=10
            )
            try:
                # headers announce a large body; send none of it
                s.sendall(
                    b"PUT /blobs/objects/x HTTP/1.1\r\n"
                    b"Host: x\r\n"
                    b"Content-Type: application/octet-stream\r\n"
                    b"Content-Length: 104857600\r\n\r\n"
                )
                resp = s.recv(4096)  # 401 arrives despite no body sent
                assert b"401" in resp.split(b"\r\n", 1)[0], resp
            finally:
                s.close()
        finally:
            server.stop()

    def test_daemon_rejects_escaping_keys(self, blob_daemon):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"{blob_daemon}/blobs/..%2Foutside", data=b"x", method="PUT",
            headers={"Content-Type": "application/octet-stream"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400

    def test_registry_env_wiring_http(self, tmp_home, monkeypatch,
                                      blob_daemon):
        from pio_tpu.storage.registry import Storage

        monkeypatch.setenv(
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "RB"
        )
        monkeypatch.setenv("PIO_STORAGE_SOURCES_RB_TYPE", "blob")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_RB_PATH", blob_daemon)
        Storage.reset()
        try:
            models = Storage.get_model_data_models()
            models.insert(Model("inst1", b"remote-weights"))
            assert models.get("inst1").models == b"remote-weights"
        finally:
            Storage.reset()


def test_registry_env_wiring(tmp_home, monkeypatch):
    from pio_tpu.storage.registry import Storage

    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "BLOB")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_BLOB_TYPE", "blob")
    monkeypatch.setenv(
        "PIO_STORAGE_SOURCES_BLOB_PATH", "file://" + str(tmp_home / "mb")
    )
    Storage.reset()
    try:
        models = Storage.get_model_data_models()
        models.insert(Model("inst1", b"weights"))
        assert Storage.get_model_data_models().get("inst1").models == b"weights"
        assert (tmp_home / "mb" / "refs").exists()
    finally:
        Storage.reset()
