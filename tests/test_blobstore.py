"""Content-addressed blob Models store (pio_tpu/storage/blobstore.py).

The Models-trait conformance runs in tests/test_storage.py (the backend is
a parameterized fixture there); this file covers the content-addressing
semantics that make it the HDFS/S3 slot: dedupe, digest verification,
ref-count garbage collection, and the URI-scheme registry.
"""

import pytest

from pio_tpu.storage.base import StorageError
from pio_tpu.storage.blobstore import (
    BlobModels,
    FileBlobBackend,
    open_blob_backend,
    register_blob_scheme,
)
from pio_tpu.storage.records import Model


@pytest.fixture()
def store(tmp_path):
    return BlobModels(FileBlobBackend(str(tmp_path / "blobs")))


def test_identical_models_dedupe(store, tmp_path):
    store.insert(Model("a", b"same-bytes"))
    store.insert(Model("b", b"same-bytes"))
    backend = store._b
    objects = [k for k in backend.list("objects")]
    assert len(objects) == 1  # one blob, two refs
    assert store.get("a").models == b"same-bytes"
    assert store.get("b").models == b"same-bytes"


def test_reinsert_replaces_pointer(store):
    store.insert(Model("m", b"v1"))
    store.insert(Model("m", b"v2"))
    assert store.get("m").models == b"v2"


def test_gc_keeps_shared_blob(store):
    store.insert(Model("a", b"shared"))
    store.insert(Model("b", b"shared"))
    assert store.delete("a")
    assert store.get("b").models == b"shared"  # blob survives b's ref
    assert store.delete("b")
    assert store._b.list("objects") == []  # last ref gone → object gc'd


def test_delete_missing_is_false(store):
    assert store.delete("nope") is False


def test_slash_and_underscore_ids_do_not_collide(store):
    store.insert(Model("a/b", b"slash"))
    store.insert(Model("a_b", b"under"))
    assert store.get("a/b").models == b"slash"
    assert store.get("a_b").models == b"under"


def test_overwrite_gcs_old_object(store):
    store.insert(Model("m", b"v1"))
    store.insert(Model("m", b"v2"))
    assert len(store._b.list("objects")) == 1  # v1's blob reclaimed
    store.delete("m")
    assert store._b.list("objects") == []


def test_corrupt_object_detected(store, tmp_path):
    store.insert(Model("m", b"payload"))
    # flip bytes in the stored object behind the store's back
    (obj,) = store._b.list("objects")
    store._b.put(obj, b"tampered")
    with pytest.raises(StorageError, match="digest mismatch"):
        store.get("m")


def test_missing_object_detected(store):
    store.insert(Model("m", b"payload"))
    (obj,) = store._b.list("objects")
    store._b.delete(obj)
    with pytest.raises(StorageError, match="missing"):
        store.get("m")


def test_key_escape_rejected(tmp_path):
    b = FileBlobBackend(str(tmp_path / "root"))
    with pytest.raises(StorageError, match="escapes"):
        b.put("../outside", b"x")


def test_uri_scheme_registry(tmp_path):
    # file:// and bare paths resolve to the file backend
    m = BlobModels(open_blob_backend("file://" + str(tmp_path / "b1")))
    m.insert(Model("x", b"1"))
    assert m.get("x").models == b"1"
    m2 = BlobModels(open_blob_backend(str(tmp_path / "b2")))
    m2.insert(Model("y", b"2"))
    assert m2.get("y").models == b"2"
    # unregistered scheme: actionable error
    with pytest.raises(StorageError, match="no blob backend registered"):
        open_blob_backend("gs://bucket/prefix")
    # a third-party scheme plugs in without touching BlobModels
    register_blob_scheme(
        "memtest", lambda loc: FileBlobBackend(str(tmp_path / "m" / loc))
    )
    m3 = BlobModels(open_blob_backend("memtest://ns1"))
    m3.insert(Model("z", b"3"))
    assert m3.get("z").models == b"3"


def test_registry_env_wiring(tmp_home, monkeypatch):
    from pio_tpu.storage.registry import Storage

    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "BLOB")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_BLOB_TYPE", "blob")
    monkeypatch.setenv(
        "PIO_STORAGE_SOURCES_BLOB_PATH", "file://" + str(tmp_home / "mb")
    )
    Storage.reset()
    try:
        models = Storage.get_model_data_models()
        models.insert(Model("inst1", b"weights"))
        assert Storage.get_model_data_models().get("inst1").models == b"weights"
        assert (tmp_home / "mb" / "refs").exists()
    finally:
        Storage.reset()
