"""Admission control & QoS (ISSUE 3): spec grammar, token buckets
(including the pool-wide striped budget), concurrency limiting, circuit
breaking, deadlines, stale-cache degradation — plus the two servers'
behavior under synthetic overload: excess load must shed with 429/503 +
``Retry-After`` (or degrade to a marked stale 200) while the server
stays up and every rejection lands in ``pio_tpu_qos_shed_total``."""

import datetime as dt
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

import pio_tpu.templates  # noqa: F401  (registers the factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.obs import monotonic_s
from pio_tpu.data import Event
from pio_tpu.qos import (
    DEADLINE_HEADER,
    DEGRADED_HEADER,
    DEGRADED_VALUE,
    CircuitBreaker,
    ConcurrencyLimiter,
    Deadline,
    QoSError,
    QoSGate,
    QoSPolicy,
    StaleCache,
    TokenBucket,
    cache_key,
    parse_deadline_ms,
    parse_qos,
    policy_from_dict,
    priority_floor,
    resolve_policy,
)
from pio_tpu.server import create_event_server, create_query_server
from pio_tpu.storage import AccessKey, App, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


# -- policy / spec grammar ---------------------------------------------------


class TestParseQos:
    def test_issue_spec(self):
        p = parse_qos("rps=500,queue=64,deadline=100ms")
        assert p.rps == 500.0
        assert p.queue == 64
        assert p.deadline_ms == 100.0
        assert p.effective_burst() == 500.0  # default: one second of rps

    def test_all_keys(self):
        p = parse_qos(
            "rps=10,burst=20,key_rps=5,key_burst=7,inflight=4,queue=2,"
            "deadline=50ms,cache=128,fail_rate=0.3,fail_window=10,"
            "probes=2,cooldown=250ms"
        )
        assert (p.rps, p.burst, p.key_rps, p.key_burst) == (10, 20, 5, 7)
        assert (p.inflight, p.queue, p.cache) == (4, 2, 128)
        assert p.deadline_ms == 50.0
        assert (p.fail_rate, p.fail_window, p.probes) == (0.3, 10, 2)
        assert p.cooldown_s == pytest.approx(0.25)

    @pytest.mark.parametrize("bad", [
        "rps",                 # not key=value
        "turbo=9",             # unknown key
        "rps=-1",              # negative
        "queue=-5",
        "fail_rate=1.5",       # fraction > 1
        "deadline=banana",     # not a duration
        "rps=abc",
    ])
    def test_rejects(self, bad):
        with pytest.raises(QoSError):
            parse_qos(bad)

    def test_policy_from_dict(self):
        assert policy_from_dict({"spec": "rps=3"}).rps == 3.0
        p = policy_from_dict({"rps": 3, "queue": 2})
        assert (p.rps, p.queue) == (3, 2)
        with pytest.raises(QoSError):
            policy_from_dict({"nope": 1})

    def test_resolve_precedence(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_QOS", "rps=7")
        assert resolve_policy("rps=9").rps == 9.0       # explicit wins
        assert resolve_policy(None).rps == 7.0          # env next
        monkeypatch.delenv("PIO_TPU_QOS")
        assert resolve_policy(None, {"qos": "rps=5"}).rps == 5.0
        assert resolve_policy(None, {"qos": {"spec": "rps=4"}}).rps == 4.0
        assert resolve_policy(None, {}) is None         # QoS off
        ready = QoSPolicy(rps=1.0)
        assert resolve_policy(ready) is ready           # passthrough

    def test_priority_floors(self):
        assert priority_floor("interactive") == 0.0
        assert priority_floor("batchpredict") == 0.25
        assert priority_floor("shadow") == 0.5
        assert priority_floor(None) == 0.0
        assert priority_floor("TyPo") == 0.0  # unknown ⇒ interactive


# -- token buckets -----------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_refill(self):
        clock = FakeClock()
        b = TokenBucket(rate=2.0, burst=3.0, clock=clock)
        assert all(b.try_acquire()[0] for _ in range(3))
        ok, retry = b.try_acquire()
        assert not ok and retry == pytest.approx(0.5)  # 1 token / 2 rps
        clock.advance(0.5)
        assert b.try_acquire()[0]
        clock.advance(100.0)  # refill clamps at burst
        assert b.level() == pytest.approx(3.0)

    def test_priority_floor_reserves_headroom(self):
        clock = FakeClock()
        b = TokenBucket(rate=1.0, burst=10.0, clock=clock)
        for _ in range(5):
            assert b.try_acquire()[0]
        # 5 tokens left = exactly the shadow floor: background is shed,
        # interactive still admitted
        assert not b.try_acquire(floor=0.5)[0]
        assert b.try_acquire(floor=0.0)[0]

    def test_pool_wide_budget_via_striped_segment(self, tmp_path):
        """Two registries bound as pool workers 0/1 share ONE budget:
        admissions through either gate drain the other's bucket."""
        from pio_tpu.obs.metrics import MetricsRegistry
        from pio_tpu.obs.shm import PoolMetricsSegment

        clock = FakeClock()
        policy = parse_qos("rps=1,burst=6")
        path = str(tmp_path / "pool-metrics")
        seg = PoolMetricsSegment.create(path, n_workers=2)
        try:
            gates = []
            for idx in range(2):
                reg = MetricsRegistry()
                gate = QoSGate(policy, reg, scope="queryserver",
                               clock=clock)
                reg.bind_pool_segment(
                    PoolMetricsSegment.open(path), idx
                )
                gate.on_pool_bound()
                gates.append(gate)
            a, b = gates
            for _ in range(4):
                assert a.admit().ok
            # worker B observes A's 4 admissions through the segment:
            # only 2 of the shared 6-token burst remain
            assert b.admit().ok
            assert b.admit().ok
            refused = b.admit()
            assert not refused.ok and refused.reason == "rate_limit"
            assert refused.retry_after_s > 0
            # ...and A sees B's consumption right back
            assert not a.admit().ok
            # pool-wide admitted total covers both workers
            assert a.bucket._pool_total() == pytest.approx(6.0)
        finally:
            seg.unlink()

    def test_rebase_forgets_stripe_history(self, tmp_path):
        """A respawned worker adopting a stripe with prior admissions
        must not start with a pre-drained bucket."""
        from pio_tpu.obs.metrics import MetricsRegistry
        from pio_tpu.obs.shm import PoolMetricsSegment

        clock = FakeClock()
        policy = parse_qos("rps=1,burst=4")
        path = str(tmp_path / "pool-metrics")
        seg = PoolMetricsSegment.create(path, n_workers=1)
        try:
            def spawn_worker():
                reg = MetricsRegistry()
                gate = QoSGate(policy, reg, scope="queryserver",
                               clock=clock)
                reg.bind_pool_segment(PoolMetricsSegment.open(path), 0)
                gate.on_pool_bound()
                return gate

            first = spawn_worker()
            for _ in range(3):
                assert first.admit().ok  # stripe now carries history
            # "respawn": a fresh worker adopts the same stripe — rebase
            # must keep those 3 historical admissions from draining the
            # new bucket, leaving the full burst of 4
            respawned = spawn_worker()
            assert all(respawned.admit().ok for _ in range(4))
            assert not respawned.admit().ok
        finally:
            seg.unlink()


class TestConcurrencyLimiter:
    def test_slots_queue_and_timeout(self):
        lim = ConcurrencyLimiter(max_inflight=1, max_queue=0)
        assert lim.enter() == ConcurrencyLimiter.OK
        assert lim.enter() == ConcurrencyLimiter.QUEUE_FULL
        lim.exit()
        assert lim.enter() == ConcurrencyLimiter.OK
        lim.exit()

    def test_queue_timeout(self):
        lim = ConcurrencyLimiter(max_inflight=1, max_queue=2)
        assert lim.enter() == ConcurrencyLimiter.OK
        assert lim.enter(timeout_s=0.0) == ConcurrencyLimiter.TIMEOUT
        lim.exit()

    def test_freed_slot_reaches_later_waiter_after_peer_timeout(self):
        """A deadline waiter that gives up must not strand capacity: a
        freed slot has to reach the remaining queued waiter promptly.
        The survivor waits on its full 30s deadline — there is no poll
        tick to paper over a dropped notify, so a lost wakeup here
        hangs the join."""
        lim = ConcurrencyLimiter(max_inflight=1, max_queue=2)
        assert lim.enter() == ConcurrencyLimiter.OK
        out = {}

        def waiter(name, timeout_s):
            out[name] = lim.enter(timeout_s=timeout_s)

        ta = threading.Thread(target=waiter, args=("a", 0.05))
        tb = threading.Thread(target=waiter, args=("b", 30.0))
        ta.start()
        deadline = monotonic_s() + 5.0
        while lim.queued < 1 and monotonic_s() < deadline:
            time.sleep(0.005)
        tb.start()
        while lim.queued < 2 and monotonic_s() < deadline:
            time.sleep(0.005)
        ta.join(5.0)
        assert out.get("a") == ConcurrencyLimiter.TIMEOUT
        lim.exit()  # the freed slot must wake b, not vanish
        tb.join(5.0)
        assert not tb.is_alive(), "freed slot never reached waiter b"
        assert out.get("b") == ConcurrencyLimiter.OK
        lim.exit()


# -- circuit breaker ---------------------------------------------------------


class TestCircuitBreaker:
    def test_trip_cooldown_probe_close(self):
        clock = FakeClock()
        states = []
        br = CircuitBreaker(failure_rate=0.5, window=4, cooldown_s=5.0,
                            probes=2, clock=clock,
                            on_state_change=states.append)
        for _ in range(4):
            assert br.allow()[0]
            br.record_failure()
        assert br.state == "open"
        ok, retry = br.allow()
        assert not ok and 0 < retry <= 5.0
        clock.advance(5.0)
        assert br.state == "half_open"
        # probe trickle: 2 concurrent probes pass, the 3rd is refused
        assert br.allow()[0] and br.allow()[0]
        assert not br.allow()[0]
        br.record_success()
        br.record_success()
        assert br.state == "closed"
        assert br.snapshot()["windowSamples"] == 0  # window cleared
        assert states == ["open", "half_open", "closed"]

    def test_half_open_failure_reopens(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_rate=0.5, window=2, cooldown_s=1.0,
                            probes=1, clock=clock)
        br.record_failure()
        br.record_failure()
        assert br.state == "open"
        clock.advance(1.0)
        assert br.allow()[0]  # half-open probe
        br.record_failure()   # still sick: cooldown restarts
        assert br.state == "open"
        assert not br.allow()[0]

    def test_mixed_window_below_rate_stays_closed(self):
        br = CircuitBreaker(failure_rate=0.75, window=4,
                            clock=FakeClock())
        for failed in (True, False, True, False, True, False):
            br.record_failure() if failed else br.record_success()
        assert br.state == "closed"

    def test_abandoned_probe_grants_do_not_wedge_half_open(self):
        """Exits that never reach the dependency (parse 400s, deadline
        sheds) release their probe grant via cancel(): the breaker must
        not get stuck HALF_OPEN with every grant leaked and no call ever
        able to record an outcome."""
        clock = FakeClock()
        br = CircuitBreaker(failure_rate=0.5, window=2, cooldown_s=1.0,
                            probes=2, clock=clock)
        br.record_failure()
        br.record_failure()
        assert br.state == "open"
        clock.advance(1.0)
        assert br.state == "half_open"
        # burn through more abandoned calls than there are probe grants
        for _ in range(5):
            call = br.acquire()
            assert call.allowed, "cancel() must hand the grant back"
            call.cancel()
            call.cancel()  # idempotent
        # real probes still get grants and can close the breaker
        c1, c2 = br.acquire(), br.acquire()
        assert c1.allowed and c2.allowed
        c1.success()
        c2.success()
        assert br.state == "closed"

    def test_straggler_from_closed_epoch_cannot_close_half_open(self):
        """A call admitted while CLOSED that finishes after the breaker
        tripped and cooled down must not count as a half-open probe —
        only calls that actually touched the recovered dependency may
        close the breaker."""
        clock = FakeClock()
        br = CircuitBreaker(failure_rate=0.5, window=2, cooldown_s=1.0,
                            probes=1, clock=clock)
        straggler = br.acquire()  # granted while CLOSED
        assert straggler.allowed
        br.record_failure()
        br.record_failure()
        assert br.state == "open"
        clock.advance(1.0)
        assert br.state == "half_open"
        straggler.success()  # stale generation: ignored
        assert br.state == "half_open"
        probe = br.acquire()
        assert probe.allowed, "straggler must not consume the probe grant"
        probe.success()
        assert br.state == "closed"

    def test_stale_failure_cannot_reopen_half_open(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_rate=0.5, window=2, cooldown_s=1.0,
                            probes=1, clock=clock)
        straggler = br.acquire()
        br.record_failure()
        br.record_failure()
        clock.advance(1.0)
        assert br.state == "half_open"
        straggler.failure()  # stale generation: must not restart cooldown
        assert br.state == "half_open"

    def test_refused_call_records_nothing(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_rate=0.5, window=2, cooldown_s=5.0,
                            probes=1, clock=clock)
        br.record_failure()
        br.record_failure()
        refused = br.acquire()
        assert not refused.allowed and refused.retry_after_s > 0
        refused.success()  # no-op: was never granted
        refused.cancel()
        assert br.state == "open"


# -- deadlines & degradation -------------------------------------------------


class TestDeadline:
    def test_parse(self):
        assert parse_deadline_ms(None) is None
        assert parse_deadline_ms("  ") is None
        assert parse_deadline_ms("150") == 150.0
        for bad in ("abc", "-5", "0", "nan"):
            with pytest.raises(ValueError):
                parse_deadline_ms(bad)

    def test_remaining_and_expiry(self):
        clock = FakeClock()
        d = Deadline(100.0, clock=clock)
        assert d.remaining_s() == pytest.approx(0.1)
        assert not d.expired()
        clock.advance(0.1)
        assert d.expired()

    def test_from_header_default(self):
        clock = FakeClock()
        assert Deadline.from_header(None, default_ms=None,
                                    clock=clock) is None
        d = Deadline.from_header(None, default_ms=50.0, clock=clock)
        assert d.remaining_s() == pytest.approx(0.05)
        d = Deadline.from_header("25", default_ms=50.0, clock=clock)
        assert d.remaining_s() == pytest.approx(0.025)


class TestStaleCache:
    def test_lru_and_stats(self):
        c = StaleCache(2)
        c.put("a", 1)
        c.put("b", 2)
        assert c.get("a") == 1  # touches a: b is now the LRU entry
        c.put("c", 3)
        assert c.get("b") is None  # evicted
        assert c.get("a") == 1 and c.get("c") == 3
        s = c.stats()
        assert s["entries"] == 2 and s["hits"] == 3 and s["misses"] == 1

    def test_cache_key_order_insensitive(self):
        assert cache_key({"user": "u1", "num": 3}) == \
            cache_key({"num": 3, "user": "u1"})


# -- http env hardening (satellite) ------------------------------------------


class TestEnvHardening:
    def test_malformed_env_warns_and_falls_back(self, monkeypatch):
        from pio_tpu.server import http as http_mod

        monkeypatch.setenv("PIO_TPU_MAX_BODY_MB", "banana")
        with pytest.warns(RuntimeWarning, match="PIO_TPU_MAX_BODY_MB"):
            assert http_mod._env_float("PIO_TPU_MAX_BODY_MB", 4096.0) \
                == 4096.0

    @pytest.mark.parametrize("bad", ["-3", "0", "nan"])
    def test_non_positive_env_warns_and_falls_back(self, monkeypatch, bad):
        from pio_tpu.server import http as http_mod

        monkeypatch.setenv("PIO_TPU_MAX_JSON_BODY_MB", bad)
        with pytest.warns(RuntimeWarning):
            assert http_mod._env_float(
                "PIO_TPU_MAX_JSON_BODY_MB", 64.0
            ) == 64.0

    def test_valid_env_parses(self, monkeypatch):
        from pio_tpu.server import http as http_mod

        monkeypatch.setenv("PIO_TPU_MAX_BODY_MB", "10.5")
        assert http_mod._env_float("PIO_TPU_MAX_BODY_MB", 4096.0) == 10.5
        monkeypatch.delenv("PIO_TPU_MAX_BODY_MB")
        assert http_mod._env_float("PIO_TPU_MAX_BODY_MB", 4096.0) == 4096.0


# -- live servers under overload ---------------------------------------------


@pytest.fixture(autouse=True)
def mem_storage(tmp_home, monkeypatch):
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "MEM")
    Storage.reset()
    yield
    Storage.reset()


def http(method, url, body=None, headers=None):
    """(status, parsed body, lowercase header dict)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            return (resp.status, json.loads(resp.read() or b"null"),
                    {k.lower(): v for k, v in resp.headers.items()})
    except urllib.error.HTTPError as e:
        return (e.code, json.loads(e.read() or b"null"),
                {k.lower(): v for k, v in e.headers.items()})


VARIANT = {
    "id": "rec-qos",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "qos-test"}},
    "algorithms": [
        {"name": "als",
         "params": {"rank": 4, "num_iterations": 6, "lambda_": 0.1}}
    ],
}


@pytest.fixture()
def app_id():
    return Storage.get_meta_data_apps().insert(App(0, "qos-test"))


def _train(app_id):
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    for u in range(8):
        for i in range(6):
            in_block = (u < 4) == (i < 3)
            le.insert(
                Event("rate", "user", f"u{u}", "item", f"i{i}",
                      properties={"rating": 5.0 if in_block else 1.0},
                      event_time=t0),
                app_id,
            )
    variant = variant_from_dict(VARIANT)
    engine, ep = build_engine(variant)
    ctx = ComputeContext.local()
    run_train(engine, ep, variant, ctx=ctx)
    return variant, ctx


def _serve(app_id, qos, **kwargs):
    variant, ctx = _train(app_id)
    server, service = create_query_server(
        variant, host="127.0.0.1", port=0, ctx=ctx, qos=qos, **kwargs
    )
    server.start()
    return server, service, f"http://127.0.0.1:{server.port}"


def _scrape(url):
    from pio_tpu.obs.promparse import parse_prometheus_text

    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
        return parse_prometheus_text(r.read().decode("utf-8"))


class TestQueryServerOverload:
    def test_2x_burst_sheds_and_survives(self, app_id):
        """The acceptance scenario: a burst well past the admitted
        budget. Excess requests shed as 429 + Retry-After, admitted ones
        complete, the server stays up, and shed_total accounts for every
        rejection."""
        import concurrent.futures

        server, service, url = _serve(app_id, qos="rps=5,burst=5")
        try:
            def one(t):
                return http("POST", f"{url}/queries.json",
                            {"user": f"u{t % 8}", "num": 3})

            with concurrent.futures.ThreadPoolExecutor(8) as ex:
                results = list(ex.map(one, range(40)))
            admitted = [r for r in results if r[0] == 200]
            shed = [r for r in results if r[0] == 429]
            assert {r[0] for r in results} <= {200, 429}
            assert admitted, "budget-sized slice must complete"
            assert shed, "2x burst must shed"
            for _, body, headers in shed:
                assert int(headers["retry-after"]) >= 1
                assert "overloaded" in body["message"]
            for _, body, _ in admitted:
                assert len(body["itemScores"]) == 3
            # still alive and healthy after the burst
            assert http("GET", f"{url}/healthz")[0] == 200
            # every rejection is accounted, none double-counted
            pm = _scrape(url)
            assert sum(
                pm.family("pio_tpu_qos_shed_total").values()
            ) == len(shed)
            assert pm.value(
                "pio_tpu_qos_shed_total",
                scope="queryserver", reason="rate_limit",
            ) == len(shed)
            snap = http("GET", f"{url}/qos.json")[1]
            assert snap["shed"]["rate_limit"] == len(shed)
            assert snap["admitted"] == len(admitted)
        finally:
            server.stop()

    def test_deadline_expired_in_queue_never_reaches_scorer(
            self, app_id, monkeypatch):
        """A query whose X-Pio-Deadline-Ms budget elapses in the
        micro-batch queue is shed BEFORE model execution: 503, counted
        as reason=deadline, and its user never appears in any batch.
        The in-queue expiry is forced by wedging the batch worker inside
        a slow dispatch — the deadline-bounded collection window alone
        would dispatch the member BEFORE its budget ran out."""
        import concurrent.futures

        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_US", "50000")
        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_ADAPTIVE", "0")
        server, service, url = _serve(app_id, qos="rps=1000")
        try:
            seen = []
            real = service._predict_batch
            wedged = threading.Event()

            def spying(queries):
                seen.extend(q.user for q in queries)
                if not wedged.is_set():
                    wedged.set()
                    time.sleep(0.4)  # hold the worker past u2's budget
                return real(queries)

            monkeypatch.setattr(service, "_predict_batch", spying)
            with concurrent.futures.ThreadPoolExecutor(1) as ex:
                fut = ex.submit(
                    http, "POST", f"{url}/queries.json",
                    {"user": "u1", "num": 3},
                )
                assert wedged.wait(10.0), "u1 never reached the worker"
                # 100ms budget burns entirely behind the wedged worker
                status, body, headers = http(
                    "POST", f"{url}/queries.json",
                    {"user": "u2", "num": 3},
                    headers={DEADLINE_HEADER: "100"},
                )
                assert fut.result()[0] == 200  # the slow batch completes
            assert "u1" in seen
            assert status == 503
            assert "deadline" in body["message"]
            assert int(headers["retry-after"]) >= 1
            assert "u2" not in seen, "expired query must not execute"
            snap = http("GET", f"{url}/qos.json")[1]
            assert snap["shed"]["deadline"] == 1
        finally:
            server.stop()

    def test_tighter_deadline_arriving_mid_window_dispatches_early(
            self, app_id, monkeypatch):
        """A member enqueued DURING the collection window with a tight
        deadline shortens the window: the batch dispatches before that
        member expires instead of shedding it at a wakeup computed
        before it arrived (which a 2s window would guarantee here)."""
        import concurrent.futures

        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_US", "2000000")
        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_ADAPTIVE", "0")
        server, service, url = _serve(app_id, qos="rps=1000")
        try:
            with concurrent.futures.ThreadPoolExecutor(2) as ex:
                # u1 (no deadline) opens the 2s collection window
                f1 = ex.submit(
                    http, "POST", f"{url}/queries.json",
                    {"user": "u1", "num": 3},
                )
                time.sleep(0.3)  # u2 arrives mid-window
                f2 = ex.submit(
                    http, "POST", f"{url}/queries.json",
                    {"user": "u2", "num": 3},
                    {DEADLINE_HEADER: "300"},
                )
                s2, b2, _ = f2.result()
                s1, b1, _ = f1.result()
            assert s2 == 200, "tight member must be served, not shed"
            assert len(b2["itemScores"]) == 3
            assert s1 == 200 and len(b1["itemScores"]) == 3
            snap = http("GET", f"{url}/qos.json")[1]
            assert snap["shed"]["deadline"] == 0
        finally:
            server.stop()

    def test_malformed_deadline_is_client_error(self, app_id):
        server, service, url = _serve(app_id, qos="rps=1000")
        try:
            status, body, _ = http(
                "POST", f"{url}/queries.json", {"user": "u1", "num": 3},
                headers={DEADLINE_HEADER: "soon"},
            )
            assert status == 400
        finally:
            server.stop()

    def test_scorer_breaker_opens_and_recovers(self, app_id):
        """Scorer failures trip the breaker: subsequent queries shed
        fast as 503 reason=breaker; after the cooldown a half-open probe
        success closes it again."""
        server, service, url = _serve(
            app_id,
            qos="rps=1000,fail_rate=0.5,fail_window=4,"
                "cooldown=300ms,probes=1",
        )
        try:
            class Sick:
                def predict(self, model, query):
                    raise RuntimeError("scorer down")

            good_pairs = service.pairs
            service.pairs = [(Sick(), None)]
            for _ in range(4):
                status, _, _ = http(
                    "POST", f"{url}/queries.json", {"user": "u1", "num": 3}
                )
                assert status == 500
            # breaker open: shed BEFORE the scorer is even attempted
            status, body, headers = http(
                "POST", f"{url}/queries.json", {"user": "u1", "num": 3}
            )
            assert status == 503
            assert "breaker" in body["message"]
            assert int(headers["retry-after"]) >= 1
            snap = http("GET", f"{url}/qos.json")[1]
            assert snap["breakers"]["scorer"]["state"] == "open"
            assert snap["shed"]["breaker"] >= 1
            # dependency recovers; cooldown elapses; probe closes it
            service.pairs = good_pairs
            time.sleep(0.35)
            status, body, _ = http(
                "POST", f"{url}/queries.json", {"user": "u1", "num": 3}
            )
            assert status == 200 and body["itemScores"]
            snap = http("GET", f"{url}/qos.json")[1]
            assert snap["breakers"]["scorer"]["state"] == "closed"
        finally:
            server.stop()

    def test_stale_cache_degrades_instead_of_shedding(self, app_id):
        """With cache= configured, a shed whose query was answered
        recently returns the stale answer as a marked 200; only true
        rejections count as shed."""
        # rps is tiny so refill during the first query's JAX warmup
        # cannot hand the third request a fresh token
        server, service, url = _serve(app_id,
                                      qos="rps=0.05,burst=2,cache=32")
        try:
            body = {"user": "u1", "num": 3}
            s1, fresh, h1 = http("POST", f"{url}/queries.json", body)
            assert s1 == 200 and DEGRADED_HEADER.lower() not in h1
            http("POST", f"{url}/queries.json", body)  # drains the burst
            status, stale, headers = http(
                "POST", f"{url}/queries.json", body
            )
            assert status == 200
            assert headers[DEGRADED_HEADER.lower()] == DEGRADED_VALUE
            assert stale["itemScores"] == fresh["itemScores"]
            # an uncached query past the budget is a real 429
            status, _, headers = http(
                "POST", f"{url}/queries.json", {"user": "u7", "num": 2}
            )
            assert status == 429 and "retry-after" in headers
            snap = http("GET", f"{url}/qos.json")[1]
            assert snap["degraded"] == 1
            assert snap["shed"]["rate_limit"] == 1
            assert snap["staleCache"]["hits"] == 1
        finally:
            server.stop()

    def test_priority_header_sheds_background_first(self, app_id):
        """Shadow traffic only rides a mostly-full bucket: once the
        burst is half drained, shadow sheds while interactive admits."""
        # tiny rps: warmup-time refill must stay well under one token
        server, service, url = _serve(app_id, qos="rps=0.05,burst=8")
        try:
            for _ in range(4):  # drain to the shadow floor (50%)
                assert http("POST", f"{url}/queries.json",
                            {"user": "u1", "num": 3})[0] == 200
            status, _, headers = http(
                "POST", f"{url}/queries.json", {"user": "u1", "num": 3},
                headers={"X-Pio-Priority": "shadow"},
            )
            assert status == 429 and "retry-after" in headers
            assert http("POST", f"{url}/queries.json",
                        {"user": "u1", "num": 3})[0] == 200
        finally:
            server.stop()

    def test_qos_json_disabled_without_policy(self, app_id):
        server, service, url = _serve(app_id, qos=None)
        try:
            assert http("GET", f"{url}/qos.json")[1] == {"enabled": False}
            # no QoS ⇒ untouched serving path
            assert http("POST", f"{url}/queries.json",
                        {"user": "u1", "num": 3})[0] == 200
        finally:
            server.stop()

    def test_qos_json_snapshot_shape(self, app_id):
        server, service, url = _serve(
            app_id, qos="rps=100,inflight=8,queue=4,cache=16"
        )
        try:
            snap = http("GET", f"{url}/qos.json")[1]
            assert snap["enabled"] is True
            assert snap["scope"] == "queryserver"
            assert snap["policy"]["rps"] == 100.0
            assert snap["policy"]["priorities"]["shadow"] == 0.5
            assert set(snap["shed"]) == {
                "rate_limit", "key_rate_limit", "queue_full",
                "queue_timeout", "deadline", "breaker",
            }
            assert snap["bucket"]["burst"] == 100.0
            assert snap["concurrency"]["maxInflight"] == 8
            assert snap["staleCache"]["capacity"] == 16
            assert snap["breakers"]["scorer"]["state"] == "closed"
        finally:
            server.stop()


class TestEventServerQoS:
    def test_per_key_rate_limit(self):
        """Ingest is throttled per access key: one key exhausting its
        bucket gets 429 + Retry-After; another key is unaffected."""
        app_id = Storage.get_meta_data_apps().insert(App(0, "ev-qos"))
        keys = Storage.get_meta_data_access_keys()
        k1 = keys.insert(AccessKey("", app_id))
        k2 = keys.insert(AccessKey("", app_id))
        server = create_event_server(
            host="127.0.0.1", port=0, qos="key_rps=1,key_burst=2"
        ).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            ev = {"event": "rate", "entityType": "user", "entityId": "u1",
                  "properties": {"rating": 4.0},
                  "eventTime": "2026-03-01T10:00:00Z"}
            for _ in range(2):
                assert http(
                    "POST", f"{url}/events.json?accessKey={k1}", ev
                )[0] == 201
            status, body, headers = http(
                "POST", f"{url}/events.json?accessKey={k1}", ev
            )
            assert status == 429 and int(headers["retry-after"]) >= 1
            # a different key still has its full bucket
            assert http(
                "POST", f"{url}/events.json?accessKey={k2}", ev
            )[0] == 201
            snap = http("GET", f"{url}/qos.json")[1]
            assert snap["scope"] == "eventserver"
            assert snap["shed"]["key_rate_limit"] == 1
            assert snap["keyBuckets"]["keys"] == 2
        finally:
            server.stop()

    def test_shed_runs_before_auth_key_lookup(self, monkeypatch):
        """The rate limiter protects the storage-backed access-key
        lookup it used to sit behind: a shed request — even a flood of
        unique keys that the positive auth cache can never absorb — is
        rejected 429 before any metadata read happens."""
        lookups = []
        real_store = Storage.get_meta_data_access_keys()

        class CountingStore:
            def get(self, key):
                lookups.append(key)
                return real_store.get(key)

            def __getattr__(self, name):
                return getattr(real_store, name)

        monkeypatch.setattr(
            Storage, "get_meta_data_access_keys",
            classmethod(lambda cls: CountingStore()),
        )
        server = create_event_server(
            host="127.0.0.1", port=0, qos="rps=0.05,burst=1"
        ).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            ev = {"event": "buy", "entityType": "user", "entityId": "u1",
                  "eventTime": "2026-03-01T10:00:00Z"}
            # first request drains the burst: admitted, auth does its
            # (failing) lookup for the bogus key
            status, _, _ = http(
                "POST", f"{url}/events.json?accessKey=nope-1", ev
            )
            assert status == 401
            assert lookups == ["nope-1"]
            # the rest of the unique-key flood is shed with NO further
            # metadata reads (misses are never cached, so pre-auth
            # admission is the only thing standing in front of storage)
            for i in range(2, 5):
                status, body, headers = http(
                    "POST", f"{url}/events.json?accessKey=nope-{i}", ev
                )
                assert status == 429
                assert int(headers["retry-after"]) >= 1
                assert "overloaded" in body["message"]
            assert lookups == ["nope-1"]
        finally:
            server.stop()

    def test_engine_wide_ingest_limit(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "ev-qos2"))
        key = Storage.get_meta_data_access_keys().insert(
            AccessKey("", app_id)
        )
        server = create_event_server(
            host="127.0.0.1", port=0, qos="rps=1,burst=3"
        ).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            ev = {"event": "buy", "entityType": "user", "entityId": "u1",
                  "eventTime": "2026-03-01T10:00:00Z"}
            codes = [
                http("POST", f"{url}/events.json?accessKey={key}", ev)[0]
                for _ in range(6)
            ]
            assert codes.count(201) >= 3
            assert 429 in codes
            # sheds feed the error accounting (and thus the SLO engine)
            stats = http("GET", f"{url}/stats.json")[1]
            assert stats["errorCount"] == codes.count(429)
        finally:
            server.stop()
