"""Tests for the e2 reusable model helpers (reference e2/ subproject).

Mirrors the reference's ``CategoricalNaiveBayesTest``, ``MarkovChainTest``,
``BinaryVectorizerTest`` and ``CrossValidationTest`` (SURVEY.md §4).
"""

import math

import numpy as np
import pytest

from pio_tpu.controller.cross_validation import split_data
from pio_tpu.models.markov_chain import train_markov_chain
from pio_tpu.models.naive_bayes import LabeledPoint, train_naive_bayes
from pio_tpu.models.vectorizer import BinaryVectorizer


# ------------------------------------------------------- CategoricalNaiveBayes
def _tennis_points():
    # classic play-tennis toy set: features = (outlook, temperature)
    rows = [
        ("yes", "sunny", "hot"),
        ("yes", "overcast", "mild"),
        ("yes", "overcast", "hot"),
        ("yes", "rain", "mild"),
        ("no", "rain", "cool"),
        ("no", "sunny", "hot"),
    ]
    return [LabeledPoint(lab, (o, t)) for lab, o, t in rows]


class TestNaiveBayes:
    def test_priors(self):
        model = train_naive_bayes(_tennis_points())
        pri = {l: math.exp(p) for l, p in zip(model.labels, model.priors)}
        assert pri["yes"] == pytest.approx(4 / 6)
        assert pri["no"] == pytest.approx(2 / 6)

    def test_likelihood_add_one_smoothing(self):
        model = train_naive_bayes(_tennis_points())
        li = model.labels.index("yes")
        f0 = model.feature_vocabs[0]
        # P(overcast | yes) = (2 + 1) / (4 + |V|=3)
        assert math.exp(
            model.likelihoods[0][li, f0["overcast"]]
        ) == pytest.approx(3 / 7)
        # P(rain | no) = (1 + 1) / (2 + 3)
        ln = model.labels.index("no")
        assert math.exp(
            model.likelihoods[0][ln, f0["rain"]]
        ) == pytest.approx(2 / 5)

    def test_predict(self):
        model = train_naive_bayes(_tennis_points())
        assert model.predict(("overcast", "hot")) == "yes"
        # unseen combination falls back to priors+smoothing; cool only ever "no"
        assert model.predict(("rain", "cool")) == "no"

    def test_predict_batch_matches_scalar(self):
        model = train_naive_bayes(_tennis_points())
        queries = [
            ("sunny", "hot"),
            ("overcast", "mild"),
            ("rain", "cool"),
            ("nowhere", "hot"),  # OOV feature → contributes nothing
        ]
        batch = model.predict_batch(queries)
        # scalar path ignores OOV values the same way
        assert batch[:3] == [model.predict(q) for q in queries[:3]]
        assert batch[3] == model.predict(("nowhere", "hot"))

    def test_log_score_option_semantics(self):
        model = train_naive_bayes(_tennis_points())
        known = LabeledPoint("yes", ("sunny", "hot"))
        assert model.log_score(known) is not None
        oov = LabeledPoint("yes", ("blizzard", "hot"))
        assert model.log_score(oov) is None  # OOV without default → None
        with_default = model.log_score(oov, default_likelihood=-10.0)
        assert with_default is not None and with_default < model.log_score(known)
        assert model.log_score(LabeledPoint("maybe", ("sunny", "hot"))) is None

    def test_ragged_features_rejected(self):
        with pytest.raises(ValueError):
            train_naive_bayes(
                [LabeledPoint("a", ("x",)), LabeledPoint("b", ("x", "y"))]
            )


# --------------------------------------------------------------- MarkovChain
class TestMarkovChain:
    def test_row_normalization_and_order(self):
        model = train_markov_chain(
            [(0, 1, 3.0), (0, 2, 1.0), (1, 0, 2.0)], n_states=3, top_k=2
        )
        t0 = model.transitions_of(0)
        assert t0[0][0] == 1 and t0[0][1] == pytest.approx(0.75)
        assert t0[1][0] == 2 and t0[1][1] == pytest.approx(0.25)
        t1 = model.transitions_of(1)
        assert t1 == [(0, pytest.approx(1.0))]

    def test_dangling_state_has_no_transitions(self):
        model = train_markov_chain([(0, 1, 1.0)], n_states=3, top_k=2)
        assert model.transitions_of(2) == []

    def test_duplicate_triples_accumulate(self):
        model = train_markov_chain(
            [(0, 1, 1.0), (0, 1, 1.0), (0, 2, 2.0)], n_states=3, top_k=3
        )
        probs = dict(model.transitions_of(0))
        assert probs[1] == pytest.approx(0.5)
        assert probs[2] == pytest.approx(0.5)

    def test_out_of_range_rejected(self):
        with pytest.raises(ValueError):
            train_markov_chain([(0, 5, 1.0)], n_states=3)


# ----------------------------------------------------------- BinaryVectorizer
class TestBinaryVectorizer:
    def test_fit_and_vectorize(self):
        maps = [
            {"color": "red", "size": "L", "ignored": "x"},
            {"color": "blue"},
        ]
        vz = BinaryVectorizer.fit(maps, fields=["color", "size"])
        assert vz.dim == 3  # (color,red) (size,L) (color,blue)
        v = vz.to_vector({"color": "blue", "size": "L"})
        assert v[vz.index[("color", "blue")]] == 1.0
        assert v[vz.index[("size", "L")]] == 1.0
        assert sum(v) == 2.0

    def test_unseen_value_is_zero(self):
        vz = BinaryVectorizer.fit([{"a": "1"}], fields=["a"])
        assert vz.to_vector({"a": "2"}) == [0.0]

    def test_to_matrix(self):
        maps = [{"a": "x"}, {"a": "y"}, {"b": "z"}]
        vz = BinaryVectorizer.fit(maps, fields=["a", "b"])
        m = vz.to_matrix(maps)
        assert m.shape == (3, 3)
        assert m.sum() == 3.0
        assert (m.sum(axis=1) == 1.0).all()


# ------------------------------------------------------------ cross-validation
class TestSplitData:
    def test_folds_partition_data(self):
        data = list(range(10))
        folds = split_data(
            3,
            data,
            to_training_data=list,
            to_query_actual=lambda d: (d, d * 2),
        )
        assert len(folds) == 3
        all_test = []
        for i, (train, info, qa) in enumerate(folds):
            assert info == {"fold": i}
            test_elems = [q for q, _ in qa]
            assert set(train) | set(test_elems) == set(data)
            assert not set(train) & set(test_elems)
            all_test += test_elems
        # every element is tested exactly once across folds
        assert sorted(all_test) == data

    def test_k_must_be_at_least_two(self):
        with pytest.raises(ValueError):
            split_data(1, [1], list, lambda d: (d, d))
