"""Server route tests over real HTTP on ephemeral ports (reference
EventServiceSpec / CreateServer tests, SURVEY.md §4). Memory storage
backend; recommendation engine for the query server."""

import datetime as dt
import json
import urllib.error
import urllib.request

import pytest

import pio_tpu.templates  # noqa: F401
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.server import create_event_server, create_query_server
from pio_tpu.storage import AccessKey, App, Channel, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict


@pytest.fixture(autouse=True)
def mem_storage(tmp_home, monkeypatch):
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "MEM")
    Storage.reset()
    yield
    Storage.reset()


def http(method, url, body=None, headers=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


def http_h(method, url, body=None, headers=None):
    """Like http() but also returns the response headers (lowercased)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return (resp.status, json.loads(resp.read() or b"null"),
                    {k.lower(): v for k, v in resp.getheaders()})
    except urllib.error.HTTPError as e:
        return (e.code, json.loads(e.read() or b"null"),
                {k.lower(): v for k, v in e.headers.items()})


@pytest.fixture()
def eventserver():
    server = create_event_server(host="127.0.0.1", port=0).start()
    yield f"http://127.0.0.1:{server.port}"
    server.stop()


@pytest.fixture()
def app_and_key():
    app_id = Storage.get_meta_data_apps().insert(App(0, "srv-test"))
    key = Storage.get_meta_data_access_keys().insert(AccessKey("", app_id))
    return app_id, key


EV = {
    "event": "rate",
    "entityType": "user",
    "entityId": "u1",
    "targetEntityType": "item",
    "targetEntityId": "i1",
    "properties": {"rating": 4.5},
    "eventTime": "2026-03-01T10:00:00Z",
}


class TestEventServer:
    def test_alive(self, eventserver):
        assert http("GET", f"{eventserver}/")[1] == {"status": "alive"}

    def test_ingest_and_get(self, eventserver, app_and_key):
        app_id, key = app_and_key
        status, body = http("POST", f"{eventserver}/events.json?accessKey={key}", EV)
        assert status == 201 and "eventId" in body
        eid = body["eventId"]
        status, got = http(
            "GET", f"{eventserver}/events/{eid}.json?accessKey={key}"
        )
        assert status == 200
        assert got["event"] == "rate" and got["properties"]["rating"] == 4.5
        # visible in storage
        assert len(Storage.get_levents().find(app_id)) == 1
        # delete
        assert http("DELETE", f"{eventserver}/events/{eid}.json?accessKey={key}")[0] == 200
        assert http("GET", f"{eventserver}/events/{eid}.json?accessKey={key}")[0] == 404

    def test_auth_failures(self, eventserver, app_and_key):
        _, key = app_and_key
        assert http("POST", f"{eventserver}/events.json", EV)[0] == 401
        assert http("POST", f"{eventserver}/events.json?accessKey=WRONG", EV)[0] == 401
        # Authorization header works
        status, _ = http(
            "POST", f"{eventserver}/events.json", EV,
            headers={"Authorization": f"Bearer {key}"},
        )
        assert status == 201

    def test_storage_reset_invalidates_auth_cache(self, eventserver,
                                                  app_and_key):
        """A reset within AUTH_CACHE_TTL_S must not keep serving cached
        AccessKey records from the store that was just dropped."""
        _, key = app_and_key
        url = f"{eventserver}/events.json?accessKey={key}"
        assert http("POST", url, EV)[0] == 201  # primes the auth cache
        Storage.reset()  # key store gone; cached positive auth must go too
        assert http("POST", url, EV)[0] == 401

    def test_auth_cache_generation_fences_stale_insert(self, app_and_key,
                                                       monkeypatch):
        """An invalidation landing BETWEEN the store lookup and the
        cache insert must win: the in-flight _auth's record came from
        the old store and must not repopulate the cache."""
        from pio_tpu.server.event_server import EventServerService
        from pio_tpu.server.http import Request

        _, key = app_and_key
        service = EventServerService()
        store = Storage.get_meta_data_access_keys()
        orig_get = store.get

        def racy_get(k):
            ak = orig_get(k)
            service.invalidate_auth_cache()  # reset races the lookup
            return ak

        monkeypatch.setattr(store, "get", racy_get)
        req = Request(method="POST", path="/events.json",
                      params={"accessKey": key}, body=None)
        service._auth(req)  # authenticates against the pre-reset store
        assert service._auth_cache == {}  # ...but must NOT re-cache

    def test_event_whitelist(self, eventserver, app_and_key):
        app_id, _ = app_and_key
        limited = Storage.get_meta_data_access_keys().insert(
            AccessKey("", app_id, ("view",))
        )
        assert http("POST", f"{eventserver}/events.json?accessKey={limited}", EV)[0] == 403

    def test_malformed_events(self, eventserver, app_and_key):
        _, key = app_and_key
        url = f"{eventserver}/events.json?accessKey={key}"
        bad = dict(EV)
        del bad["entityId"]
        assert http("POST", url, bad)[0] == 400
        assert http("POST", url, {**EV, "event": "$badname"})[0] == 400
        assert http("POST", url, {**EV, "eventTime": "yesterday"})[0] == 400

    def test_channels(self, eventserver, app_and_key):
        app_id, key = app_and_key
        Storage.get_meta_data_channels().insert(Channel(0, "mobile", app_id))
        url = f"{eventserver}/events.json?accessKey={key}&channel=mobile"
        assert http("POST", url, EV)[0] == 201
        assert http("POST", f"{eventserver}/events.json?accessKey={key}&channel=nope", EV)[0] == 400
        # channel isolation
        _, default_events = http("GET", f"{eventserver}/events.json?accessKey={key}")
        assert default_events == []
        _, chan_events = http("GET", url)
        assert len(chan_events) == 1

    def test_batch_partial_failure(self, eventserver, app_and_key):
        _, key = app_and_key
        batch = [EV, {"event": "rate"}, {**EV, "entityId": "u2"}]
        status, results = http(
            "POST", f"{eventserver}/batch/events.json?accessKey={key}", batch
        )
        assert status == 200
        assert [r["status"] for r in results] == [201, 400, 201]
        assert "message" in results[1]

    def test_batch_too_large(self, eventserver, app_and_key):
        _, key = app_and_key
        status, body = http(
            "POST", f"{eventserver}/batch/events.json?accessKey={key}", [EV] * 51
        )
        assert status == 400 and "exceeds" in body["message"]

    def test_find_filters_and_limit(self, eventserver, app_and_key):
        _, key = app_and_key
        url = f"{eventserver}/events.json?accessKey={key}"
        for i in range(5):
            http("POST", url, {
                **EV, "entityId": f"u{i%2}",
                "eventTime": f"2026-03-0{i+1}T10:00:00Z",
            })
        _, out = http("GET", f"{url}&limit=3")
        assert len(out) == 3
        # reversed by default: newest first
        assert out[0]["eventTime"] > out[-1]["eventTime"]
        _, out = http("GET", f"{url}&entityId=u1&limit=-1&reversed=false")
        assert len(out) == 2
        assert out[0]["eventTime"] < out[1]["eventTime"]
        _, out = http("GET", f"{url}&startTime=2026-03-03T00:00:00Z")
        assert len(out) == 3
        assert http("GET", f"{url}&startTime=nope")[0] == 400

    def test_search_501_on_non_searchable_backend(
        self, eventserver, app_and_key
    ):
        _, key = app_and_key
        st, body = http(
            "GET", f"{eventserver}/events/search.json?accessKey={key}&q=x"
        )
        assert st == 501
        assert "searchable" in body["message"]

    def test_search_route_on_searchable_backend(
        self, tmp_home, monkeypatch
    ):
        """The ES-analog capability over REST: BM25 event search."""
        monkeypatch.setenv(
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "ES"
        )
        monkeypatch.setenv("PIO_STORAGE_SOURCES_ES_TYPE", "searchable")
        monkeypatch.setenv(
            "PIO_STORAGE_SOURCES_ES_PATH", str(tmp_home / "se.db")
        )
        Storage.reset()
        # metadata still memory: re-mint the app/key there
        app_id = Storage.get_meta_data_apps().insert(App(0, "search-test"))
        key = Storage.get_meta_data_access_keys().insert(
            AccessKey("", app_id)
        )
        server = create_event_server(host="127.0.0.1", port=0).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            ev = dict(EV, properties={"genre": "dystopian scifi"})
            st, _ = http(
                "POST", f"{base}/events.json?accessKey={key}", ev
            )
            assert st == 201
            st, body = http(
                "GET", f"{base}/events/search.json?accessKey={key}&q=scifi"
            )
            assert st == 200 and len(body) == 1, body
            assert body[0]["properties"]["genre"] == "dystopian scifi"
            st, body = http(
                "GET",
                f"{base}/events/search.json?accessKey={key}&q=romance",
            )
            assert st == 200 and body == []
            # malformed FTS query → 400, not a server error
            st, body = http(
                "GET",
                f"{base}/events/search.json?accessKey={key}&q=AND%20AND%20(",
            )
            assert st == 400
            # missing q → 400; bad key → 401; limit shares find's contract
            st, _ = http(
                "GET", f"{base}/events/search.json?accessKey={key}"
            )
            assert st == 400
            st, _ = http(
                "GET",
                f"{base}/events/search.json?accessKey={key}&q=x&limit=-5",
            )
            assert st == 400
            st, _ = http(
                "GET", f"{base}/events/search.json?accessKey=bogus&q=x"
            )
            assert st == 401
        finally:
            server.stop()
            Storage.reset()

    def test_stats(self, eventserver, app_and_key):
        app_id, key = app_and_key
        http("POST", f"{eventserver}/events.json?accessKey={key}", EV)
        http("POST", f"{eventserver}/events.json?accessKey={key}", {"event": "x"})
        _, stats = http("GET", f"{eventserver}/stats.json")
        counts = stats["apps"][0]["counts"]
        assert {"event": "rate", "entityType": "user", "status": 201, "count": 1} in counts
        assert any(c["status"] == 400 for c in counts)

    def test_prometheus_metrics(self, eventserver, app_and_key):
        """GET /metrics: Prometheus text exposition of ingest counters."""
        import urllib.request

        _, key = app_and_key
        http("POST", f"{eventserver}/events.json?accessKey={key}", EV)
        with urllib.request.urlopen(
            f"{eventserver}/metrics", timeout=10
        ) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert "# TYPE pio_tpu_events_ingested_total counter" in text
        assert 'event="rate"' in text and 'status="201"' in text

    def test_metrics_round_trip_and_stage_histograms(
        self, eventserver, app_and_key
    ):
        """/metrics parses with the obs text parser; the ingest stage
        histogram (parse/validate/store) has observations after a POST."""
        import urllib.request

        from pio_tpu.obs.promparse import parse_prometheus_text

        _, key = app_and_key
        http("POST", f"{eventserver}/events.json?accessKey={key}", EV)
        with urllib.request.urlopen(f"{eventserver}/metrics", timeout=10) as r:
            pm = parse_prometheus_text(r.read().decode())
        assert pm.types["pio_tpu_events_ingested_total"] == "counter"
        assert pm.types["pio_tpu_event_stage_seconds"] == "histogram"
        for stage in ("parse", "validate", "store"):
            assert pm.value("pio_tpu_event_stage_seconds_count", stage=stage) >= 1
        # bucket counts are cumulative => monotone non-decreasing
        buckets = pm.histogram_buckets("pio_tpu_event_stage_seconds", stage="store")
        cums = [c for _, c in buckets]
        assert cums == sorted(cums) and cums[-1] >= 1

    def test_stats_parity_and_window(self, eventserver, app_and_key):
        """/stats.json exposes the same request-latency keys as the query
        server, plus a per-stage summary; ?window= narrows the view."""
        _, key = app_and_key
        for _ in range(3):
            http("POST", f"{eventserver}/events.json?accessKey={key}", EV)
        _, stats = http("GET", f"{eventserver}/stats.json")
        assert stats["requestCount"] >= 3
        assert stats["errorCount"] == 0
        assert stats["p50Ms"] is not None and stats["p50Ms"] < 1000
        assert stats["p95Ms"] >= stats["p50Ms"]
        assert "store" in stats["stages"]
        assert stats["apps"]  # classic per-app block preserved
        _, win = http("GET", f"{eventserver}/stats.json?window=60")
        assert win["windowSeconds"] == 60.0
        assert win["requestCount"] >= 3
        _, zero = http("GET", f"{eventserver}/stats.json?window=0.000001")
        assert zero["requestCount"] == 0

    def test_traces_json(self, eventserver, app_and_key):
        _, key = app_and_key
        http("POST", f"{eventserver}/events.json?accessKey={key}", EV)
        # commits=0: the default merged view ranks this request against
        # the process-global commit ring (slowest first), so an unlucky
        # slow flush from an EARLIER test would displace it — the merge
        # itself is covered by test_commit_ring_merged_into_traces
        _, body = http("GET", f"{eventserver}/traces.json?n=5&commits=0")
        traces = body["traces"]
        assert traces and traces[0]["kind"] == "event"
        stages = {s["stage"] for t in traces for s in t["spans"]}
        assert {"parse", "validate", "store"} <= stages

    def test_webhook_json(self, eventserver, app_and_key):
        app_id, key = app_and_key
        payload = {
            "type": "track", "event": "signup", "userId": "u42",
            "properties": {"plan": "pro"},
        }
        status, body = http(
            "POST", f"{eventserver}/webhooks/segmentio.json?accessKey={key}", payload
        )
        assert status == 201
        evs = Storage.get_levents().find(app_id)
        assert evs[0].event == "signup" and evs[0].entity_id == "u42"
        assert http(
            "POST", f"{eventserver}/webhooks/nope.json?accessKey={key}", payload
        )[0] == 404
        assert http(
            "POST", f"{eventserver}/webhooks/segmentio.json?accessKey={key}",
            {"type": "weird"},
        )[0] == 400

    def test_webhook_form(self, eventserver, app_and_key):
        app_id, key = app_and_key
        form = "type=subscribe&fired_at=2026-03-01 10:00:00&data[email]=a@b.c&data[plan]=free"
        req = urllib.request.Request(
            f"{eventserver}/webhooks/mailchimp.form?accessKey={key}",
            data=form.encode(),
            method="POST",
            headers={"Content-Type": "application/x-www-form-urlencoded"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 201
        evs = Storage.get_levents().find(app_id)
        assert evs[0].event == "subscribe" and evs[0].entity_id == "a@b.c"
        assert evs[0].properties.get("plan", str) == "free"


# ------------------------------------------------------------- query server
VARIANT = {
    "id": "rec-srv",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "srv-test"}},
    "algorithms": [
        {"name": "als", "params": {"rank": 4, "num_iterations": 6, "lambda_": 0.1}}
    ],
}


def _train(app_id):
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    for u in range(8):
        for i in range(6):
            in_block = (u < 4) == (i < 3)
            le.insert(
                Event("rate", "user", f"u{u}", "item", f"i{i}",
                      properties={"rating": 5.0 if in_block else 1.0},
                      event_time=t0),
                app_id,
            )
    variant = variant_from_dict(VARIANT)
    engine, ep = build_engine(variant)
    ctx = ComputeContext.local()
    iid = run_train(engine, ep, variant, ctx=ctx)
    return variant, ctx, iid


@pytest.fixture()
def queryserver(app_and_key):
    app_id, _ = app_and_key
    variant, ctx, iid = _train(app_id)
    server, service = create_query_server(
        variant, host="127.0.0.1", port=0, ctx=ctx,
        feedback=True, feedback_app_id=app_id,
    )
    server.start()
    yield f"http://127.0.0.1:{server.port}", service, app_id
    server.stop()


class TestQueryServer:
    def test_status_page(self, queryserver):
        url, service, _ = queryserver
        status, body = http("GET", f"{url}/")
        assert status == 200
        assert body["status"] == "deployed"
        assert body["engineFactory"] == "templates.recommendation"
        assert body["engineInstanceId"] == service.instance_id

    def test_query_roundtrip(self, queryserver):
        url, _, _ = queryserver
        status, body = http("POST", f"{url}/queries.json", {"user": "u1", "num": 3})
        assert status == 200
        assert len(body["itemScores"]) == 3
        items = {s["item"] for s in body["itemScores"]}
        assert items <= {"i0", "i1", "i2"}  # u1's block
        assert "prId" in body  # feedback enabled

    def test_feedback_logged(self, queryserver):
        url, _, app_id = queryserver
        _, body = http("POST", f"{url}/queries.json", {"user": "u1"})
        evs = Storage.get_levents().find(app_id, entity_type="pio_pr")
        assert len(evs) == 1
        assert evs[0].pr_id == body["prId"]
        assert evs[0].properties.get("prediction", dict)["prId"] == body["prId"]

    def test_bad_query(self, queryserver):
        url, _, _ = queryserver
        status, body = http("POST", f"{url}/queries.json", {"uzer": "u1"})
        assert status == 400 and "unknown params" in body["message"]
        assert http("POST", f"{url}/queries.json")[0] == 400

    def test_stats_latency(self, queryserver):
        url, _, _ = queryserver
        for _ in range(3):
            http("POST", f"{url}/queries.json", {"user": "u1"})
        _, stats = http("GET", f"{url}/stats.json")
        assert stats["requestCount"] >= 3
        assert stats["p50Ms"] is not None and stats["p50Ms"] < 1000

    def test_reload_hot_swap(self, queryserver):
        url, service, app_id = queryserver
        old_iid = service.instance_id
        variant, ctx, new_iid = _train(app_id)  # second training run
        status, body = http("POST", f"{url}/reload", {})
        assert status == 200
        assert body["engineInstanceId"] == new_iid != old_iid
        # still serving
        assert http("POST", f"{url}/queries.json", {"user": "u1"})[0] == 200

    def test_undeploy(self, queryserver):
        url, _, _ = queryserver
        assert http("POST", f"{url}/undeploy", {})[0] == 200
        assert http("POST", f"{url}/queries.json", {"user": "u1"})[0] == 503

    def test_concurrent_queries(self, queryserver):
        """16 threads × 8 posts: every response correct, stats coherent
        (the serving path under contention — swap-lock, scorer, storage)."""
        import concurrent.futures

        url, service, _ = queryserver

        def worker(t):
            got = []
            for q in range(8):
                u = f"u{(t + q) % 8}"
                status, body = http(
                    "POST", f"{url}/queries.json", {"user": u, "num": 2}
                )
                got.append((status, len(body.get("itemScores", []))))
            return got

        with concurrent.futures.ThreadPoolExecutor(16) as ex:
            results = [r for rs in ex.map(worker, range(16)) for r in rs]
        assert all(status == 200 for status, _ in results)
        assert all(n == 2 for _, n in results)
        assert service.stats.count >= 128

    def test_microbatch_coalesces(self, app_and_key, monkeypatch):
        """With PIO_TPU_SERVE_MICROBATCH_US set, concurrent queries ride
        one batch_predict dispatch and answers stay per-query correct."""
        import concurrent.futures

        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_US", "2000")
        app_id, _ = app_and_key
        variant, ctx, iid = _train(app_id)
        server, service = create_query_server(
            variant, host="127.0.0.1", port=0, ctx=ctx
        )
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}"

            def one(t):
                u = f"u{t % 8}"
                status, body = http(
                    "POST", f"{url}/queries.json", {"user": u, "num": 3}
                )
                items = {s["item"] for s in body["itemScores"]}
                expect = (
                    {"i0", "i1", "i2"} if t % 8 < 4 else {"i3", "i4", "i5"}
                )
                return status, items <= expect, len(items)

            with concurrent.futures.ThreadPoolExecutor(12) as ex:
                results = list(ex.map(one, range(48)))
            assert all(s == 200 for s, _, _ in results)
            assert all(ok for _, ok, _ in results)
            assert all(n == 3 for _, _, n in results)
            mb = service._batcher.to_dict()
            assert mb["batchedQueries"] == 48
            # coalescing actually happened (not 48 batches of 1)
            assert mb["batches"] < 48 and mb["maxBatch"] > 1, mb
            status, stats = http("GET", f"{url}/stats.json")
            assert stats["microbatch"]["batches"] == mb["batches"]
        finally:
            server.stop()

    def test_microbatch_adaptive_probe_decides(self, app_and_key,
                                               monkeypatch):
        """The adaptive batcher A/B-probes both regimes under live load
        and settles on a permanent mode; in the losing regime's place it
        stops paying that regime's cost (bypass or stay coalesced)."""
        import concurrent.futures

        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_US", "500")
        app_id, _ = app_and_key
        variant, ctx, iid = _train(app_id)
        server, service = create_query_server(
            variant, host="127.0.0.1", port=0, ctx=ctx
        )
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}"

            def one(t):
                return http(
                    "POST", f"{url}/queries.json",
                    {"user": f"u{t % 8}", "num": 2},
                )[0]

            # 2× probe window + slack → the decision must have been made
            n = 2 * service._batcher.PROBE_QUERIES + 40
            with concurrent.futures.ThreadPoolExecutor(8) as ex:
                statuses = list(ex.map(one, range(n)))
            assert all(s == 200 for s in statuses)
            mb = service._batcher.to_dict()
            assert mb["mode"] in ("on", "off"), mb
            assert mb["probe"]["batchedP50Ms"] is not None
            assert mb["probe"]["perQueryP50Ms"] is not None
            if mb["mode"] == "off":
                # bypass: further queries never touch the batch queue
                before = service._batcher.to_dict()["batchedQueries"]
                for t in range(10):
                    assert one(t) == 200
                assert service._batcher.to_dict()["batchedQueries"] == before

        finally:
            server.stop()

    def test_microbatch_adaptive_opt_out(self, app_and_key, monkeypatch):
        """PIO_TPU_SERVE_MICROBATCH_ADAPTIVE=0 pins coalescing on."""
        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_US", "500")
        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_ADAPTIVE", "0")
        app_id, _ = app_and_key
        variant, ctx, iid = _train(app_id)
        server, service = create_query_server(
            variant, host="127.0.0.1", port=0, ctx=ctx
        )
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            for t in range(6):
                assert http(
                    "POST", f"{url}/queries.json", {"user": "u1", "num": 2}
                )[0] == 200
            mb = service._batcher.to_dict()
            assert mb["mode"] == "on"
            assert mb["batchedQueries"] >= 6
        finally:
            server.stop()

    def test_query_server_prometheus_metrics(self, queryserver):
        import urllib.request

        url, _, _ = queryserver
        http("POST", f"{url}/queries.json", {"user": "u1", "num": 2})
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
            assert r.status == 200
            text = r.read().decode()
        assert "pio_tpu_queries_total{" in text
        assert 'quantile="0.95"' in text

    def test_stage_histograms_after_query(self, queryserver):
        """Acceptance criterion: queue/execute/serialize stage histograms
        show non-zero observations after a served request, and the whole
        exposition round-trips through the obs text parser."""
        import urllib.request

        from pio_tpu.obs.promparse import parse_prometheus_text

        url, _, _ = queryserver
        http("POST", f"{url}/queries.json", {"user": "u1", "num": 2})
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
            pm = parse_prometheus_text(r.read().decode())
        assert pm.value("pio_tpu_queries_total", engine_id="rec-srv") >= 1
        assert pm.types["pio_tpu_query_stage_seconds"] == "histogram"
        for stage in ("parse", "queue", "execute", "serialize"):
            assert pm.value(
                "pio_tpu_query_stage_seconds_count",
                engine_id="rec-srv", stage=stage,
            ) >= 1, f"stage {stage} never observed"
        buckets = pm.histogram_buckets(
            "pio_tpu_query_stage_seconds", engine_id="rec-srv", stage="execute"
        )
        cums = [c for _, c in buckets]
        assert cums == sorted(cums) and cums[-1] >= 1
        # legacy summary surface still present alongside the histograms
        assert pm.value("pio_tpu_query_latency_ms_count", engine_id="rec-srv") >= 1

    def test_stats_stages_and_window(self, queryserver):
        url, _, _ = queryserver
        for _ in range(3):
            http("POST", f"{url}/queries.json", {"user": "u1", "num": 2})
        _, stats = http("GET", f"{url}/stats.json")
        st = stats["stages"]
        for stage in ("queue", "execute", "serialize"):
            assert st[stage]["count"] >= 3
            assert st[stage]["avgMs"] is not None
        _, win = http("GET", f"{url}/stats.json?window=60")
        assert win["windowSeconds"] == 60.0
        assert win["requestCount"] >= 3
        _, zero = http("GET", f"{url}/stats.json?window=0.000001")
        assert zero["requestCount"] == 0

    def test_traces_json(self, queryserver):
        url, _, _ = queryserver
        for _ in range(2):
            http("POST", f"{url}/queries.json", {"user": "u1", "num": 2})
        _, body = http("GET", f"{url}/traces.json?n=10")
        traces = body["traces"]
        assert len(traces) >= 2
        t = traces[0]
        assert t["kind"] == "query"
        stages = [s["stage"] for s in t["spans"]]
        for stage in ("parse", "queue", "execute", "serialize"):
            assert stage in stages
        totals = [x["totalMs"] for x in traces]
        assert totals == sorted(totals, reverse=True)  # slowest-first default
        _, recent = http("GET", f"{url}/traces.json?n=1&order=recent")
        assert len(recent["traces"]) == 1

    def test_trace_header_adoption_and_waterfall(self, queryserver):
        """ISSUE 6: an inbound X-Pio-Trace id is adopted (one id names
        the whole cross-process waterfall), echoed on the response, and
        the retrieved trace shows the full accept→write budget."""
        import time

        url, _, _ = queryserver
        status, body, hdrs = http_h(
            "POST", f"{url}/queries.json", {"user": "u1", "num": 2},
            headers={"X-Pio-Trace": "client-77/frontend.call"},
        )
        assert status == 200
        assert hdrs.get("x-pio-trace") == "client-77"
        # the write span lands from the post-flush hook — poll briefly
        for _ in range(100):
            status, got = http("GET", f"{url}/traces.json?id=client-77")
            if status == 200:
                stages = {s["stage"] for s in got["traces"][0]["spans"]}
                if "write" in stages:
                    break
            time.sleep(0.01)
        t = got["traces"][0]
        assert t["id"] == "client-77" and t["parent"] == "frontend.call"
        assert {"accept", "admit", "parse", "queue", "execute",
                "serialize", "write"} <= stages, stages
        assert "execute.device" in stages
        accepts = [s for s in t["spans"] if s["stage"] == "accept"]
        assert accepts[0]["startMs"] == 0.0
        # malformed header: fresh minted id, never a 400
        status, _, hdrs = http_h(
            "POST", f"{url}/queries.json", {"user": "u1", "num": 2},
            headers={"X-Pio-Trace": "not valid!"},
        )
        assert status == 200
        assert hdrs.get("x-pio-trace", "").startswith("query-")

    def test_hotpath_budget_attribution(self, queryserver):
        """/debug/hotpath.json: top-level stages tile the e2e average;
        dotted substages are reported but excluded from the sum."""
        import time

        url, _, _ = queryserver
        N = 8
        for _ in range(N):
            assert http(
                "POST", f"{url}/queries.json", {"user": "u1", "num": 2}
            )[0] == 200
        for _ in range(100):
            _, p = http("GET", f"{url}/debug/hotpath.json?pool=0")
            if p["requestCount"] >= N:
                break
            time.sleep(0.01)
        assert p["requestCount"] >= N
        stages = {s["stage"] for s in p["stages"]}
        assert {"accept", "admit", "parse", "queue", "execute",
                "serialize", "write"} <= stages
        assert not any("." in s for s in stages)
        assert {s["stage"] for s in p["substages"]} >= {"execute.device"}
        # the attribution acceptance bar is enforced on the bench run
        # (≥0.95); here just require the budget to be coherent and most
        # of the request to have named owners even under CI jitter
        assert p["e2e"]["avgMs"] > 0
        assert 0.5 < p["attributedFraction"] <= 1.5, p
        assert p["residualMsPerRequest"] == pytest.approx(
            p["e2e"]["avgMs"] - p["attributedMsPerRequest"], abs=0.01
        )

    def test_microbatch_batch_trace_links_members(self, app_and_key,
                                                  monkeypatch):
        """The micro-batch dispatch gets ONE trace linking every member
        request trace, and each member's waterfall back-links the batch
        it rode (meta.microbatch)."""
        import concurrent.futures

        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_US", "2000")
        app_id, _ = app_and_key
        variant, ctx, _ = _train(app_id)
        server, service = create_query_server(
            variant, host="127.0.0.1", port=0, ctx=ctx
        )
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            member_ids = [f"member-{i}" for i in range(12)]

            def one(tid):
                return http_h(
                    "POST", f"{url}/queries.json", {"user": "u1", "num": 2},
                    headers={"X-Pio-Trace": tid},
                )[0]

            with concurrent.futures.ThreadPoolExecutor(12) as ex:
                assert all(s == 200 for s in ex.map(one, member_ids))
            traces = {t["id"]: t for t in service.tracer.recent(100)}
            batches = [t for t in traces.values()
                       if t["kind"] == "microbatch"]
            assert batches, "no batch trace minted"
            linked = {tid for b in batches for tid in b.get("links", [])}
            # every member that actually coalesced is linked; solo
            # dispatches (batch of 1) still link their one member
            assert linked & set(member_ids), (linked, member_ids)
            multi = [b for b in batches if len(b.get("links", [])) > 1]
            assert multi, [b.get("links") for b in batches]
            # back-link: the member names the batch whose execute it shared
            b = multi[0]
            for tid in b["links"]:
                assert traces[tid]["meta"]["microbatch"] == b["id"]
            # device time lands on the batch trace, not double-counted on
            # each member (budget math: N members + 1 batch span)
            bstages = [s["stage"] for s in b["spans"]]
            assert "execute.device" in bstages
            assert "execute" not in bstages
            member_stages = [s["stage"] for s in traces[b["links"][0]]["spans"]]
            assert "execute" in member_stages
            assert "execute.device" not in member_stages
        finally:
            server.stop()

    def test_microbatch_stage_timings(self, app_and_key, monkeypatch):
        """On the micro-batch path, queue and execute stage timings come
        from the worker thread (drain wait + shared dispatch) and land in
        the same histogram the inline path uses."""
        import urllib.request

        from pio_tpu.obs.promparse import parse_prometheus_text

        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_US", "2000")
        app_id, _ = app_and_key
        variant, ctx, _ = _train(app_id)
        server, service = create_query_server(
            variant, host="127.0.0.1", port=0, ctx=ctx
        )
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            for _ in range(4):
                assert http(
                    "POST", f"{url}/queries.json", {"user": "u1", "num": 2}
                )[0] == 200
            with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
                pm = parse_prometheus_text(r.read().decode())
            for stage in ("queue", "execute"):
                assert pm.value(
                    "pio_tpu_query_stage_seconds_count",
                    engine_id="rec-srv", stage=stage,
                ) >= 4
            # queue times are real waits, not zero-stamped
            assert pm.value(
                "pio_tpu_query_stage_seconds_sum",
                engine_id="rec-srv", stage="queue",
            ) > 0
        finally:
            server.stop()

    def test_microbatch_poisoned_query_falls_back_concurrently(self):
        """One query whose batch dispatch fails must not serialize its
        batch-mates behind the worker thread: the fallback per-query
        predict runs in each request's own thread, and only the poisoned
        query's caller sees the error."""
        import concurrent.futures

        from pio_tpu.server.query_server import _MicroBatcher

        class StubService:
            def _predict_batch(self, queries):
                raise RuntimeError("poisoned batch")

            def _predict_one(self, query):
                if query == "bad":
                    raise ValueError("bad query")
                return f"ok:{query}"

        mb = _MicroBatcher(StubService(), window_s=0.005)
        try:
            def one(q):
                try:
                    return mb.submit(q)
                except ValueError as e:
                    return f"err:{e}"

            qs = [f"q{i}" for i in range(8)] + ["bad"]
            with concurrent.futures.ThreadPoolExecutor(9) as ex:
                got = list(ex.map(one, qs))
            assert got[:8] == [f"ok:q{i}" for i in range(8)]
            assert got[8] == "err:bad query"
        finally:
            mb.stop()

    def test_no_trained_instance_errors(self, app_and_key):
        variant = variant_from_dict({**VARIANT, "id": "never-trained"})
        with pytest.raises(RuntimeError, match="no COMPLETED engine instance"):
            create_query_server(variant, host="127.0.0.1", port=0)


class TestOpsEndpoints:
    """The serving ops plane end-to-end (ISSUE 2 acceptance): deep
    probes, log/trace correlation over real HTTP, live SLO evaluation,
    and strict query-param validation."""

    def test_healthz_and_readyz_report_checks(self, queryserver):
        url, _, _ = queryserver
        status, report = http("GET", f"{url}/healthz")
        assert status == 200 and report["status"] == "ok"
        assert set(report["checks"]) >= {"http_loop", "microbatch_worker"}
        assert all(c["ok"] for c in report["checks"].values())
        status, report = http("GET", f"{url}/readyz")
        assert status == 200 and report["status"] == "ready"
        assert set(report["checks"]) >= {"engine", "storage"}
        assert "instance" in report["checks"]["engine"]["detail"]

    def test_undeploy_flips_readyz_not_healthz(self, queryserver):
        url, _, _ = queryserver
        http("POST", f"{url}/undeploy", {})
        status, report = http("GET", f"{url}/readyz")
        assert status == 503
        assert report["checks"]["engine"]["detail"] == "undeployed"
        # the process is still healthy — a restart would fix nothing
        assert http("GET", f"{url}/healthz")[0] == 200

    def test_dead_microbatch_thread_flips_healthz(self, app_and_key,
                                                  monkeypatch):
        """Acceptance: killing the micro-batch worker thread turns
        /healthz into a 503 naming the dead thread (the condition the
        pool supervisor kills-and-respawns on)."""
        monkeypatch.setenv("PIO_TPU_SERVE_MICROBATCH_US", "500")
        app_id, _ = app_and_key
        variant, ctx, _ = _train(app_id)
        server, service = create_query_server(
            variant, host="127.0.0.1", port=0, ctx=ctx
        )
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            assert http("GET", f"{url}/healthz")[0] == 200
            service._batcher.stop()
            service._batcher._thread.join(timeout=5)
            assert not service._batcher._thread.is_alive()
            status, report = http("GET", f"{url}/healthz")
            assert status == 503 and report["status"] == "unhealthy"
            assert not report["checks"]["microbatch_worker"]["ok"]
            assert "dead" in report["checks"]["microbatch_worker"]["detail"]
        finally:
            server.stop()

    def test_logs_join_traces_by_trace_id(self, queryserver):
        """Acceptance: a served query emits a JSON log record whose
        trace_id matches the id /traces.json reports, and /logs.json can
        filter down to exactly that request's lines."""
        url, _, _ = queryserver
        assert http(
            "POST", f"{url}/queries.json", {"user": "u1", "num": 2}
        )[0] == 200
        _, body = http("GET", f"{url}/traces.json")
        (trace,) = body["traces"]
        tid = trace["id"]
        status, logs = http("GET", f"{url}/logs.json?trace_id={tid}")
        assert status == 200
        assert logs["logs"], f"no log lines for trace {tid}"
        assert all(e["trace_id"] == tid for e in logs["logs"])
        assert any("served query" in e["msg"] for e in logs["logs"])
        # every record is the full structured shape
        e = logs["logs"][-1]
        assert {"ts", "level", "logger", "msg", "trace_id", "span"} <= set(e)

    def test_slo_json_from_live_histograms(self, app_and_key):
        """Acceptance: with --slo p99=50ms:99.9 declared, /slo.json
        reports burn rate and remaining error budget computed from the
        live pio_tpu_request_seconds histogram, and the same numbers export
        as gauges on /metrics."""
        app_id, _ = app_and_key
        variant, ctx, _ = _train(app_id)
        server, _ = create_query_server(
            variant, host="127.0.0.1", port=0, ctx=ctx,
            slos=["p99=50ms:99.9", "availability=99.9"],
        )
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            for _ in range(3):
                assert http(
                    "POST", f"{url}/queries.json", {"user": "u1"}
                )[0] == 200
            status, body = http("GET", f"{url}/slo.json")
            assert status == 200 and body["configured"] is True
            by_name = {s["name"]: s for s in body["slos"]}
            lat = by_name["latency_p99"]
            assert lat["kind"] == "latency" and lat["thresholdMs"] == 50.0
            assert lat["total"] >= 3
            assert "300s" in lat["burnRates"] and "3600s" in lat["burnRates"]
            assert -1000.0 <= lat["errorBudgetRemaining"] <= 1.0
            avail = by_name["availability"]
            assert avail["errors"] == 0 and avail["errorBudgetRemaining"] == 1.0
            assert all(not a["firing"] for a in avail["alerts"])
            with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
                text = r.read().decode()
            assert 'pio_tpu_slo_error_budget_remaining{slo="latency_p99"}' \
                in text
            assert 'pio_tpu_slo_burn_rate{slo="availability",window="300s"}' \
                in text
            assert "# TYPE pio_tpu_log_messages_total counter" in text
        finally:
            server.stop()

    def test_unconfigured_slo_endpoint(self, queryserver):
        url, _, _ = queryserver
        status, body = http("GET", f"{url}/slo.json")
        assert status == 200
        assert body == {"slos": [], "configured": False}

    def test_query_param_validation(self, queryserver):
        """Satellite: ?n= and ?window= are validated — negatives and
        non-numerics are a 400, oversized n clamps to the ring size."""
        url, service, _ = queryserver
        http("POST", f"{url}/queries.json", {"user": "u1"})
        for bad in ("/traces.json?n=-1", "/traces.json?n=abc",
                    "/stats.json?window=abc", "/stats.json?window=-3",
                    "/stats.json?window=nan",
                    "/logs.json?n=-5", "/logs.json?n=1.5",
                    "/logs.json?level=loud"):
            status, body = http("GET", url + bad)
            assert status == 400, f"{bad} -> {status} {body}"
            assert "message" in body
        # above the ring capacity: clamp, not error
        status, body = http(
            "GET", f"{url}/traces.json?n={service.tracer._ring_cap + 999}"
        )
        assert status == 200 and len(body["traces"]) >= 1
        status, body = http("GET", f"{url}/logs.json?n=999999")
        assert status == 200 and len(body["logs"]) <= body["ringCapacity"]

    def test_eventserver_probes_logs_and_validation(self, eventserver,
                                                    app_and_key):
        _, key = app_and_key
        status, report = http("GET", f"{eventserver}/healthz")
        assert status == 200 and report["status"] == "ok"
        assert "group_commit" in report["checks"]
        status, report = http("GET", f"{eventserver}/readyz")
        assert status == 200 and report["status"] == "ready"
        assert report["checks"]["storage"]["ok"]
        # ingest one event, then the ops surface
        assert http(
            "POST", f"{eventserver}/events.json?accessKey={key}", EV
        )[0] == 201
        status, body = http("GET", f"{eventserver}/logs.json")
        assert status == 200 and body["ringCapacity"] >= 1
        status, body = http("GET", f"{eventserver}/slo.json")
        assert status == 200 and body["configured"] is False
        assert http("GET", f"{eventserver}/stats.json?window=abc")[0] == 400
        assert http("GET", f"{eventserver}/logs.json?n=-2")[0] == 400
        assert http("GET", f"{eventserver}/traces.json?n=-2")[0] == 400


class TestHTTPHardening:
    """Hand-rolled HTTP/1.1 parser edge cases (pio_tpu/server/http.py):
    framing attacks and resource-exhaustion vectors must be rejected
    before any body is consumed or buffered."""

    @pytest.fixture()
    def echo(self):
        from pio_tpu.server.http import JsonHTTPServer, Router

        r = Router()
        r.add("POST", "/echo", lambda req: (200, {"got": req.body}))
        srv = JsonHTTPServer(r, "127.0.0.1", 0, name="echo")
        srv.start()
        yield srv.port
        srv.stop()

    @staticmethod
    def _raw(port, payload: bytes) -> bytes:
        import socket

        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        try:
            s.sendall(payload)
            return s.recv(65536)
        finally:
            s.close()

    def test_negative_content_length_rejected(self, echo):
        resp = self._raw(
            echo,
            b"POST /echo HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: -1\r\n\r\n",
        )
        assert b"400" in resp.split(b"\r\n", 1)[0], resp

    def test_differing_duplicate_content_length_rejected(self, echo):
        resp = self._raw(
            echo,
            b"POST /echo HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 5\r\nContent-Length: 50\r\n\r\nhello",
        )
        assert b"400" in resp.split(b"\r\n", 1)[0], resp

    def test_equal_duplicate_content_length_collapses(self, echo):
        resp = self._raw(
            echo,
            b"POST /echo HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 2\r\nContent-Length: 2\r\n\r\n{}",
        )
        assert b"200" in resp.split(b"\r\n", 1)[0], resp

    def test_structured_body_ram_cap(self, echo, monkeypatch):
        import pio_tpu.server.http as http_mod

        monkeypatch.setattr(http_mod, "MAX_JSON_BODY_MB", 0.001)  # 1 KiB
        resp = self._raw(
            echo,
            b"POST /echo HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: 10000\r\n\r\n" + b"x" * 100,
        )
        assert b"413" in resp.split(b"\r\n", 1)[0], resp

    def test_chunked_transfer_rejected(self, echo):
        resp = self._raw(
            echo,
            b"POST /echo HTTP/1.1\r\nHost: x\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            b"2\r\nhi\r\n0\r\n\r\n",
        )
        assert b"411" in resp.split(b"\r\n", 1)[0], resp

    def test_http10_and_keepalive_header(self, echo):
        import socket

        s = socket.create_connection(("127.0.0.1", echo), timeout=10)
        try:
            # HTTP/1.0 without keep-alive: served, then connection closes
            s.sendall(
                b"POST /echo HTTP/1.0\r\nHost: x\r\n"
                b"Content-Length: 2\r\n\r\n{}"
            )
            buf = b""
            while True:
                got = s.recv(65536)
                if not got:
                    break
                buf += got
            assert b"200" in buf.split(b"\r\n", 1)[0]
            assert b"Connection: close" in buf
        finally:
            s.close()

    def test_octet_stream_capped_without_large_uploads(self, echo,
                                                       monkeypatch):
        """Servers that did not opt into large uploads apply the tight
        structured-body cap to octet-stream bodies too — otherwise every
        connection could spool MAX_BODY_MB of unauthenticated bytes."""
        import pio_tpu.server.http as http_mod

        monkeypatch.setattr(http_mod, "MAX_JSON_BODY_MB", 0.001)  # 1 KiB
        resp = self._raw(
            echo,
            b"POST /echo HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/octet-stream\r\n"
            b"Content-Length: 10000\r\n\r\n" + b"x" * 100,
        )
        assert b"413" in resp.split(b"\r\n", 1)[0], resp

    def test_blob_server_still_accepts_large_octet_stream(self, tmp_path,
                                                          monkeypatch):
        import pio_tpu.server.http as http_mod
        from pio_tpu.server.blob_server import create_blob_server

        monkeypatch.setattr(http_mod, "MAX_JSON_BODY_MB", 0.001)  # 1 KiB
        server = create_blob_server(
            str(tmp_path / "s"), host="127.0.0.1", port=0
        )
        server.start()
        try:
            body = b"y" * 4096  # above the structured cap
            resp = self._raw(
                server.port,
                b"PUT /blobs/objects/big HTTP/1.1\r\nHost: x\r\n"
                b"Content-Type: application/octet-stream\r\n"
                + f"Content-Length: {len(body)}\r\n\r\n".encode() + body,
            )
            assert b"201" in resp.split(b"\r\n", 1)[0], resp
        finally:
            server.stop()

    def test_pre_body_exception_returns_500(self):
        """A pre_body bug must produce an HTTP 500, not a dropped
        connection with a raw socketserver traceback."""
        from pio_tpu.server.http import JsonHTTPServer, Router

        r = Router()
        r.add("POST", "/x", lambda req: (200, {}))

        def boom(req):
            raise ValueError("bug in pre_body")

        srv = JsonHTTPServer(
            r, "127.0.0.1", 0, name="boom", pre_body=boom
        ).start()
        try:
            resp = self._raw(
                srv.port,
                b"POST /x HTTP/1.1\r\nHost: x\r\n"
                b"Content-Length: 2\r\n\r\n{}",
            )
            assert b"500" in resp.split(b"\r\n", 1)[0], resp
            assert b"internal server error" in resp
        finally:
            srv.stop()

    def test_http10_keepalive_echoed_and_reusable(self, echo):
        """Honoring an HTTP/1.0 keep-alive must be ECHOED, or a
        conforming 1.0 client assumes close and never reuses the
        connection we keep holding open."""
        import socket

        s = socket.create_connection(("127.0.0.1", echo), timeout=10)
        try:
            req = (
                b"POST /echo HTTP/1.0\r\nHost: x\r\n"
                b"Connection: keep-alive\r\nContent-Length: 2\r\n\r\n{}"
            )
            s.sendall(req)
            buf = s.recv(65536)
            assert b"200" in buf.split(b"\r\n", 1)[0], buf
            assert b"Connection: keep-alive" in buf
            s.sendall(req)  # the connection is genuinely reusable
            buf2 = s.recv(65536)
            assert b"200" in buf2.split(b"\r\n", 1)[0], buf2
        finally:
            s.close()

    def test_unauth_json_put_rejected_before_body(self, tmp_path):
        """The pre-body auth guard applies to ALL content types — a big
        JSON-typed body must not be buffered in RAM before the 401."""
        import socket

        from pio_tpu.server.blob_server import create_blob_server

        server = create_blob_server(
            str(tmp_path / "s"), host="127.0.0.1", port=0,
            access_key="sekrit",
        )
        server.start()
        try:
            s = socket.create_connection(
                ("127.0.0.1", server.port), timeout=10
            )
            try:
                s.sendall(
                    b"PUT /blobs/objects/x HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: 10000000\r\n\r\n"
                )
                resp = s.recv(4096)  # 401 without the body ever sent
                assert b"401" in resp.split(b"\r\n", 1)[0], resp
            finally:
                s.close()
        finally:
            server.stop()
