"""Doc-rot guards for the quickstart walkthroughs (docs/quickstart.md).

The full lifecycle itself is executed by tests/test_quickstart_scenario.py;
here we pin the doc's inline payloads: every ```json block must parse, and
every event payload in it must pass the Event Server's own validation
(`Event.from_api_dict`) — so the walkthrough can't drift from the wire
contract it documents.
"""

import json
import os
import re

DOC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "quickstart.md",
)


def _json_blocks():
    text = open(DOC).read()
    return re.findall(r"```json\n(.*?)```", text, re.DOTALL)


def test_all_json_blocks_parse():
    blocks = _json_blocks()
    assert len(blocks) >= 15, "walkthrough lost its examples?"
    for b in blocks:
        json.loads(b)


def test_event_payloads_pass_server_validation():
    from pio_tpu.data.event import Event

    events = [
        json.loads(b) for b in _json_blocks()
        if '"event"' in b and '"entityType"' in b
    ]
    assert len(events) >= 5  # one per event-ingesting template section
    for d in events:
        ev = Event.from_api_dict(d)
        assert ev.entity_id
        # reserved-event rules enforced ($set needs properties, etc.)
        if ev.event.startswith("$"):
            assert ev.properties


def test_rest_api_doc_routes_exist(tmp_path):
    """Every route documented in docs/rest-api.md's tables for the event,
    query, and blob servers must exist on that server's Router (method +
    path pattern) — the doc cannot drift from the wire surface it
    documents. (Dashboard/Admin are excluded: UI pages + trivial CRUD
    covered by their own tests.)"""
    import re as _re

    from pio_tpu.server.blob_server import BlobServerService
    from pio_tpu.server.event_server import EventServerService
    from pio_tpu.server.query_server import QueryServerService
    from pio_tpu.workflow.engine_json import variant_from_dict

    doc = open(os.path.join(os.path.dirname(DOC), "rest-api.md")).read()

    def doc_routes(section: str, until: str):
        block = doc.split(section, 1)[1].split(until, 1)[0]
        out = []
        for m in _re.finditer(
            r"^\| (GET|POST|PUT|DELETE|HEAD) \| `([^`]+)`", block,
            _re.MULTILINE,
        ):
            path = m.group(2).split("?")[0]
            out.append((m.group(1), path))
        return out

    def router_matches(router, method, path):
        # substitute doc placeholders with plausible concrete values
        concrete = (
            path.replace("<id>", "abc123").replace("<key>", "objects/x")
            .replace("<connector>", "segmentio")
        )
        return any(
            m == method and pat.match(concrete)
            for m, pat, _ in router._routes
        )

    ev = EventServerService()
    for method, path in doc_routes("## Event Server", "## Query Server"):
        assert router_matches(ev.router, method, path), (method, path)

    class _StubQueryService(QueryServerService):
        # routes are what's under test; skip the model load
        def _load(self, instance_id):
            self.engine = self.engine_params = None
            self.instance_id = "stub"
            self.pairs, self.serving, self.query_class = [], None, None

    qs = _StubQueryService(variant_from_dict({
        "id": "doc-rot", "engineFactory": "x.y",
        "algorithms": [{"name": "a", "params": {}}],
    }))
    for method, path in doc_routes("## Query Server", "## Dashboard"):
        assert router_matches(qs.router, method, path), (method, path)

    blob = BlobServerService(root=str(tmp_path / "blob"))
    for method, path in doc_routes("## Blob server", "## TLS"):
        assert router_matches(blob.router, method, path), (method, path)


def test_query_shapes_bind_to_template_query_classes():
    """The documented queries must bind to the templates' query dataclasses
    exactly as the query server would bind them."""
    from pio_tpu.controller.params import params_from_dict
    from pio_tpu.templates import (
        classification, recommendation, sequence, similarproduct,
        textclassification,
    )

    cases = [
        (recommendation.Query, {"user": "u1", "num": 4}),
        (similarproduct.Query, {"items": ["i1", "i4"], "num": 4}),
        (classification.Query, {"attrs": [2.0, 0.0, 1.0]}),
        (textclassification.Query, {"text": "great product"}),
        (sequence.Query, {"history": ["i1", "i5"], "num": 4}),
    ]
    for qc, payload in cases:
        q = params_from_dict(qc, payload)
        assert q is not None
