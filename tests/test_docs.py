"""Doc-rot guards for the quickstart walkthroughs (docs/quickstart.md).

The full lifecycle itself is executed by tests/test_quickstart_scenario.py;
here we pin the doc's inline payloads: every ```json block must parse, and
every event payload in it must pass the Event Server's own validation
(`Event.from_api_dict`) — so the walkthrough can't drift from the wire
contract it documents.
"""

import json
import os
import re

DOC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "quickstart.md",
)


def _json_blocks():
    text = open(DOC).read()
    return re.findall(r"```json\n(.*?)```", text, re.DOTALL)


def test_all_json_blocks_parse():
    blocks = _json_blocks()
    assert len(blocks) >= 15, "walkthrough lost its examples?"
    for b in blocks:
        json.loads(b)


def test_event_payloads_pass_server_validation():
    from pio_tpu.data.event import Event

    events = [
        json.loads(b) for b in _json_blocks()
        if '"event"' in b and '"entityType"' in b
    ]
    assert len(events) >= 5  # one per event-ingesting template section
    for d in events:
        ev = Event.from_api_dict(d)
        assert ev.entity_id
        # reserved-event rules enforced ($set needs properties, etc.)
        if ev.event.startswith("$"):
            assert ev.properties


def test_query_shapes_bind_to_template_query_classes():
    """The documented queries must bind to the templates' query dataclasses
    exactly as the query server would bind them."""
    from pio_tpu.controller.params import params_from_dict
    from pio_tpu.templates import (
        classification, recommendation, sequence, similarproduct,
        textclassification,
    )

    cases = [
        (recommendation.Query, {"user": "u1", "num": 4}),
        (similarproduct.Query, {"items": ["i1", "i4"], "num": 4}),
        (classification.Query, {"attrs": [2.0, 0.0, 1.0]}),
        (textclassification.Query, {"text": "great product"}),
        (sequence.Query, {"history": ["i1", "i5"], "num": 4}),
    ]
    for qc, payload in cases:
        q = params_from_dict(qc, payload)
        assert q is not None
