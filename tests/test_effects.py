"""Interprocedural effect analysis + frame-layout verifier units.

Fixture modules live in string literals (the clean gate lints tests/
too, and only sees constants here). The guard class at the bottom runs
against the real tree: the three shipped frame families must each parse
into at least one verified writer/reader pair, and the seeded hot-path
roots must be discovered from their markers.
"""

from __future__ import annotations

import os
import textwrap

from pio_tpu.analysis import run_lint
from pio_tpu.analysis.core import Finding, collect_files, parse_module
from pio_tpu.analysis.effects import (
    EffectAnalysis,
    effects_inventory,
    frame_inventory,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def analyze(tmp_path, source, *, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    module = parse_module(str(p))
    assert not isinstance(module, Finding), module
    return EffectAnalysis([module])


def lint_src(tmp_path, source, *, name="fixture.py", rules=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return run_lint([str(p)], rule_ids=rules)


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# effect-summary propagation


class TestPropagation:
    def test_direct_effects(self, tmp_path):
        a = analyze(tmp_path, """
        import json
        import time

        def f(payload):
            time.sleep(0.1)
            doc = json.loads(payload.decode("utf-8"))
            items = [x for x in doc]
            return items
        """)
        (qual,) = [q for q in a.fns if q.endswith(".f")]
        assert a.trans[qual] >= {"blocks", "json_codec",
                                 "copies_bytes", "allocates"}

    def test_transitive_two_frames(self, tmp_path):
        a = analyze(tmp_path, """
        import time

        def leaf():
            time.sleep(0.1)

        def mid():
            leaf()

        def top():
            mid()
        """)
        top = next(q for q in a.fns if q.endswith(".top"))
        assert "blocks" in a.trans[top]
        sites = a.reachable_sites(top, ("blocks",))
        assert len(sites) == 1
        _site, chain = sites[0]
        assert [q.rsplit(".", 1)[-1] for q in chain] == ["top", "mid", "leaf"]

    def test_recursive_cycle_terminates(self, tmp_path):
        a = analyze(tmp_path, """
        import time

        def ping(n):
            if n:
                pong(n - 1)

        def pong(n):
            time.sleep(0.01)
            ping(n)
        """)
        ping = next(q for q in a.fns if q.endswith(".ping"))
        pong = next(q for q in a.fns if q.endswith(".pong"))
        assert "blocks" in a.trans[ping]
        assert "blocks" in a.trans[pong]

    def test_self_method_edges(self, tmp_path):
        a = analyze(tmp_path, """
        import time

        class C:
            def leaf(self):
                time.sleep(0.1)

            def top(self):
                self.leaf()
        """)
        top = next(q for q in a.fns if q.endswith("C.top"))
        assert "blocks" in a.trans[top]

    def test_nested_def_not_attributed(self, tmp_path):
        # a closure defined in f runs elsewhere (or never)
        a = analyze(tmp_path, """
        import time

        def f():
            def later():
                time.sleep(1.0)
            return later
        """)
        f = next(q for q in a.fns if q.endswith(".f"))
        assert "blocks" not in a.trans[f]

    def test_wallclock_informational(self, tmp_path):
        a = analyze(tmp_path, """
        import time

        def f():
            return time.time()
        """)
        f = next(q for q in a.fns if q.endswith(".f"))
        assert a.trans[f] == {"wallclock"}


# ---------------------------------------------------------------------------
# hot-path root discovery + rule findings


class TestHotpathRules:
    def test_root_discovery_from_markers(self, tmp_path):
        a = analyze(tmp_path, """
        def plain():
            pass

        def handler(req):  # pio: hotpath
            pass

        # pio: hotpath=zerocopy
        def packer(codes):
            pass
        """)
        roots = {r.qual.rsplit(".", 1)[-1]: r.marker for r in a.roots()}
        assert roots == {"handler": "", "packer": "zerocopy"}

    def test_sleep_two_frames_down_is_finding_with_chain(self, tmp_path):
        findings = lint_src(tmp_path, """
        import time

        def leaf():
            time.sleep(0.1)

        def mid():
            leaf()

        def handler(req):  # pio: hotpath
            mid()
        """, rules=["hotpath-blocking"])
        assert rule_ids(findings) == ["hotpath-blocking"]
        assert "handler -> mid -> leaf" in findings[0].message
        assert "time.sleep" in findings[0].message

    def test_seeded_json_below_zerocopy_root(self, tmp_path):
        findings = lint_src(tmp_path, """
        import json

        def encode(body):
            return json.dumps(body)

        def submit(body):  # pio: hotpath=zerocopy
            return encode(body)
        """, rules=["hotpath-zero-copy"])
        assert rule_ids(findings) == ["hotpath-zero-copy"]
        assert "json_codec" in findings[0].message
        assert "submit -> encode" in findings[0].message

    def test_plain_hotpath_allows_json(self, tmp_path):
        # json is only contraband on zerocopy roots
        findings = lint_src(tmp_path, """
        import json

        def handler(body):  # pio: hotpath
            return json.dumps(body)
        """, rules=["hotpath-zero-copy"])
        assert findings == []

    def test_root_suppression_covers_reachable_findings(self, tmp_path):
        # satellite: disable on the ROOT function suppresses findings
        # attributed to it, not just same-line module findings
        findings = lint_src(tmp_path, """
        import time

        def leaf():
            time.sleep(0.1)

        def handler(req):  # pio: hotpath  # pio: disable=hotpath-blocking
            leaf()
        """, rules=["hotpath-blocking"])
        assert findings == []

    def test_site_suppression_covers_every_root(self, tmp_path):
        findings = lint_src(tmp_path, """
        import time

        def leaf():
            # pio: disable=hotpath-blocking
            time.sleep(0.1)

        def a(req):  # pio: hotpath
            leaf()

        def b(req):  # pio: hotpath
            leaf()
        """, rules=["hotpath-blocking"])
        assert findings == []

    def test_edge_suppression_cuts_the_chain(self, tmp_path):
        findings = lint_src(tmp_path, """
        import time

        def leaf():
            time.sleep(0.1)

        def handler(req):  # pio: hotpath
            leaf()  # pio: disable=hotpath-blocking
            time.sleep(0.2)
        """, rules=["hotpath-blocking"])
        # the direct sleep still fires; the call edge is cut
        assert len(findings) == 1
        assert "time.sleep" in findings[0].message
        assert "leaf" not in findings[0].message


# ---------------------------------------------------------------------------
# frame-layout verifier


class TestFrameLayout:
    def test_field_count_mismatch(self, tmp_path):
        findings = lint_src(tmp_path, """
        import struct

        def write(m, n, k):
            struct.pack_into("<II", m, 0, n, k)  # pio: frame=hdr

        def read(m):
            return struct.unpack_from("<III", m, 0)  # pio: frame=hdr
        """, rules=["shm-frame-layout"])
        assert rule_ids(findings) == ["shm-frame-layout"]
        text = " ".join(f.message for f in findings)
        assert "hdr" in text and "field count" in text

    def test_one_byte_size_mismatch(self, tmp_path):
        # writer pads the record to 12 bytes, reader assumes 11
        findings = lint_src(tmp_path, """
        import struct

        def write(m, a, b):
            struct.pack_into("<QHBx", m, 0, a, b, 1)  # pio: frame=rec

        def read(m):
            return struct.unpack_from("<QHB", m, 0)  # pio: frame=rec
        """, rules=["shm-frame-layout"])
        assert rule_ids(findings) == ["shm-frame-layout"]
        text = " ".join(f.message for f in findings)
        assert "rec" in text and "byte size" in text

    def test_endianness_mismatch(self, tmp_path):
        findings = lint_src(tmp_path, """
        import struct

        def write(m, n, k):
            struct.pack_into("<II", m, 0, n, k)  # pio: frame=hdr

        def read(m):
            return struct.unpack_from(">II", m, 0)  # pio: frame=hdr
        """, rules=["shm-frame-layout"])
        assert rule_ids(findings) == ["shm-frame-layout"]
        assert any("endianness" in f.message for f in findings)

    def test_matching_pair_is_clean(self, tmp_path):
        findings = lint_src(tmp_path, """
        import struct

        HDR = struct.Struct("<QQI4x")  # pio: frame=slot

        def write(m, off, a, b):
            struct.pack_into("<Q", m, off, a)  # pio: frame=slot
            struct.pack_into("<Q", m, off + 8, b)  # pio: frame=slot
            struct.pack_into("<I", m, off + 16, 1)  # pio: frame=slot

        def read(m, off):
            return HDR.unpack_from(m, off)
        """, rules=["shm-frame-layout"])
        assert findings == []

    def test_unassigned_struct_site_in_frame_module(self, tmp_path):
        findings = lint_src(tmp_path, """
        import struct

        def write(m, n):
            struct.pack_into("<I", m, 0, n)  # pio: frame=hdr

        def sneak(m, n):
            struct.pack_into("<H", m, 0, n)
        """, rules=["shm-frame-layout"])
        assert rule_ids(findings) == ["shm-frame-layout"]
        assert any("not" in f.message and "assigned" in f.message
                   for f in findings)

    def test_reader_inside_magic(self, tmp_path):
        findings = lint_src(tmp_path, """
        import struct

        MAGIC = b"PIOTEST1"

        def write(f, n, k):
            f.write(MAGIC)
            # pio: frame=hdr
            f.write(struct.pack("<II", n, k))

        def read(head):
            return struct.unpack_from("<II", head, 4)  # pio: frame=hdr
        """, rules=["shm-frame-layout"])
        assert any("magic" in f.message for f in findings)


# ---------------------------------------------------------------------------
# guards over the real tree


class TestRealTree:
    def _modules(self):
        mods = []
        for p in collect_files([os.path.join(REPO_ROOT, "pio_tpu")]):
            m = parse_module(p)
            if not isinstance(m, Finding):
                mods.append(m)
        return mods

    def test_real_frame_families_verify(self):
        fams = frame_inventory(self._modules())
        for fam in ("lane-slot", "metrics-stripe", "pel2-record"):
            assert fam in fams, f"frame family {fam} not discovered"
            info = fams[fam]
            assert info["writers"] >= 1, (fam, info)
            assert info["readers"] >= 1, (fam, info)
            assert info["verified"], (fam, info)
        assert fams["lane-slot"]["fields"] == 5
        assert fams["lane-slot"]["extent"] == 28

    def test_seeded_roots_discovered(self):
        inv = effects_inventory(self._modules())
        roots = {r["function"] for r in inv["roots"]}
        expected = {
            "pio_tpu.server.query_server.QueryServerService.query",
            "pio_tpu.server.query_server._MicroBatcher._run",
            "pio_tpu.server.query_server._MicroBatcher.submit",
            "pio_tpu.server.bucketcache.dispatch_bucketed",
            "pio_tpu.server.batchlane.LaneClient.submit",
            "pio_tpu.server.batchlane.LaneClient._submit_payload",
            "pio_tpu.server.batchlane.LaneClient.submit_packed",
            "pio_tpu.server.batchlane.LaneDrainer._run",
            "pio_tpu.server.batchlane.pack_query_i8",
            "pio_tpu.server.batchlane.unpack_query_i8",
            "pio_tpu.server.batchlane.packed_frame_ok",
            # ISSUE 13: the evloop front's connection path and the
            # zero-copy packed ingest
            "pio_tpu.server.evfront.EvLoopHTTPServer._run",
            "pio_tpu.server.evfront.EvLoopHTTPServer._serve_one",
            "pio_tpu.server.evfront._packed_view",
            "pio_tpu.server.query_server.QueryServerService._query_packed",
        }
        missing = expected - roots
        assert not missing, f"hot-path roots missing: {missing}"

    def test_reexport_chain_resolves_failpoint(self):
        # `from pio_tpu.faults import failpoint` goes through the
        # package __init__ re-export; the summary machinery must land
        # on the def in faults/registry.py for the sleep to be visible
        a = EffectAnalysis(self._modules())
        target = a.resolve("pio_tpu.faults.failpoint")
        assert target == "pio_tpu.faults.registry.failpoint"
        assert "blocks" in a.trans[target]
