"""DASE controller tests: params binding, engine orchestration, metrics,
FastEval memoization (reference EngineTest/JsonExtractorSuite/
MetricEvaluatorTest analogs, SURVEY.md §4)."""

import dataclasses

import pytest

from pio_tpu.controller import (
    AverageMetric,
    ComputeContext,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    MetricEvaluator,
    OptionAverageMetric,
    Params,
    ParamsError,
    StdevMetric,
    SumMetric,
    ZeroMetric,
    get_engine_factory,
    params_from_dict,
    register_engine,
)
from tests.fixtures import (
    AlgoParams,
    DSParams,
    FixtureAlgo,
    FixtureDataSource,
    FixtureModel,
    PrepParams,
    ServParams,
    fixture_engine,
)


CTX = ComputeContext.local()


# ---------------------------------------------------------------- params
@dataclasses.dataclass(frozen=True)
class PTest(Params):
    rank: int = 10
    reg: float = 0.01
    name: str = "als"
    required_field: int = dataclasses.field(default=3)


class TestParamsBinding:
    def test_defaults_and_overrides(self):
        p = params_from_dict(PTest, {"rank": 20})
        assert p.rank == 20 and p.reg == 0.01

    def test_int_coerces_to_float(self):
        assert params_from_dict(PTest, {"reg": 1}).reg == 1.0

    def test_unknown_key_rejected(self):
        with pytest.raises(ParamsError, match="unknown params.*'rnak'"):
            params_from_dict(PTest, {"rnak": 20})

    def test_type_mismatch(self):
        with pytest.raises(ParamsError):
            params_from_dict(PTest, {"rank": "ten"})
        with pytest.raises(ParamsError):
            params_from_dict(PTest, {"rank": True})

    def test_missing_required(self):
        @dataclasses.dataclass(frozen=True)
        class NeedsIt(Params):
            must: int

        with pytest.raises(ParamsError, match="missing required param 'must'"):
            params_from_dict(NeedsIt, {})
        assert params_from_dict(NeedsIt, {"must": 5}).must == 5

    def test_none_uses_defaults(self):
        assert params_from_dict(PTest, None) == PTest()

    def test_camel_case_keys_bind(self):
        """Reference wire parity: engine.json and queries use camelCase
        ("numIterations", "whiteList"); fields are snake_case."""

        from typing import Tuple

        @dataclasses.dataclass(frozen=True)
        class Cam(Params):
            num_iterations: int = 1
            white_list: Tuple[str, ...] = ()

        p = params_from_dict(
            Cam, {"numIterations": 5, "whiteList": ["a"]}
        )
        assert p.num_iterations == 5 and tuple(p.white_list) == ("a",)
        # exact field name still wins; giving both is ambiguous
        with pytest.raises(ParamsError, match="both"):
            params_from_dict(
                Cam, {"numIterations": 5, "num_iterations": 6}
            )
        with pytest.raises(ParamsError, match="unknown"):
            params_from_dict(Cam, {"numIterationsTypo": 5})


# ---------------------------------------------------------------- engine
def variant(algos=None, ds=None):
    v = {
        "id": "test",
        "engineFactory": "fixture-engine",
        "datasource": {"params": ds or {"id": 7}},
        "preparator": {"params": {"id": 8}},
        "serving": {"params": {"id": 9}},
    }
    if algos is not None:
        v["algorithms"] = algos
    return v


class TestEngine:
    def test_params_from_variant(self):
        engine = fixture_engine()
        ep = engine.params_from_variant(
            variant(algos=[{"name": "algo", "params": {"id": 1, "mult": 3}}])
        )
        assert ep.data_source_params == DSParams(id=7)
        assert ep.preparator_params == PrepParams(id=8)
        assert ep.serving_params == ServParams(id=9)
        assert ep.algorithm_params_list == (("algo", AlgoParams(id=1, mult=3)),)

    def test_variant_default_algorithms(self):
        engine = fixture_engine()
        ep = engine.params_from_variant(variant())
        assert [n for n, _ in ep.algorithm_params_list] == ["algo", "algo2"]

    def test_variant_unknown_algorithm(self):
        with pytest.raises(ParamsError, match="unknown algorithm 'nope'"):
            fixture_engine().params_from_variant(variant(algos=[{"name": "nope"}]))

    def test_train_plumbs_params_through_stages(self):
        engine = fixture_engine()
        ep = engine.params_from_variant(
            variant(algos=[
                {"name": "algo", "params": {"id": 1, "mult": 2}},
                {"name": "algo2", "params": {"id": 2, "mult": 5}},
            ])
        )
        models = engine.train(CTX, ep)
        assert models == [
            FixtureModel(algo_id=1, mult=2, prep_id=8, ds_id=7),
            FixtureModel(algo_id=2, mult=5, prep_id=8, ds_id=7),
        ]

    def test_sanity_check_runs_and_fails(self):
        engine = fixture_engine()
        ep = engine.params_from_variant(
            variant(ds={"id": 1, "fail_sanity": True},
                    algos=[{"name": "algo"}])
        )
        with pytest.raises(ValueError, match="sanity check failed"):
            engine.train(CTX, ep)
        # skip flag bypasses it
        models = engine.train(CTX, ep, skip_sanity_check=True)
        assert len(models) == 1

    def test_stop_after_flags(self):
        engine = fixture_engine()
        ep = engine.params_from_variant(variant(algos=[{"name": "algo"}]))
        assert engine.train(CTX, ep, stop_after_read=True) == []
        assert engine.train(CTX, ep, stop_after_prepare=True) == []

    def test_eval_serving_combines(self):
        engine = fixture_engine()
        ep = engine.params_from_variant(
            variant(ds={"id": 1, "eval_folds": 2},
                    algos=[{"name": "algo", "params": {"mult": 1}},
                           {"name": "algo2", "params": {"mult": 10}}])
        )
        folds = engine.eval(CTX, ep)
        assert len(folds) == 2
        info, qpa = folds[0]
        assert info == {"fold": 0}
        # serving=max over {q*1, q*10}
        assert [(q, p) for q, p, a in qpa] == [(0, 0), (1, 10), (2, 20)]
        assert [a for _, _, a in qpa] == [0, 2, 4]

    def test_registry_unknown(self):
        with pytest.raises(ParamsError, match="not registered"):
            get_engine_factory("no-such-engine")

    def test_registry_module_attr(self):
        f = get_engine_factory("tests.fixtures:fixture_engine")
        assert isinstance(f(), Engine)

    def test_mismatched_models(self):
        engine = fixture_engine()
        ep = engine.params_from_variant(variant(algos=[{"name": "algo"}]))
        with pytest.raises(ValueError, match="1 algorithms but 2 models"):
            engine.algorithms_with_models(ep, [1, 2])


# ---------------------------------------------------------------- metrics
class AbsErr(AverageMetric):
    def calculate_one(self, q, p, a):
        return abs(p - a)


class TestMetrics:
    DATA = [({}, [(0, 1.0, 2.0), (1, 5.0, 5.0)]), ({}, [(2, 0.0, 4.0)])]

    def test_average(self):
        assert AbsErr().calculate(self.DATA) == pytest.approx((1 + 0 + 4) / 3)

    def test_option_average_skips_none(self):
        class M(OptionAverageMetric):
            def calculate_one(self, q, p, a):
                return None if p == 0.0 else float(p)

        assert M().calculate(self.DATA) == pytest.approx(3.0)

    def test_sum_and_zero(self):
        class S(SumMetric):
            def calculate_one(self, q, p, a):
                return float(p)

        assert S().calculate(self.DATA) == 6.0
        assert ZeroMetric().calculate(self.DATA) == 0.0

    def test_stdev(self):
        class S(StdevMetric):
            def calculate_one(self, q, p, a):
                return float(p)

        import statistics

        assert S().calculate(self.DATA) == pytest.approx(
            statistics.pstdev([1.0, 5.0, 0.0])
        )

    def test_compare_direction(self):
        m = AbsErr()
        m.higher_is_better = False
        assert m.compare(1.0, 2.0) > 0  # lower err wins

    def test_empty_is_nan(self):
        import math

        assert math.isnan(AbsErr().calculate([]))


# ---------------------------------------------------------------- evaluator
class NegAbsErr(AverageMetric):
    """Higher-is-better form of abs error."""

    def calculate_one(self, q, p, a):
        return -abs(p - a)


class TestMetricEvaluator:
    def _params(self, mult):
        engine = fixture_engine()
        return engine.params_from_variant(
            variant(ds={"id": 1, "eval_folds": 1},
                    algos=[{"name": "algo", "params": {"mult": mult}}])
        )

    def test_picks_best(self):
        engine = fixture_engine()
        # actual = q*2, prediction = q*mult -> mult=2 is perfect
        candidates = [self._params(m) for m in (1, 2, 5)]
        result = MetricEvaluator(NegAbsErr()).evaluate(CTX, engine, candidates)
        assert result.best_index == 1
        assert result.best_score == 0.0
        assert ("algo", AlgoParams(mult=2)) in result.best_engine_params.algorithm_params_list
        assert "bestEngineParams" in result.to_json()

    def test_fast_eval_memoizes_stages(self, monkeypatch):
        engine = fixture_engine()
        reads = {"n": 0}
        orig = FixtureDataSource.read_eval

        def counting_read_eval(self, ctx):
            reads["n"] += 1
            return orig(self, ctx)

        monkeypatch.setattr(FixtureDataSource, "read_eval", counting_read_eval)
        candidates = [self._params(m) for m in (1, 2, 3)]  # same DS params
        MetricEvaluator(NegAbsErr()).evaluate(CTX, engine, candidates)
        assert reads["n"] == 1  # DataSource ran once for the whole sweep

        reads["n"] = 0
        MetricEvaluator(NegAbsErr()).evaluate(
            CTX, engine, candidates, fast_eval=False
        )
        assert reads["n"] == 3  # no memoization

    def test_generator_requires_nonempty(self):
        with pytest.raises(ValueError):
            EngineParamsGenerator([])
