"""Ops plane (ISSUE 2): structured logs + trace correlation, health
probes, SLO burn-rate engine, and the pool supervisor's health-driven
respawn logic — the unit tier (server-route coverage lives in
test_servers.py, real-process pool coverage in test_worker_pool.py)."""

import json
import logging
import threading
import time

import pytest

from pio_tpu.obs import slog
from pio_tpu.obs.health import Heartbeat, HealthMonitor, thread_alive
from pio_tpu.obs.metrics import MetricsRegistry, REGISTRY
from pio_tpu.obs.slo import (
    DEFAULT_BURN_WINDOWS,
    SLOEngine,
    SLObjective,
    engine_for_specs,
    parse_duration_s,
    parse_slo,
)
from pio_tpu.obs.tracing import Tracer


@pytest.fixture(autouse=True)
def fresh_slog():
    slog._reset_for_tests()
    yield
    slog._reset_for_tests()


# ---------------------------------------------------------------- slog
class TestJsonLogHandler:
    def test_one_line_json_with_fields(self):
        h = slog.JsonLogHandler(worker=3)
        rec = logging.LogRecord(
            "pio_tpu.test", logging.WARNING, __file__, 1,
            "boom %d", (7,), None,
        )
        line = h.format_line(rec)
        assert "\n" not in line
        entry = json.loads(line)
        assert entry["level"] == "WARNING"
        assert entry["logger"] == "pio_tpu.test"
        assert entry["msg"] == "boom 7"
        assert entry["worker"] == 3
        assert entry["trace_id"] is None and entry["span"] is None
        assert entry["ts"].endswith("+00:00")  # UTC ISO-8601
        assert "levelno" not in entry  # internal field stays internal

    def test_exception_text_attached(self):
        h = slog.JsonLogHandler()
        try:
            raise ValueError("bad")
        except ValueError:
            import sys

            rec = logging.LogRecord(
                "pio_tpu.test", logging.ERROR, __file__, 1,
                "failed", (), sys.exc_info(),
            )
        entry = json.loads(h.format_line(rec))
        assert "ValueError: bad" in entry["exc"]

    def test_bad_format_does_not_raise(self):
        h = slog.JsonLogHandler()
        rec = logging.LogRecord(
            "pio_tpu.test", logging.INFO, __file__, 1,
            "%d", ("not-an-int",), None,
        )
        assert json.loads(h.format_line(rec))["msg"] == "%d"

    def test_emit_feeds_ring_and_counter(self):
        h = slog.JsonLogHandler()
        before = REGISTRY.counter(
            "pio_tpu_log_messages_total", "", ("level", "logger")
        ).value("INFO", "pio_tpu.feedtest")
        logger = logging.getLogger("pio_tpu.feedtest")
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
        try:
            logger.info("hello ring")
        finally:
            logger.removeHandler(h)
        assert h.ring.tail()[-1]["msg"] == "hello ring"
        after = REGISTRY.counter(
            "pio_tpu_log_messages_total", "", ("level", "logger")
        ).value("INFO", "pio_tpu.feedtest")
        assert after == before + 1


class TestLogRing:
    def _fill(self, ring, n, **kw):
        for i in range(n):
            ring.append({"msg": f"m{i}", "levelno": logging.INFO,
                         "level": "INFO", **kw})

    def test_bounded_with_dropped_count(self):
        ring = slog.LogRing(cap=4)
        self._fill(ring, 10)
        got = ring.snapshot()
        assert [e["msg"] for e in got] == ["m6", "m7", "m8", "m9"]
        assert ring.dropped == 6

    def test_tail_newest_n_chronological(self):
        ring = slog.LogRing(cap=8)
        self._fill(ring, 5)
        assert [e["msg"] for e in ring.tail(n=2)] == ["m3", "m4"]

    def test_level_is_minimum_severity(self):
        ring = slog.LogRing(cap=8)
        ring.append({"msg": "d", "levelno": logging.DEBUG})
        ring.append({"msg": "w", "levelno": logging.WARNING})
        ring.append({"msg": "e", "levelno": logging.ERROR})
        assert [e["msg"] for e in ring.tail(level="warning")] == ["w", "e"]
        with pytest.raises(ValueError, match="unknown level"):
            ring.tail(level="loud")

    def test_trace_and_logger_filters(self):
        ring = slog.LogRing(cap=8)
        ring.append({"msg": "a", "levelno": 20, "trace_id": "query-1",
                     "logger": "pio_tpu.queryserver"})
        ring.append({"msg": "b", "levelno": 20, "trace_id": "query-2",
                     "logger": "pio_tpu.storage"})
        assert [e["msg"] for e in ring.tail(trace_id="query-2")] == ["b"]
        assert [e["msg"] for e in ring.tail(logger="pio_tpu.query")] == ["a"]

    def test_install_idempotent_upgrades_in_place(self):
        h1 = slog.install()
        h2 = slog.install(worker=5)
        assert h1 is h2 and h1.worker == 5
        pio = logging.getLogger("pio_tpu")
        assert sum(1 for x in pio.handlers
                   if isinstance(x, slog.JsonLogHandler)) == 1


class TestTraceCorrelation:
    def test_logs_inside_span_carry_trace_id(self):
        slog.install()
        tracer = Tracer("corr")
        log = logging.getLogger("pio_tpu.corrtest")
        with tracer.trace("corr") as tr:
            log.info("at trace top")
            with tr.span("work"):
                log.info("inside span")
            trace_id = tr._trace.trace_id
        log.info("after trace")
        entries = slog.ring().tail(trace_id=trace_id)
        assert [e["msg"] for e in entries] == [
            "at trace top", "inside span",
        ]
        assert entries[0]["span"] is None
        assert entries[1]["span"] == "work"
        # context restored on exit
        assert slog.current_trace_id() is None
        # and the post-trace record has no trace id
        assert slog.ring().tail()[-1]["trace_id"] is None

    def test_contextvar_restored_on_error(self):
        slog.install()
        tracer = Tracer("corr2")
        with pytest.raises(RuntimeError):
            with tracer.trace("corr2"):
                raise RuntimeError("x")
        assert slog.current_trace_id() is None


# -------------------------------------------------------------- health
class TestHealth:
    def test_heartbeat_ages_out(self):
        hb = Heartbeat(max_age_s=0.05)
        ok, _ = hb.check()
        assert ok
        time.sleep(0.08)
        ok, detail = hb.check()
        assert not ok and "last beat" in detail
        hb.beat()
        assert hb.check()[0]

    def test_thread_alive_check(self):
        evt = threading.Event()
        t = threading.Thread(target=evt.wait, daemon=True)
        t.start()
        check = thread_alive(lambda: t)
        assert check()[0]
        evt.set()
        t.join()
        ok, detail = check()
        assert not ok and "dead" in detail
        # None thread = feature disabled, not a failure
        assert thread_alive(lambda: None)()[0]

    def test_monitor_reports_and_normalizes(self):
        mon = HealthMonitor()
        mon.add_liveness("truthy", lambda: True)
        mon.add_liveness("tuple", lambda: (True, "fine"))
        mon.add_readiness("raises", lambda: 1 / 0)
        ok, report = mon.liveness()
        assert ok and report["status"] == "ok"
        assert report["checks"]["tuple"] == {"ok": True, "detail": "fine"}
        ok, report = mon.readiness()
        assert not ok and report["status"] == "not ready"
        assert "ZeroDivisionError" in report["checks"]["raises"]["detail"]

    def test_one_failure_flips_probe(self):
        mon = HealthMonitor()
        mon.add_liveness("good", lambda: True)
        mon.add_liveness("bad", lambda: (False, "wedged"))
        ok, report = mon.liveness()
        assert not ok
        assert report["checks"]["good"]["ok"]
        assert not report["checks"]["bad"]["ok"]


class TestGroupCommitProbe:
    """Group commit is leader/follower (no thread to watch): the event
    server's /healthz liveness instead probes that the commit lock is
    acquirable — a leader wedged inside a hung backend flush holds it."""

    def test_acquirable_lock_is_healthy(self):
        from pio_tpu.storage.groupcommit import GroupCommitter

        gc = GroupCommitter(lambda payloads: list(payloads), store="t")
        ok, detail = gc.probe(timeout=0.1)
        assert ok and "acquirable" in detail
        # probing must not LEAVE the lock held
        ok, _ = gc.probe(timeout=0.1)
        assert ok

    def test_wedged_flush_flips_probe(self):
        from pio_tpu.storage.groupcommit import GroupCommitter

        wedge = threading.Event()
        in_flush = threading.Event()

        def hung_flush(payloads):
            in_flush.set()
            wedge.wait(timeout=10)
            return list(payloads)

        gc = GroupCommitter(hung_flush, store="t")
        t = threading.Thread(target=gc.submit, args=("x",), daemon=True)
        t.start()
        assert in_flush.wait(timeout=5)
        ok, detail = gc.probe(timeout=0.2)
        assert not ok and "0.2" in detail
        wedge.set()
        t.join(timeout=5)
        assert gc.probe(timeout=0.5)[0]


# ----------------------------------------------------------------- slo
class TestSLOParsing:
    def test_latency_spec(self):
        slo = parse_slo("p99=50ms:99.9")
        assert slo.name == "latency_p99" and slo.kind == "latency"
        assert slo.objective == pytest.approx(0.999)
        assert slo.threshold_s == pytest.approx(0.05)
        assert slo.window_s == 3600.0

    def test_availability_spec_with_window(self):
        slo = parse_slo("availability=99.95/6h")
        assert slo.kind == "availability"
        assert slo.objective == pytest.approx(0.9995)
        assert slo.window_s == 6 * 3600.0

    @pytest.mark.parametrize("bad", [
        "p99=50ms", "p99:99.9", "availability=101", "availability=0",
        "nonsense", "p99=50parsecs:99.9", "p99=50ms:99.9/2fortnights",
    ])
    def test_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_slo(bad)

    def test_duration_units(self):
        assert parse_duration_s("250us") == pytest.approx(2.5e-4)
        assert parse_duration_s("50ms") == pytest.approx(0.05)
        assert parse_duration_s("2m") == 120.0
        assert parse_duration_s("1d") == 86400.0
        with pytest.raises(ValueError):
            parse_duration_s("fast")

    def test_objective_validates(self):
        with pytest.raises(ValueError):
            SLObjective("x", "availability", objective=1.5)
        with pytest.raises(ValueError):
            SLObjective("x", "latency", objective=0.99)  # no threshold


class TestCountLe:
    def test_threshold_snaps_down_to_bucket_edge(self):
        reg = MetricsRegistry()
        cell = reg.histogram(
            "t_req_seconds", "", (), buckets=(0.01, 0.05, 0.1)
        ).labels()
        for v in (0.005, 0.02, 0.06, 0.2):
            cell.observe(v)
        # 0.05 is an edge: counts the <=0.01 and <=0.05 buckets
        assert cell.count_le(0.05, pool=False) == (2, 4)
        # 0.07 is NOT an edge: snaps DOWN to 0.05 (conservative)
        assert cell.count_le(0.07, pool=False) == (2, 4)
        assert cell.count_le(0.005, pool=False) == (0, 4)
        # a threshold past the last edge can't see into +Inf
        assert cell.count_le(0.1, pool=False) == (3, 4)


class TestSLOEngine:
    def _engine_with_source(self, registry=None):
        eng = SLOEngine(registry=registry)
        state = {"good": 0.0, "total": 0.0}
        eng.add(
            SLObjective("availability", "availability", objective=0.999),
            lambda: (state["good"], state["total"]),
        )
        return eng, state

    def test_burn_rate_and_budget_from_windows(self):
        eng, state = self._engine_with_source()
        t = 1000.0
        eng.sample(now=t)
        # 1000 requests, 10 errors over the next hour → error rate 1%,
        # burn = 0.01 / 0.001 = 10 on every window that saw the delta
        state["good"], state["total"] = 990.0, 1000.0
        out = eng.evaluate(now=t + 3600.0)["slos"][0]
        assert out["total"] == 1000.0 and out["errors"] == 10.0
        assert out["burnRates"]["3600s"] == pytest.approx(10.0, abs=0.01)
        # budget for the hour: 0.001 * 1000 = 1 allowed error, 10 spent
        assert out["errorBudgetRemaining"] == pytest.approx(-9.0, abs=0.01)

    def test_alerts_need_both_windows(self):
        eng, state = self._engine_with_source()
        t = 1000.0
        eng.sample(now=t)
        # big burst INSIDE the fast window only: 5m sees it, the 1h
        # window also sees it (same delta) → page fires
        state["good"], state["total"] = 900.0, 1000.0
        out = eng.evaluate(now=t + 300.0)["slos"][0]
        page = [a for a in out["alerts"] if a["severity"] == "page"][0]
        assert page["firing"]
        # quiet hour afterwards: fast window decays to zero burn → the
        # SAME cumulative numbers no longer page
        eng.sample(now=t + 300.0)
        out = eng.evaluate(now=t + 300.0 + 3600.0)["slos"][0]
        page = [a for a in out["alerts"] if a["severity"] == "page"][0]
        assert not page["firing"]

    def test_no_traffic_is_healthy(self):
        eng, _ = self._engine_with_source()
        out = eng.evaluate(now=10.0)["slos"][0]
        assert out["errorBudgetRemaining"] == 1.0
        assert all(not a["firing"] for a in out["alerts"])

    def test_gauges_exported(self):
        reg = MetricsRegistry()
        eng, state = self._engine_with_source(registry=reg)
        state["good"], state["total"] = 990.0, 1000.0
        eng.sample(now=0.0)
        eng.evaluate(now=3600.0)
        text = "\n".join(reg.render())
        assert "pio_tpu_slo_error_budget_remaining{" in text
        assert 'pio_tpu_slo_burn_rate{slo="availability",window="300s"}' \
            in text

    def test_engine_for_specs_wires_latency_to_histogram(self):
        reg = MetricsRegistry()
        cell = reg.histogram(
            "t2_req_seconds", "", (), buckets=(0.01, 0.05, 0.1)
        ).labels()
        eng = engine_for_specs(
            ["p99=50ms:99.9", "availability=99.9"], reg,
            availability_source=lambda: (10.0, 10.0),
            latency_cell_getter=lambda: cell,
        )
        assert len(eng) == 2
        for v in (0.02, 0.02, 0.2):  # 2 fast, 1 slow
            cell.observe(v)
        eng.sample(now=0.0)
        by_name = {
            s["name"]: s for s in eng.evaluate(now=60.0)["slos"]
        }
        lat = by_name["latency_p99"]
        assert lat["total"] == 3.0 and lat["errors"] == 1.0
        assert lat["thresholdMs"] == 50.0
        assert by_name["availability"]["errors"] == 0.0

    def test_default_burn_windows_shape(self):
        # the documented fast/slow page+ticket pairs (SRE workbook)
        assert DEFAULT_BURN_WINDOWS[0] == (300.0, 3600.0, 14.4, "page")
        assert DEFAULT_BURN_WINDOWS[1] == (1800.0, 21600.0, 6.0, "ticket")


# -------------------------------------------- supervisor health logic
class _FakeProc:
    """Process stand-in for the supervisor sweep (no real spawn)."""

    def __init__(self):
        self.alive = True
        self.killed = 0

    def is_alive(self):
        return self.alive

    def kill(self):
        self.killed += 1
        self.alive = False

    def join(self, timeout=None):
        pass


class TestSupervisorHealthSweep:
    @pytest.fixture()
    def harness(self):
        """A ServingPool shell (no spawned workers) + one in-process HTTP
        server whose /healthz status the test flips at will."""
        from pio_tpu.server.http import JsonHTTPServer, Router
        from pio_tpu.server.worker_pool import ServingPool

        state = {"status": 503}
        r = Router()
        r.add("GET", "/healthz", lambda req: (state["status"], {}))
        server = JsonHTTPServer(r, "127.0.0.1", 0, name="fake-worker")
        server.start()

        pool = ServingPool.__new__(ServingPool)  # skip __init__: no spawn
        pool.n_workers = 1
        pool._procs = [_FakeProc()]
        pool._respawns = [{"crash": 0, "unhealthy": 0}]
        pool._health_ports = [server.port]
        pool._health_fails = [0]
        pool._kill_reason = [None]
        pool._health_gauge = REGISTRY.gauge(
            "pio_tpu_worker_health_state", "", ("worker",)
        )
        yield pool, state
        server.stop()

    def test_kill_after_k_consecutive_failures(self, harness):
        from pio_tpu.server.worker_pool import _HEALTH_FAILS_TO_KILL

        pool, state = harness
        proc = pool._procs[0]
        for i in range(_HEALTH_FAILS_TO_KILL - 1):
            pool._health_sweep()
            assert proc.killed == 0, f"killed after only {i + 1} failures"
        pool._health_sweep()
        assert proc.killed == 1
        pool._health_sweep()  # next sweep sees the corpse
        assert pool._health_gauge.value("0") == -1

    def test_success_resets_failure_streak(self, harness):
        pool, state = harness
        proc = pool._procs[0]
        pool._health_sweep()
        pool._health_sweep()  # two strikes
        state["status"] = 200
        pool._health_sweep()  # healthy → streak resets
        assert pool._health_fails[0] == 0
        assert pool._health_gauge.value("0") == 1
        state["status"] = 503
        pool._health_sweep()
        pool._health_sweep()
        assert proc.killed == 0  # needs a fresh full streak

    def test_unpublished_port_is_not_a_failure(self, harness):
        pool, _ = harness
        pool._health_ports = [0]  # sidecar not up yet
        pool._health_sweep()
        assert pool._health_fails[0] == 0
        assert pool._procs[0].killed == 0


# -------------------------------------------------- deprecation shim
class TestMetricsShim:
    def test_import_warns_once_and_reexports(self):
        import importlib
        import warnings

        import pio_tpu.server.metrics as shim

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            importlib.reload(shim)
        assert any(
            issubclass(x.category, DeprecationWarning) for x in w
        )
        from pio_tpu.server.http import METRICS_CONTENT_TYPE

        assert shim.CONTENT_TYPE == METRICS_CONTENT_TYPE
        assert shim.escape_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
        resp = shim.render(["# TYPE x counter", "x 1"])
        assert "x 1" in resp.body
        assert resp.content_type == METRICS_CONTENT_TYPE

    def test_no_remaining_in_tree_importers(self):
        """The shim exists for out-of-tree plugins only — nothing in
        pio_tpu/ may import it anymore (satellite: reroute callers)."""
        import pathlib
        import re

        root = pathlib.Path(__file__).resolve().parent.parent / "pio_tpu"
        offenders = []
        for py in root.rglob("*.py"):
            if py.name == "metrics.py" and py.parent.name == "server":
                continue
            text = py.read_text()
            if re.search(
                r"from pio_tpu\.server\.metrics import|"
                r"from pio_tpu\.server import metrics|"
                r"import pio_tpu\.server\.metrics", text,
            ):
                offenders.append(str(py))
        assert not offenders, offenders
