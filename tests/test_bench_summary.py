"""bench.py output-contract tests (round-5 VERDICT #1).

BENCH_r04's artifact of record was lost: bench.py printed the whole
result as ONE JSON line, the driver keeps only the LAST 2000 chars of
stdout, and the line's FRONT (the headline) was truncated away
(`parsed: null`). These tests pin the fixed contract: stdout's final
line is a compact summary that ALWAYS survives a 2000-char tail window
with the headline fields intact, and the full blob goes to
BENCH_FULL.json.
"""

import importlib.util
import json
import os

import pytest

_BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _full_result() -> dict:
    """A representative FULL result at round-4 size (the shape that
    overflowed the tail window), including the verbose members —
    roofline notes, probe dicts, rank sweep — that made it fat."""
    return {
        "metric": "ALS@MovieLens-25M examples/sec/chip",
        "value": 29_600_000.0,
        "value_best_of_5": 31_200_000.0,
        "link_mb_s": 17.4,
        "device_examples_per_sec": 50_400_000.0,
        "unit": "examples/sec/chip",
        "vs_baseline": 23.7,
        "p50_predict_ms": 1.612,
        "p50_inproc_ms": 0.485,
        "phases": {
            "pack_s": 1.82, "h2d_s": 3.51, "device_s": 4.96,
            "wire_bytes": 61_000_000, "wire_mb_per_s": 17.4,
            "encoding": "u4+delta12", "n_stream": 4,
            "overlapped_total_s": 8.45,
            "device_examples_per_sec": 50_400_000.0,
            "achieved_gflops": 1371.0,
        },
        "serving": {
            "p50_ms": 1.612,
            "concurrent": {"qps": 1431.0, "p50_ms": 10.5, "p95_ms": 22.8},
            "concurrent_microbatch": {
                "qps": 1380.0, "p50_ms": 10.7, "p95_ms": 22.8,
                "mode": "off",
                "mode_by_bucket": {
                    "1": {"mode": "on", "p50Ms": 0.31, "samples": 64},
                    "2": {"mode": "on", "p50Ms": 0.52, "samples": 64},
                    "8": {"mode": "off", "p50Ms": 10.7, "samples": 41},
                },
                "probe": {"batchedP50Ms": 10.665, "perQueryP50Ms": 0.396},
                "avg_batch": 7.21, "max_batch": 8,
            },
            "pool": {"qps": 1306.2, "p50_ms": 10.3, "p95_ms": 23.4,
                     "workers": 2, "host_cores": 1,
                     "laned_qps": 1188.4, "laned_p50_ms": 11.2,
                     "laned_p95_ms": 24.8,
                     "routed_qps": 1240.7, "routed_p50_ms": 11.1,
                     "routed_p95_ms": 24.2, "router_overhead_ms": 0.8},
            "resident": {
                "queries": 200,
                "int8": {"wire": "int8", "h2d_bytes_per_request": 3.0,
                         "donation_hit_rate": 0.985, "retraces": 0,
                         "param_bytes": 160},
                "float32": {"wire": "float32",
                            "h2d_bytes_per_request": 12.0,
                            "donation_hit_rate": 0.985, "retraces": 0,
                            "param_bytes": 160},
                "h2d_ratio_f32_over_i8": 4.0,
                "donation_hit_rate": 0.985,
                "parity_delta": 0.0,
            },
        },
        "secondary": {
            "classification_examples_per_sec": {
                "value": 4_300_000.0, "cpu_anchor": 1_070_000.0,
                "vs_baseline": 4.02,
                "anchor_note": "median-of-5 cpu anchor",
            },
            "similarproduct_examples_per_sec": {
                "value": 23_100_000.0, "cpu_anchor": 4_370_000.0,
                "vs_baseline": 5.28,
            },
            "twotower_examples_per_sec": {
                "value": 478_000.0, "cpu_anchor": 12_300.0,
                "vs_baseline": 38.8, "achieved_gflops": 847.6,
                "roofline_note": "0.43% of v5e bf16 peak — e2e wall-clock"
                                 " incl. per-step host batch feed",
            },
            "seqrec": {
                "tokens_per_sec": 1_967_000.0, "achieved_gflops": 3980.0,
                "roofline_note": "2.02% of v5e bf16 peak — e2e wall-clock"
                                 " incl. host batch staging; f32 params",
            },
            "textclassification": {
                "pallas_tokens_per_sec": 9_100_000.0,
                "xla_tokens_per_sec": 10_400_000.0,
                "cpu_anchor": 2_600_000.0, "vs_baseline": 4.0,
            },
            "als_rank_sweep": {
                str(k): {"examples_per_sec": v,
                         "device_examples_per_sec": v * 1.7,
                         "achieved_gflops": g}
                for k, v, g in ((16, 2.9e7, 1371.0), (64, 1.1e7, 9104.0),
                                (128, 4.4e6, 14120.0))
            },
            "eventserver_events_per_sec": {
                "sqlite": {"single_events_per_sec": 3844.0,
                           "single_trials": [3758.9, 3844.8, 4877.2],
                           "single_p50_us": 146.9,
                           "single_p50_events_per_sec": 6806.9,
                           "inproc_events_per_sec": 16_311.0,
                           "concurrent_single_events_per_sec": 3900.0,
                           "batch_events_per_sec": 24_900.0,
                           "client": "raw-keepalive"},
                "eventlog": {"single_events_per_sec": 7555.0,
                             "single_trials": [5881.6, 7555.0, 7571.6],
                             "single_p50_us": 126.8,
                             "single_p50_events_per_sec": 7888.2,
                             "inproc_events_per_sec": 13_397.9,
                             "concurrent_single_events_per_sec": 5877.0,
                             "batch_events_per_sec": 37_697.0,
                             "client": "raw-keepalive"},
            },
        },
    }


def test_full_result_would_overflow_tail_window(bench):
    # regression premise: the FULL blob genuinely exceeds the window
    # (if it didn't, the summary layer would be untestable dead weight)
    assert len(json.dumps(_full_result())) > 2000


def test_summary_fits_budget_with_margin(bench):
    line = json.dumps(bench.build_summary(_full_result()))
    assert len(line) <= 1500, len(line)


def test_summary_survives_tail_truncation(bench):
    """The driver-shaped check: junk before the final line, keep only
    the LAST 2000 chars, and the headline must still json-parse."""
    line = json.dumps(bench.build_summary(_full_result()))
    stdout = "x" * 10_000 + "\n" + line + "\n"
    tail = stdout[-2000:]
    parsed = json.loads(tail.strip().splitlines()[-1])
    assert parsed["metric"].startswith("ALS@MovieLens-25M")
    assert parsed["value"] == 29_600_000.0
    assert parsed["vs_baseline"] == 23.7
    assert parsed["link_mb_s"] == 17.4
    assert parsed["device_examples_per_sec"] == 50_400_000.0
    assert parsed["pack_s"] == 1.82
    assert parsed["p50_predict_ms"] == 1.612
    assert parsed["serving_qps"] == 1431.0
    assert parsed["pool_qps"] == 1306.2
    assert parsed["pool_laned_qps"] == 1188.4
    assert parsed["routed_qps"] == 1240.7
    assert parsed["router_overhead_ms"] == 0.8
    # per-bucket mode map compacts to {bucket: mode} in the summary
    assert parsed["serving_mb_mode"] == {"1": "on", "2": "on", "8": "off"}
    assert parsed["serving_h2d_x"] == 4.0
    assert parsed["serving_donation_hit"] == 0.985
    assert parsed["serving_wire_parity_delta"] == 0.0
    cfg = parsed["configs"]
    assert cfg["classification"]["x"] == 4.02
    assert cfg["similarproduct"]["x"] == 5.28
    assert cfg["twotower"]["gflops"] == 847.6
    assert cfg["seqrec"]["gflops"] == 3980.0
    assert cfg["ingest"]["sqlite_single"] == 3844.0
    assert cfg["ingest"]["sqlite_p50"] == 6806.9
    assert cfg["ingest"]["eventlog_batch"] == 37_697.0
    assert parsed["full"] == "BENCH_FULL.json"


def test_emit_writes_full_blob_and_returns_summary(bench, tmp_path):
    full = _full_result()
    path = str(tmp_path / "BENCH_r05_full.json")
    line = bench.emit(full, path=path)
    parsed = json.loads(line)
    # the summary pointer must follow the ACTUAL path, not a literal
    assert parsed["full"] == "BENCH_r05_full.json"
    assert parsed == bench.build_summary(full, full_path=path)
    with open(path) as f:
        assert json.load(f) == full


def test_emit_smoke_run_does_not_clobber_record(bench, tmp_path,
                                                monkeypatch):
    """A workload-shrinking knob marks a smoke run: its artifact goes
    to the gitignored bench_full_smoke.json, never BENCH_FULL.json."""
    for k in bench._FULL_SCALE_DEFAULTS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("PIO_TPU_BENCH_EDGES", "200000")
    line = bench.emit(_full_result(), base_dir=str(tmp_path))
    assert json.loads(line)["full"] == "bench_full_smoke.json"
    assert (tmp_path / "bench_full_smoke.json").exists()
    assert not (tmp_path / "BENCH_FULL.json").exists()
    # a deadline-limited (partial) run is a smoke run too
    monkeypatch.delenv("PIO_TPU_BENCH_EDGES")
    monkeypatch.setenv("PIO_TPU_BENCH_DEADLINE_S", "60")
    line = bench.emit(_full_result(), base_dir=str(tmp_path))
    assert json.loads(line)["full"] == "bench_full_smoke.json"
    assert not (tmp_path / "BENCH_FULL.json").exists()
    # with no knobs set, the artifact of record is chosen
    monkeypatch.delenv("PIO_TPU_BENCH_DEADLINE_S")
    line = bench.emit(_full_result(), base_dir=str(tmp_path))
    assert json.loads(line)["full"] == "BENCH_FULL.json"
    assert (tmp_path / "BENCH_FULL.json").exists()
    # explicitly exporting the documented DEFAULTS is still a full run
    monkeypatch.setenv("PIO_TPU_BENCH_ITERS", "10")
    monkeypatch.setenv("PIO_TPU_BENCH_SECONDARY", "1")
    monkeypatch.setenv("PIO_TPU_BENCH_SCALE", "1.0")
    assert not bench._is_smoke_run()


def test_emit_failure_preserves_previous_artifact(bench, tmp_path):
    """Atomic replace: a non-serializable stage value must not destroy
    the prior artifact of record."""
    path = str(tmp_path / "BENCH_FULL.json")
    bench.emit(_full_result(), path=path)
    before = open(path).read()
    bad = _full_result()
    bad["phases"]["oops"] = object()  # json.dump raises mid-write
    with pytest.raises(TypeError):
        bench.emit(bad, path=path)
    assert open(path).read() == before
    assert not (tmp_path / "BENCH_FULL.json.tmp").exists()  # no litter


def test_summary_sheds_to_core_when_over_budget(bench):
    full = _full_result()
    # pathological: a stage sneaks a huge string into a summarized field
    full["secondary"]["classification_examples_per_sec"]["anchor_note"] = (
        "y" * 4000
    )
    s = bench.build_summary(full)
    line = json.dumps(s)
    assert len(line) <= bench.SUMMARY_CHAR_BUDGET
    # the shed form still carries the driver-required core
    assert s["metric"] and s["value"] and s["vs_baseline"]
    assert s["full"] == "BENCH_FULL.json"


def test_summary_tolerates_missing_stages(bench):
    s = bench.build_summary({"metric": "m", "value": 1.0, "unit": "u",
                             "vs_baseline": 1.0})
    json.dumps(s)  # parseable
    assert s["value"] == 1.0
    assert s["serving_qps"] is None
    assert "configs" not in s


# --------------------------------------------------------------------------
# bench --history ledger (ISSUE 11 satellite)
# --------------------------------------------------------------------------

def _row(**over):
    base = {
        "timestamp": "2026-08-01T00:00:00+00:00", "git_sha": "abc1234",
        "smoke": False, "value": 100.0, "serving_qps": 1000.0,
        "pool_qps": 2000.0, "p50_predict_ms": 10.0, "p95_predict_ms": 20.0,
        "serving_attributed": 0.9, "serving_h2d_x": 3.0, "shed_rate": 0.01,
    }
    base.update(over)
    return base


def test_history_record_pulls_trajectory_fields(bench):
    full = _full_result()
    summary = bench.build_summary(full)
    rec = bench.history_record(full, summary, git_sha="deadbee",
                               timestamp="2026-08-05T00:00:00+00:00")
    assert rec["git_sha"] == "deadbee"
    assert rec["value"] == summary["value"]
    assert rec["p95_predict_ms"] == full["serving"]["concurrent"]["p95_ms"]
    assert rec["routed_qps"] == 1240.7
    assert rec["router_overhead_ms"] == 0.8
    ov = full["serving"].get("overload") or {}
    assert rec["shed_rate"] == ov.get("shed_rate")
    assert rec["smoke"] in (True, False)
    json.dumps(rec)  # one jsonl row


def test_history_delta_flags_regressions_by_direction(bench):
    prev = _row()
    cur = _row(value=80.0,            # down 20% on an up-is-good -> bad
               p95_predict_ms=15.0,   # down on a down-is-good -> improved
               serving_qps=1001.0)    # within threshold -> neither
    lines, regressed = bench.history_delta_table(prev, cur, 0.05)
    assert regressed == ["value"]
    text = "\n".join(lines)
    assert "REGRESSION" in text and "improved" in text
    assert "-20.0%" in text


def test_history_append_read_round_trip_skips_garbage(bench, tmp_path,
                                                      capsys):
    path = str(tmp_path / "H.jsonl")
    bench.append_history(_row(), path)
    with open(path, "a") as f:
        f.write("{not json\n")
    bench.append_history(_row(git_sha="def5678"), path)
    rows = bench.read_history(path)
    assert [r["git_sha"] for r in rows] == ["abc1234", "def5678"]
    assert "malformed history line" in capsys.readouterr().err


def test_history_argv_and_env_parsing(bench, monkeypatch):
    monkeypatch.delenv("PIO_TPU_BENCH_HISTORY", raising=False)
    monkeypatch.delenv("PIO_TPU_BENCH_HISTORY_FILE", raising=False)
    opts = bench.parse_history_argv([])
    assert not opts["history"]
    opts = bench.parse_history_argv(
        ["--history", "--history-file=/x/H.jsonl",
         "--regression-threshold", "0.2"])
    assert opts["history"] and opts["history_file"] == "/x/H.jsonl"
    assert opts["threshold"] == 0.2
    monkeypatch.setenv("PIO_TPU_BENCH_HISTORY", "1")
    assert bench.parse_history_argv([])["history"]
    # bad threshold keeps the default, loudly but non-fatally
    opts = bench.parse_history_argv(["--regression-threshold=eleven"])
    assert opts["threshold"] == bench.DEFAULT_REGRESSION_THRESHOLD


def test_maybe_record_history_appends_and_prints_delta(bench, tmp_path,
                                                       capsys, monkeypatch):
    monkeypatch.delenv("PIO_TPU_BENCH_HISTORY", raising=False)
    path = str(tmp_path / "H.jsonl")
    full = _full_result()
    summary = bench.build_summary(full)
    argv = ["--history", f"--history-file={path}"]
    bench.maybe_record_history(full, summary, argv)
    assert "baseline row recorded" in capsys.readouterr().err
    # second run: delta table on stderr, two ledger rows, stdout untouched
    bench.maybe_record_history(full, summary, argv)
    out = capsys.readouterr()
    assert out.out == ""          # summary-line stdout contract intact
    assert "bench history delta" in out.err
    assert len(bench.read_history(path)) == 2


def test_maybe_record_history_never_raises(bench, tmp_path, capsys):
    full = _full_result()
    summary = bench.build_summary(full)
    bad = str(tmp_path)  # a directory: open(..., "a") raises
    bench.maybe_record_history(full, summary,
                               ["--history", f"--history-file={bad}"])
    assert "bench history failed" in capsys.readouterr().err
