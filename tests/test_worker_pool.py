"""SO_REUSEPORT serving pool (pio_tpu/server/worker_pool.py).

Correctness tier for the multi-process query-serving mode: connections
balance across workers, answers match the single-process server, /reload
rolls every worker via the shared generation counter, and /undeploy
brings the whole pool down. Perf (the pool's reason to exist) needs a
multi-core host — this environment pins to ONE core, so QPS claims live
in bench.py/BASELINE.md, not here.
"""

import datetime as dt
import http.client
import json
import time

import pytest

import pio_tpu.templates  # noqa: F401  (registers the engine factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.obs import monotonic_s
from pio_tpu.data import Event
from pio_tpu.storage import App, Storage
from pio_tpu.workflow import build_engine, run_train, variant_from_dict

pytestmark = pytest.mark.slow  # spawns real worker processes

VARIANT = {
    "id": "pool-e2e",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "pool-test"}},
    "algorithms": [
        {
            "name": "als",
            "params": {
                "rank": 4, "num_iterations": 5, "lambda_": 0.05, "seed": 1,
            },
        }
    ],
}


def _seed_and_train(n_users=10, n_items=6):
    app_id = Storage.get_meta_data_apps().insert(App(0, "pool-test"))
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    for u in range(n_users):
        for i in range(n_items):
            in_block = (u < 5) == (i < 3)
            le.insert(
                Event(
                    "rate", "user", f"u{u}", "item", f"i{i}",
                    properties={"rating": 5.0 if in_block else 1.0},
                    event_time=t0 + dt.timedelta(minutes=u * 60 + i),
                ),
                app_id,
            )
    variant = variant_from_dict(VARIANT)
    engine, ep = build_engine(variant)
    # local (single-device) training: this suite exercises pool SERVING;
    # the mesh training path has its own coverage in test_als.py
    run_train(engine, ep, variant, ctx=ComputeContext.local())
    return variant


def _post(port, path, body, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            "POST", path, body=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def _get(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def _post_h(port, path, body, timeout=30, headers=None):
    """Like _post but also returns the response headers (lowercased)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            "POST", path, body=json.dumps(body).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        r = conn.getresponse()
        return (r.status, json.loads(r.read()),
                {k.lower(): v for k, v in r.getheaders()})
    finally:
        conn.close()


@pytest.fixture()
def pool(tmp_home):
    from pio_tpu.server.worker_pool import ServingPool

    Storage.reset()
    variant = _seed_and_train()
    pool = ServingPool(variant, host="127.0.0.1", port=0, n_workers=2)
    pool.start()
    pool.wait_ready(timeout=120)
    yield pool
    pool.stop()
    Storage.reset()


class TestServingPool:
    def test_concurrent_correctness_and_balancing(self, pool):
        # single-process reference answer (same storage, same instance)
        status, ref = _post(pool.port, "/queries.json",
                            {"user": "u1", "num": 3})
        assert status == 200 and len(ref["itemScores"]) == 3
        # u1 is in the first block → top items must come from i0..i2
        top_ref = {s["item"] for s in ref["itemScores"]}
        assert top_ref <= {"i0", "i1", "i2"}

        # every worker (fresh connections rotate across listeners) must
        # return the identical ranking — they loaded the same instance
        workers_seen = set()
        for _ in range(30):
            status, got = _post(pool.port, "/queries.json",
                                {"user": "u1", "num": 3})
            assert status == 200
            assert [s["item"] for s in got["itemScores"]] == \
                [s["item"] for s in ref["itemScores"]]
            _, stats = _get(pool.port, "/stats.json")
            assert stats["poolSize"] == 2
            workers_seen.add(stats["worker"])
        # kernel balancing is stochastic but 60+ fresh connections
        # virtually never all land on one listener
        assert len(workers_seen) == 2, workers_seen

    def test_pool_wide_metrics_on_any_worker(self, pool):
        """Acceptance criterion: with the shared-memory segment bound,
        GET /metrics on whichever worker answers reports POOL-WIDE
        totals — N requests in, a scraped counter of exactly N out,
        regardless of how the kernel split the connections."""
        from pio_tpu.obs.promparse import parse_prometheus_text

        def scrape():
            conn = http.client.HTTPConnection("127.0.0.1", pool.port,
                                              timeout=30)
            try:
                conn.request("GET", "/metrics")
                r = conn.getresponse()
                assert r.status == 200
                return parse_prometheus_text(r.read().decode())
            finally:
                conn.close()

        base = scrape().value("pio_tpu_queries_total", engine_id="pool-e2e")
        N = 20
        workers_seen = set()
        for _ in range(N):
            status, _ = _post(pool.port, "/queries.json",
                              {"user": "u1", "num": 2})
            assert status == 200
            _, stats = _get(pool.port, "/stats.json")
            workers_seen.add(stats["worker"])
        # several scrapes (fresh connections → possibly different
        # workers) must all agree on the pool-wide total
        for _ in range(6):
            pm = scrape()
            assert pm.value(
                "pio_tpu_queries_total", engine_id="pool-e2e"
            ) == base + N
        assert len(workers_seen) == 2, workers_seen
        # stage histograms aggregate the same way: every request passed
        # through execute exactly once, whichever worker served it
        assert pm.value(
            "pio_tpu_query_stage_seconds_count",
            engine_id="pool-e2e", stage="execute",
        ) >= base + N
        # /stats.json carries the pool block alongside per-worker stats
        _, stats = _get(pool.port, "/stats.json")
        assert stats["pool"]["requestCount"] >= base + N

    def test_reload_rolls_every_worker(self, pool):
        # retrain → new COMPLETED instance; one /reload must roll ALL
        # workers (generation counter), not just the one that got the POST
        variant = variant_from_dict(VARIANT)
        engine, ep = build_engine(variant)
        new_id = run_train(
            engine, ep, variant, ctx=ComputeContext.local()
        )
        status, out = _post(pool.port, "/reload", {})
        assert status == 200 and out["engineInstanceId"] == new_id
        # every worker must now serve the new instance (lazy reload on
        # next query) — hit both via fresh connections
        seen = set()
        for _ in range(30):
            status, got = _post(pool.port, "/queries.json",
                                {"user": "u2", "num": 2})
            assert status == 200
            _, st = _get(pool.port, "/")
            seen.add(st["engineInstanceId"])
        assert seen == {new_id}, seen

    def test_supervisor_respawns_crashed_worker(self, pool):
        """A worker killed out-of-band comes back under supervision and
        serves again; /undeploy then stops supervision and every worker."""
        import threading

        sup = threading.Thread(target=pool.wait, daemon=True)
        sup.start()
        victim = pool._procs[0]
        victim.terminate()
        victim.join(10)
        deadline = monotonic_s() + 30
        while monotonic_s() < deadline:
            if pool._procs[0] is not victim and pool._procs[0].is_alive():
                break
            time.sleep(0.2)
        assert pool._procs[0] is not victim, "worker never respawned"
        assert pool._respawns[0]["crash"] == 1
        # the pool still answers (either worker may take the connection)
        status, got = _post(pool.port, "/queries.json",
                            {"user": "u1", "num": 2})
        assert status == 200 and len(got["itemScores"]) == 2
        _post(pool.port, "/undeploy", {})
        sup.join(30)
        assert not sup.is_alive()
        assert all(not p.is_alive() for p in pool._procs)

    def test_supervisor_kills_wedged_worker_via_health_probe(self, pool):
        """ISSUE 2 acceptance: a worker that is alive-but-wedged (frozen
        with SIGSTOP — its process exists, its /healthz never answers)
        is killed after the consecutive-failure threshold and respawned
        by the ordinary crash path."""
        import os
        import signal
        import threading

        # every worker publishes its loopback health sidecar port
        deadline = monotonic_s() + 30
        while monotonic_s() < deadline:
            if all(p > 0 for p in pool._health_ports):
                break
            time.sleep(0.2)
        ports = list(pool._health_ports)
        assert all(p > 0 for p in ports), ports
        for p in ports:
            status, report = _get(p, "/healthz")
            assert status == 200 and report["status"] == "ok"

        sup = threading.Thread(
            target=pool.wait,
            kwargs={"poll_s": 0.2, "health_poll_s": 0.5},
            daemon=True,
        )
        sup.start()
        victim = pool._procs[1]
        os.kill(victim.pid, signal.SIGSTOP)  # wedged, not dead
        deadline = monotonic_s() + 60
        while monotonic_s() < deadline:
            if pool._procs[1] is not victim and pool._procs[1].is_alive():
                break
            time.sleep(0.2)
        assert pool._procs[1] is not victim, "wedged worker never replaced"
        assert pool._respawns[1]["unhealthy"] == 1
        # the health-sweep kill spent the unhealthy budget, not the
        # crash budget (the split is the point of the per-reason split)
        assert pool._respawns[1]["crash"] == 0
        # the replacement serves (either worker may take the connection)
        status, got = _post(pool.port, "/queries.json",
                            {"user": "u1", "num": 2})
        assert status == 200 and len(got["itemScores"]) == 2
        _post(pool.port, "/undeploy", {})
        sup.join(30)
        assert not sup.is_alive()

    def test_undeploy_stops_whole_pool(self, pool):
        status, out = _post(pool.port, "/undeploy", {})
        assert status == 200
        # the shared event reaches the supervisor and every worker
        deadline = monotonic_s() + 30
        while monotonic_s() < deadline:
            if all(not p.is_alive() for p in pool._procs):
                break
            time.sleep(0.2)
        assert all(not p.is_alive() for p in pool._procs)


@pytest.fixture()
def qos_pool(tmp_home):
    from pio_tpu.server.worker_pool import ServingPool

    Storage.reset()
    variant = _seed_and_train()
    # rps is tiny so refill during the burst stays under one token: the
    # observable budget is the burst, shared by BOTH workers
    pool = ServingPool(variant, host="127.0.0.1", port=0, n_workers=2,
                       qos="rps=0.2,burst=6")
    pool.start()
    pool.wait_ready(timeout=120)
    yield pool
    pool.stop()
    Storage.reset()


class TestPoolQoS:
    def test_rps_budget_enforced_pool_wide(self, qos_pool):
        """ISSUE 3 acceptance: with --workers 2, an rps= budget is
        enforced pool-wide, not per worker. 40 requests against a
        shared burst of 6 must admit ~6 TOTAL (each worker's token
        bucket observes the other's admissions through the shm segment)
        — per-worker budgets would admit ~12."""
        import concurrent.futures

        from pio_tpu.obs.promparse import parse_prometheus_text

        def one(t):
            # fresh connection per request → kernel spreads them over
            # both workers' SO_REUSEPORT listeners
            return _post_h(qos_pool.port, "/queries.json",
                           {"user": f"u{t % 10}", "num": 2})

        with concurrent.futures.ThreadPoolExecutor(8) as ex:
            results = list(ex.map(one, range(40)))
        admitted = [r for r in results if r[0] == 200]
        shed = [r for r in results if r[0] == 429]
        assert {r[0] for r in results} <= {200, 429}
        assert len(admitted) + len(shed) == 40
        # the SHARED budget: burst 6, plus at most a couple of tokens
        # from the cross-worker race window and trickle refill. Split
        # per-worker budgets would admit 12+.
        assert 6 <= len(admitted) <= 9, len(admitted)
        for _, body, headers in shed:
            assert int(headers["retry-after"]) >= 1
            assert "overloaded" in body["message"]
        # pool-wide accounting, scraped from whichever worker answers:
        # shed_total covers every 429, admitted the pool-wide 200s
        conn = http.client.HTTPConnection("127.0.0.1", qos_pool.port,
                                          timeout=30)
        try:
            conn.request("GET", "/metrics")
            pm = parse_prometheus_text(conn.getresponse().read().decode())
        finally:
            conn.close()
        assert pm.value(
            "pio_tpu_qos_shed_total",
            scope="queryserver", reason="rate_limit",
        ) == len(shed)
        status, snap = _get(qos_pool.port, "/qos.json")
        assert status == 200 and snap["enabled"] is True
        assert snap["admitted"] == len(admitted)
        assert snap["policy"]["rps"] == pytest.approx(0.2)
        # the pool survived the burst
        status, got = _get(qos_pool.port, "/healthz")
        assert status == 200


@pytest.fixture()
def traced_pool(tmp_home, monkeypatch):
    from pio_tpu.server.worker_pool import ServingPool

    # 100 ns slow threshold: every request breaches, so both workers'
    # slow rings fill deterministically (workers inherit the env)
    monkeypatch.setenv("PIO_TPU_SLOW_TRACE_MS", "0.0001")
    Storage.reset()
    variant = _seed_and_train()
    pool = ServingPool(variant, host="127.0.0.1", port=0, n_workers=2)
    pool.start()
    pool.wait_ready(timeout=120)
    yield pool
    pool.stop()
    Storage.reset()


class TestPoolTraceAttribution:
    def test_pool_unique_ids_merged_rings_and_slow_capture(self, traced_pool):
        """ISSUE 6 acceptance: in pool mode, minted trace ids are
        worker-namespaced (query-wN-...), /traces.json?id= resolves a
        trace whichever worker holds it (sidecar fan-out), and a slow
        request's waterfall is retrievable by id from ?slow=1 on ANY
        worker's merged view."""
        pool = traced_pool
        # sidecar ports must be published before fan-out can merge
        deadline = monotonic_s() + 30
        while monotonic_s() < deadline:
            if all(p > 0 for p in pool._health_ports):
                break
            time.sleep(0.2)
        assert all(p > 0 for p in pool._health_ports)

        ids = set()
        for i in range(12):
            status, body, headers = _post_h(
                pool.port, "/queries.json", {"user": f"u{i % 8}", "num": 2}
            )
            assert status == 200
            tid = headers.get("x-pio-trace")
            assert tid and tid.startswith("query-w"), tid
            ids.add(tid)
        assert len(ids) == 12  # pool-unique: no cross-worker collisions

        # by-id lookup crosses workers: whichever worker answers the GET
        # must resolve ids minted by EITHER worker
        for tid in sorted(ids)[:6]:
            status, got = _get(pool.port, f"/traces.json?id={tid}")
            assert status == 200, tid
            t = got["traces"][0]
            assert t["id"] == tid
            stages = {s["stage"] for s in t["spans"]}
            assert {"accept", "parse", "execute"} <= stages, stages

        # inbound header adoption still works under the pool
        status, body, headers = _post_h(
            pool.port, "/queries.json", {"user": "u1", "num": 2},
            headers={"X-Pio-Trace": "pool-client-1/dispatch"},
        )
        assert status == 200
        assert headers.get("x-pio-trace") == "pool-client-1"
        ids.add("pool-client-1")

        # every request breached the 100 ns threshold: the MERGED slow
        # view on any worker eventually covers ids from both workers
        deadline = monotonic_s() + 15
        seen = set()
        while monotonic_s() < deadline and not ids <= seen:
            status, got = _get(pool.port, "/traces.json?slow=1&n=128")
            assert status == 200
            seen = {t["id"] for t in got["traces"]}
            time.sleep(0.2)
        assert ids <= seen, ids - seen
        slow = {t["id"]: t for t in got["traces"]}
        assert all(slow[tid].get("slow") for tid in ids)
        # worker index rides the trace for the merged view
        assert all("worker" in slow[tid] for tid in ids)
