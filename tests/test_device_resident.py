"""Device-resident serving (ISSUE 8): donated buffers, int8 wire,
hot-swap retirement, and the packed lane frame.

Covers the donation reuse guard (a donated buffer re-read must raise,
never return garbage), wire parity (int8-quantized dispatches agree
with the host float path), h2d byte accounting (int8 pays exactly one
byte per feature), hot-swap retirement (a /reload retires the old
generation's resident params so stale weights can never serve), and
the lane's packed int8 payload round-tripping exactly against the JSON
path. Everything runs with ``PIO_TPU_DEVICE_RESIDENT=1`` — the auto
default keeps residency off on CPU, which is also asserted.
"""

import datetime as dt

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers engine factories)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.server.batchlane import (
    PACKED_MAGIC,
    PackedQuery,
    pack_query_i8,
    unpack_query_i8,
)
from pio_tpu.server.query_server import QueryServerService
from pio_tpu.server.residency import (
    DonatedBuffer,
    ResidentLinearScorer,
    enabled,
    wire_mode,
)
from pio_tpu.storage import App, Storage
from pio_tpu.templates.classification import Query
from pio_tpu.workflow import build_engine, run_train, variant_from_dict


# ------------------------------------------------------------- env gating
class TestGating:
    def test_auto_is_off_on_cpu(self, monkeypatch):
        monkeypatch.delenv("PIO_TPU_DEVICE_RESIDENT", raising=False)
        assert enabled() is False  # suite runs under JAX_PLATFORMS=cpu

    def test_force_on_off(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_DEVICE_RESIDENT", "1")
        assert enabled() is True
        monkeypatch.setenv("PIO_TPU_DEVICE_RESIDENT", "0")
        assert enabled() is False

    def test_wire_mode_resolution(self, monkeypatch):
        monkeypatch.delenv("PIO_TPU_SERVE_WIRE", raising=False)
        assert wire_mode(True) == "int8"  # auto: int8 when scales exist
        assert wire_mode(False) == "float32"
        monkeypatch.setenv("PIO_TPU_SERVE_WIRE", "float32")
        assert wire_mode(True) == "float32"
        monkeypatch.setenv("PIO_TPU_SERVE_WIRE", "int8")
        assert wire_mode(True) == "int8"
        # int8 without scales cannot quantize — falls back, not crashes
        assert wire_mode(False) == "float32"


# --------------------------------------------------------- donation guard
class TestDonatedBuffer:
    def test_take_is_one_shot(self):
        import jax.numpy as jnp

        g = DonatedBuffer(jnp.zeros((2, 3)))
        g.take()
        with pytest.raises(RuntimeError, match="re-used"):
            g.take()

    def test_read_after_donation_raises(self):
        import jax.numpy as jnp

        g = DonatedBuffer(jnp.zeros((2, 3)))
        assert g.array().shape == (2, 3)  # readable before donation
        g.take()
        with pytest.raises(RuntimeError, match="re-read"):
            g.array()


# ----------------------------------------------------------- scorer level
def _scorer(monkeypatch, d=4, c=3, scales=True, seed=0, **kw):
    monkeypatch.setenv("PIO_TPU_DEVICE_RESIDENT", "1")
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(d, c)).astype(np.float32)
    b = rng.normal(size=c).astype(np.float32)
    s = (np.abs(rng.normal(size=d)) / 127.0 + 1e-3).astype(np.float32)
    return ResidentLinearScorer(
        W, b, scales=s if scales else None, name="t", **kw
    ), W, b


class TestResidentScorer:
    def test_int8_wire_parity_with_host_path(self, monkeypatch):
        monkeypatch.delenv("PIO_TPU_SERVE_WIRE", raising=False)
        sc, W, b = _scorer(monkeypatch, d=8, c=4, seed=3)
        assert sc.wire == "int8"
        rng = np.random.default_rng(9)
        # features on the scales' dynamic range (|x| <= 127*s)
        X = (rng.normal(size=(64, 8)) * sc.scales * 40).astype(np.float32)
        host = np.argmax(X @ W + b, axis=1)
        got = sc.score_codes(X)
        assert (got == host).mean() >= 0.999  # training-wire tolerance

    def test_h2d_bytes_exact_per_wire(self, monkeypatch):
        X = np.ones((4, 6), np.float32)
        sc, _, _ = _scorer(monkeypatch, d=6, scales=True)
        sc.score_codes(X)
        assert sc.h2d_bytes == 4 * 6  # one byte per int8 feature
        monkeypatch.setenv("PIO_TPU_SERVE_WIRE", "float32")
        sc32, _, _ = _scorer(monkeypatch, d=6, scales=True)
        sc32.score_codes(X)
        assert sc32.h2d_bytes == 4 * 6 * 4  # 4x the int8 wire

    def test_donation_hit_miss_accounting(self, monkeypatch):
        sc, _, _ = _scorer(monkeypatch)
        sc.prealloc([1, 2])
        X = np.ones((2, 4), np.float32)
        for _ in range(5):
            sc.score_codes(X)
        assert sc.donation_hits == 5 and sc.donation_misses == 0
        sc.score_codes(np.ones((3, 4), np.float32))  # cold shape
        assert sc.donation_misses == 1
        sc.score_codes(np.ones((3, 4), np.float32))  # now standing
        assert sc.donation_hits == 6
        d = sc.to_dict()
        assert d["donation"]["hitRate"] == pytest.approx(6 / 7, abs=1e-4)

    def test_retired_scorer_refuses(self, monkeypatch):
        sc, _, _ = _scorer(monkeypatch)
        sc.retire()
        with pytest.raises(RuntimeError, match="retired"):
            sc.score_codes(np.ones((1, 4), np.float32))

    def test_quantize_dequantize_round_trip_exact(self, monkeypatch):
        sc, _, _ = _scorer(monkeypatch, d=16, seed=7)
        rng = np.random.default_rng(11)
        X = (rng.normal(size=(32, 16)) * sc.scales * 50).astype(np.float32)
        codes = sc.quantize(X)
        assert np.array_equal(sc.quantize(sc.dequantize(codes)), codes)

    def test_wire_shape_mismatch_raises(self, monkeypatch):
        sc, _, _ = _scorer(monkeypatch, d=4)
        with pytest.raises(ValueError, match="wire batch"):
            sc.score_wire(np.zeros((2, 5), np.int8))


# ------------------------------------------------------------ packed lane
class TestPackedFrame:
    def test_round_trip_exact(self):
        codes = np.array([-127, -1, 0, 1, 127, 42], np.int8)
        frame = pack_query_i8(codes)
        assert frame[:4] == PACKED_MAGIC
        got = unpack_query_i8(frame)
        assert isinstance(got, PackedQuery)
        assert np.array_equal(got.codes, codes)

    def test_magic_disambiguates_from_json(self):
        # a JSON body can never start with the NUL-led magic
        assert not b'{"attrs": [1.0]}'.startswith(PACKED_MAGIC[:1])

    def test_malformed_frame_raises(self):
        frame = pack_query_i8(np.zeros(4, np.int8))
        with pytest.raises(ValueError):
            unpack_query_i8(frame[:-1])  # truncated


# ------------------------------------------------------- service lifecycle
@pytest.fixture(autouse=True)
def isolated_storage(tmp_home):
    Storage.reset()
    yield
    Storage.reset()


def _seed_users(app_id: int):
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    rng = np.random.default_rng(7)
    n = 0
    for plan, hot in (("basic", 0), ("premium", 1), ("pro", 2)):
        for k in range(8):
            attrs = rng.integers(0, 3, size=3)
            attrs[hot] += 6
            props = {f"attr{j}": int(attrs[j]) for j in range(3)}
            props["plan"] = plan
            le.insert(
                Event("$set", "user", f"u{n}", properties=props,
                      event_time=t0 + dt.timedelta(minutes=n)),
                app_id,
            )
            n += 1


def _service(monkeypatch, algo="logreg", resident="1"):
    monkeypatch.setenv("PIO_TPU_DEVICE_RESIDENT", resident)
    monkeypatch.setenv("PIO_TPU_BATCH_BUCKETS", "1,2,4")
    monkeypatch.setenv("PIO_TPU_BUCKET_WARMUP", "1")
    app_id = Storage.get_meta_data_apps().insert(App(0, "res-test"))
    _seed_users(app_id)
    variant = variant_from_dict({
        "id": "res-e2e",
        "engineFactory": "templates.classification",
        "datasource": {"params": {"app_name": "res-test"}},
        "algorithms": [{"name": algo, "params": {}}],
    })
    engine, ep = build_engine(variant)
    ctx = ComputeContext.create(seed=0)
    run_train(engine, ep, variant, ctx=ctx)
    return QueryServerService(variant, ctx=ctx)


CASES = [
    (Query(attrs=(9.0, 1.0, 1.0)), "basic"),
    (Query(attrs=(1.0, 9.0, 1.0)), "premium"),
    (Query(attrs=(1.0, 1.0, 9.0)), "pro"),
]


class TestServiceResidency:
    @pytest.mark.parametrize("algo", ["naivebayes", "logreg"])
    def test_resident_scorer_placed_and_serves(self, monkeypatch, algo):
        svc = _service(monkeypatch, algo=algo)
        assert len(svc._resident) == 1
        sc = svc._resident[0]
        assert sc.wire == "int8" and sc.placed_bytes > 0
        for query, want in CASES:
            assert svc._predict_one(query).label == want
        assert sc.dispatches > 0  # the predictions went through the device

    def test_stats_report_residency(self, monkeypatch):
        svc = _service(monkeypatch)
        _, out = svc.get_stats(type("R", (), {"params": {}})())
        res = out["residency"]
        assert res["enabled"] is True
        assert res["paramBytes"] == svc._resident[0].placed_bytes
        assert res["scorers"][0]["wire"] == "int8"

    def test_int8_parity_with_float32_wire(self, monkeypatch):
        svc8 = _service(monkeypatch)
        labels8 = [svc8._predict_one(q).label for q, _ in CASES]
        monkeypatch.setenv("PIO_TPU_SERVE_WIRE", "float32")
        svc8._load(None)
        assert svc8._resident[0].wire == "float32"
        labels32 = [svc8._predict_one(q).label for q, _ in CASES]
        assert labels8 == labels32

    def test_disabled_leaves_host_path(self, monkeypatch):
        svc = _service(monkeypatch, resident="0")
        assert svc._resident == []
        for query, want in CASES:
            assert svc._predict_one(query).label == want

    def test_hot_swap_retires_old_generation(self, monkeypatch):
        svc = _service(monkeypatch)
        old = svc._resident[0]
        gen0 = svc._buckets.generation
        svc._load(None)  # the /reload path
        assert svc._buckets.generation == gen0 + 1
        assert old.retired is True
        with pytest.raises(RuntimeError, match="retired"):
            old.score_codes(np.ones((1, 3), np.float32))
        new = svc._resident[0]
        assert new is not old and not new.retired
        for query, want in CASES:  # no stale-weights serving
            assert svc._predict_one(query).label == want

    def test_bucketed_batches_never_retrace_and_donate(self, monkeypatch):
        svc = _service(monkeypatch)
        sc = svc._resident[0]
        for i in range(30):
            qs = [q for q, _ in CASES][: (i % 3) + 1]
            results, fresh = svc._predict_batch_bucketed(qs)
            assert not fresh and len(results) == len(qs)
        assert svc._buckets.retraces == 0
        total = sc.donation_hits + sc.donation_misses
        assert sc.donation_hits / total >= 0.95  # steady-state hit rate

    def test_lane_packed_round_trips_exactly_vs_json(self, monkeypatch):
        svc = _service(monkeypatch)
        sc = svc._resident[0]
        for query, want in CASES:
            packed = svc._lane_pack(query)
            assert packed is not None and packed[:4] == PACKED_MAGIC
            pq = unpack_query_i8(packed)
            # the wire codes the drainer re-derives from the rebuilt
            # query are bit-identical to what crossed the ring
            rebuilt = sc.query_factory(sc.dequantize(pq.codes))
            assert np.array_equal(
                sc.quantize(rebuilt.vector(sc.in_dim))[0], pq.codes
            )
            # and the served results agree between the two wire forms
            json_body = {"attrs": list(query.attrs)}
            got = svc._lane_dispatch([pq, json_body])
            assert got[0] == got[1] == {"label": want}

    def test_lane_pack_declines_without_int8_scorer(self, monkeypatch):
        svc = _service(monkeypatch, resident="0")
        assert svc._lane_pack(CASES[0][0]) is None
