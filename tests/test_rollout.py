"""Progressive-delivery rollout tests (ISSUE 19): shadow diffing,
canary keyspace carve (0.0/1.0 degeneracy, stability), the stage
machine against live fake members with real manifest verification,
burn/mismatch/unreachable-triggered rollback within one judging
window, the rollback-restores-incumbent-byte-identically property,
the routerd HTTP surface, and /fleet.json federation."""

import hashlib
import json
import time
import urllib.error
import urllib.request

import pytest

from pio_tpu.obs import monotonic_s
from pio_tpu.obs.fleet import FleetAggregator
from pio_tpu.obs.metrics import MetricsRegistry
from pio_tpu.router.core import ServingRouter
from pio_tpu.router.deploy import (
    DeployVerifyError,
    manifest_digests,
    verify_instance,
)
from pio_tpu.router.rollout import (
    STAGES,
    RolloutConfig,
    RolloutController,
    RolloutMetrics,
    diff_answers,
)
from pio_tpu.server.http import JsonHTTPServer, Router, metrics_response
from pio_tpu.server.routerd import RouterService
from pio_tpu.workflow.shard_store import SHARD_MANIFEST_SUFFIX

KEYS = [f"user{i}" for i in range(400)]


def http(method, url, body=None, headers=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _wait_for(pred, timeout_s=8.0):
    deadline = monotonic_s() + timeout_s
    while monotonic_s() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


# ---------------------------------------------------------------------------
# shared sharded store: two instances with distinct shard bytes


class _Rec:
    def __init__(self, models):
        self.models = models


class _Store(dict):
    def get(self, k, default=None):
        return dict.get(self, k, default)


def _manifest_for(instance_id, shards):
    total = sum(len(b) for b in shards)
    rows = 2 * len(shards)
    return {
        "version": 1,
        "n_shards": len(shards),
        "mesh_shape": [len(shards)],
        "algos": [{
            "template": "als",
            "arrays": [{
                "name": "emb", "shape": [rows, total // rows or 1],
                "dtype": "int8", "spec": [["rows"]],
                "shards": [
                    {"id": f"{instance_id}.shard{i}",
                     "sha256": hashlib.sha256(b).hexdigest(),
                     "size": len(b), "rows": [2 * i, 2 * i + 2]}
                    for i, b in enumerate(shards)
                ],
            }],
        }],
    }


def _dual_store(inc_byte=b"\x01", cand_byte=b"\x7f"):
    """One models store holding an incumbent and a candidate instance
    whose shard bytes (and therefore sha256 sets) differ."""
    store = _Store()
    manifests = {}
    for iid, fill in (("inc", inc_byte), ("cand", cand_byte)):
        shards = [fill * 64, fill * 96]
        manifest = _manifest_for(iid, shards)
        manifests[iid] = manifest
        store[iid + SHARD_MANIFEST_SUFFIX] = _Rec(
            json.dumps(manifest).encode()
        )
        for i, b in enumerate(shards):
            store[f"{iid}.shard{i}"] = _Rec(b)
    return store, manifests


def _shas(manifest):
    return sorted(s for s, _ in manifest_digests(manifest).values())


# ---------------------------------------------------------------------------
# fake serving member with a real verify-before-swap deploy handler


class _Member:
    """Query member double: verifies pushed manifests against its own
    (shared) store before swapping — the same contract the real
    ``deploy_verified`` handler enforces — and reports its serving
    identity on ``GET /deploy.json``."""

    def __init__(self, name, store, instance=None, manifest=None,
                 score=1.0):
        self.name = name
        self.store = store
        self.instance = instance
        self.manifest = manifest
        self.generation = 1 if instance else 0
        self.score = score
        self.fail_queries = False
        self.reject_deploys = False
        #: (instanceId, sorted sha256 list, generation) per verified swap
        self.swaps = []
        self.queries_total = 0.0
        self.query_errors_total = 0.0
        router = Router()
        router.add("POST", "/queries\\.json", self.query)
        router.add("GET", "/metrics", self.metrics)
        router.add("POST", "/deploy\\.json", self.deploy)
        router.add("GET", "/deploy\\.json", self.deploy_report)
        self.server = JsonHTTPServer(
            router, "127.0.0.1", 0, name=f"member-{name}"
        ).start()
        self.url = f"http://127.0.0.1:{self.server.port}"

    def query(self, req):
        self.queries_total += 1
        if self.fail_queries:
            self.query_errors_total += 1
            return 500, {"message": "injected"}
        return 200, {
            "itemScores": [{"item": "i1", "score": self.score}],
            "member": self.name,
            "priority": req.header("X-Pio-Priority"),
        }

    def deploy(self, req):
        if self.reject_deploys:
            return 409, {"message": "deploy verification failed: refused"}
        body = req.body or {}
        iid = body.get("engineInstanceId")
        manifest = body.get("manifest")
        try:
            verify_instance(self.store, iid, expected=manifest)
        except DeployVerifyError as e:
            return 409, {"message": str(e)}
        self.instance, self.manifest = iid, manifest
        self.generation += 1
        self.swaps.append((iid, _shas(manifest), self.generation))
        return 200, {"verified": True, "member": self.name}

    def deploy_report(self, req):
        return 200, {
            "engineInstanceId": self.instance,
            "engineId": "e1",
            "manifestSha256": _shas(self.manifest) if self.manifest else [],
            "generation": self.generation,
        }

    def metrics(self, req):
        text = (
            f"pio_tpu_queries_total {self.queries_total}\n"
            f"pio_tpu_query_errors_total {self.query_errors_total}\n"
        )
        return 200, metrics_response(text)

    def stop(self):
        self.server.stop()


# ---------------------------------------------------------------------------
# answer diffing


class TestDiffAnswers:
    def test_byte_identical_matches(self):
        assert diff_answers(200, b'{"x":1}', 200, b'{"x":1}') == (True, [])

    def test_status_disagreement_mismatches(self):
        assert diff_answers(200, b"{}", 500, b"{}")[0] is False

    def test_scores_within_tolerance_match(self):
        a = json.dumps({"itemScores": [
            {"item": "i1", "score": 1.0}, {"item": "i2", "score": 2.0},
        ]}).encode()
        b = json.dumps({"itemScores": [
            {"item": "i2", "score": 2.0004}, {"item": "i1", "score": 1.0},
        ]}).encode()
        match, deltas = diff_answers(200, a, 200, b, score_tolerance=1e-3)
        assert match and len(deltas) == 2
        assert max(deltas) == pytest.approx(0.0004)

    def test_scores_beyond_tolerance_mismatch(self):
        a = json.dumps({"itemScores": [{"item": "i1", "score": 1.0}]})
        b = json.dumps({"itemScores": [{"item": "i1", "score": 1.5}]})
        match, deltas = diff_answers(
            200, a.encode(), 200, b.encode(), score_tolerance=1e-3
        )
        assert not match and deltas == [pytest.approx(0.5)]

    def test_disjoint_item_sets_mismatch(self):
        a = json.dumps({"itemScores": [{"item": "i1", "score": 1.0}]})
        b = json.dumps({"itemScores": [{"item": "i9", "score": 1.0}]})
        assert diff_answers(200, a.encode(), 200, b.encode())[0] is False

    def test_non_json_divergence_mismatches(self):
        assert diff_answers(200, b"abc", 200, b"abd")[0] is False

    def test_iid_spelling_accepted(self):
        a = json.dumps({"itemScores": [{"iid": "i1", "score": 1.0}]})
        b = json.dumps({"itemScores": [{"item": "i1", "score": 1.0}]})
        assert diff_answers(200, a.encode(), 200, b.encode())[0] is True


# ---------------------------------------------------------------------------
# canary keyspace carve


class _DummyCore:
    timeout_s = 1.0


def _controller(cfg, core=None, fetch=None, loader=None):
    return RolloutController(
        core if core is not None else _DummyCore(),
        cfg,
        RolloutMetrics(MetricsRegistry()),
        manifest_loader=loader if loader is not None else (lambda iid: None),
        fetch=fetch if fetch is not None else (lambda url, t: b""),
    )


class TestCanaryKeyspace:
    def _ctrl(self, fraction):
        return _controller(RolloutConfig(
            candidate_instance="cand",
            candidate_targets=[("cand0", "http://127.0.0.1:9")],
            canary_fraction=fraction,
        ))

    def test_fraction_zero_is_pure_incumbent(self):
        ctrl = self._ctrl(0.0)
        assert not any(ctrl.in_canary_keyspace(k) for k in KEYS)

    def test_fraction_one_is_pure_candidate(self):
        ctrl = self._ctrl(1.0)
        assert all(ctrl.in_canary_keyspace(k) for k in KEYS)

    def test_fraction_is_stable_and_roughly_proportional(self):
        ctrl = self._ctrl(0.3)
        hit = {k for k in KEYS if ctrl.in_canary_keyspace(k)}
        # entity-affine stability: the same entity answers the same way
        assert hit == {k for k in KEYS if ctrl.in_canary_keyspace(k)}
        assert 0.15 * len(KEYS) < len(hit) < 0.45 * len(KEYS)

    def test_consecutive_rollouts_carve_different_slices(self):
        a = self._ctrl(0.3)
        b = _controller(RolloutConfig(
            candidate_instance="cand2",
            candidate_targets=[("cand0", "http://127.0.0.1:9")],
            canary_fraction=0.3,
        ))
        hits_a = {k for k in KEYS if a.in_canary_keyspace(k)}
        hits_b = {k for k in KEYS if b.in_canary_keyspace(k)}
        assert hits_a != hits_b

    def test_divert_only_in_canary_stage(self):
        ctrl = self._ctrl(1.0)
        assert ctrl.divert("user1", "") is None  # stage is pending
        ctrl.stage = "canary"
        # shadow traffic never diverts (a mirror must not re-divert)
        assert ctrl.divert("user1", "shadow") is None
        assert ctrl.divert(None, "") is None


# ---------------------------------------------------------------------------
# judge: every rollback trigger, driven with an explicit clock


def _judge_ctrl(metrics_state, cfg_kw=None):
    """Controller parked in shadow with an injectable candidate scrape
    (``metrics_state`` dict renders as the candidate's /metrics)."""
    def fetch(url, timeout):
        if metrics_state.get("raise"):
            raise OSError("injected scrape failure")
        return (
            f"pio_tpu_queries_total {metrics_state['total']}\n"
            f"pio_tpu_query_errors_total {metrics_state['errors']}\n"
        ).encode()

    kw = dict(
        candidate_instance="cand",
        candidate_targets=[("cand0", "http://127.0.0.1:9")],
        incumbent_instance="inc",
        judge_fast_s=30.0,
        judge_slow_s=120.0,
        burn_limit=2.0,
        availability_objective=0.99,
        shadow_min_samples=10_000,  # park in shadow
        down_after_failures=3,
    )
    kw.update(cfg_kw or {})
    # a placeholder incumbent; nothing in these tests forwards to it
    core = ServingRouter(
        [("inc0", "http://127.0.0.1:9")], MetricsRegistry()
    )
    ctrl = _controller(RolloutConfig(**kw), core=core, fetch=fetch)
    ctrl.stage = "shadow"
    ctrl._stage_entered = 0.0
    return ctrl


class TestJudge:
    def test_clean_candidate_judges_ok(self):
        state = {"total": 100.0, "errors": 0.0}
        ctrl = _judge_ctrl(state)
        assert ctrl.judge_once(now=0.0) == "ok"
        state["total"] = 200.0
        assert ctrl.judge_once(now=10.0) == "ok"
        assert ctrl.last_verdict == "ok"
        assert ctrl.stage == "shadow"

    def test_slo_burn_rolls_back_within_one_window(self):
        state = {"total": 100.0, "errors": 0.0}
        ctrl = _judge_ctrl(state)
        assert ctrl.judge_once(now=0.0) == "ok"
        # 90 of the next 100 queries error: burn 90/(1-0.99) >> limit 2
        state["total"], state["errors"] = 200.0, 90.0
        assert ctrl.judge_once(now=10.0) == "rollback"
        assert ctrl.stage == "rolled_back"
        rb = next(e for e in ctrl.trail if e["to"] == "rolling_back")
        assert rb["signal"] == "slo_burn"
        assert rb["window"] == "30s/120s"
        assert ctrl.trail[-1]["to"] == "rolled_back"

    def test_unreachable_candidate_rolls_back(self):
        state = {"total": 100.0, "errors": 0.0, "raise": True}
        ctrl = _judge_ctrl(state)
        assert ctrl.judge_once(now=0.0) == "ok"   # 1st failure tolerated
        assert ctrl.judge_once(now=2.0) == "ok"   # 2nd
        assert ctrl.judge_once(now=4.0) == "rollback"
        rb = next(e for e in ctrl.trail if e["to"] == "rolling_back")
        assert rb["signal"] == "candidate_unreachable"

    def test_shadow_mismatch_rolls_back(self):
        state = {"total": 100.0, "errors": 0.0}
        ctrl = _judge_ctrl(state, {"shadow_min_samples": 50,
                                   "shadow_hold_s": 10_000.0,
                                   "mismatch_limit": 0.02})
        ctrl.shadow_matches, ctrl.shadow_mismatches = 45, 5
        assert ctrl.judge_once(now=0.0) == "rollback"
        rb = next(e for e in ctrl.trail if e["to"] == "rolling_back")
        assert rb["signal"] == "shadow_mismatch"

    def test_shadow_latency_blowup_rolls_back(self):
        state = {"total": 100.0, "errors": 0.0}
        ctrl = _judge_ctrl(state, {"latency_limit_x": 5.0})
        ctrl._lat_incumbent.extend([0.010] * 30)
        ctrl._lat_candidate.extend([0.200] * 30)
        assert ctrl.judge_once(now=0.0) == "rollback"
        rb = next(e for e in ctrl.trail if e["to"] == "rolling_back")
        assert rb["signal"] == "shadow_latency"

    def test_terminal_stage_is_sticky(self):
        state = {"total": 100.0, "errors": 0.0}
        ctrl = _judge_ctrl(state)
        ctrl.abort(by="test")
        assert ctrl.stage == "rolled_back"
        assert not ctrl.active()
        assert ctrl.judge_once(now=99.0) == "rolled_back"
        ctrl.abort(by="test")  # idempotent on a terminal stage
        assert sum(
            1 for e in ctrl.trail if e["to"] == "rolled_back"
        ) == 1


# ---------------------------------------------------------------------------
# the full stage machine against live members


class _Fabric:
    """One incumbent ring + one candidate member over a shared store,
    plus a ServingRouter and a controller-ready config."""

    def __init__(self, n_incumbents=1, inc_byte=b"\x01",
                 cand_byte=b"\x7f", **cfg_kw):
        self.store, self.manifests = _dual_store(inc_byte, cand_byte)
        self.incumbents = [
            _Member(f"inc{i}", self.store, instance="inc",
                    manifest=self.manifests["inc"])
            for i in range(n_incumbents)
        ]
        self.candidate = _Member("cand0", self.store)
        self.core = ServingRouter(
            [(m.name, m.url) for m in self.incumbents],
            MetricsRegistry(),
        )
        kw = dict(
            candidate_instance="cand",
            candidate_targets=[(self.candidate.name, self.candidate.url)],
            shadow_rate=1.0,
            shadow_min_samples=2,
            shadow_hold_s=0.0,
            canary_fraction=1.0,
            canary_hold_s=0.0,
            canary_min_requests=1,
            mismatch_limit=0.5,
        )
        kw.update(cfg_kw)
        self.ctrl = RolloutController(
            self.core,
            RolloutConfig(**kw),
            RolloutMetrics(self.core.obs),
            manifest_loader=self.manifests.get,
        )

    def observe_incumbent_relay(self, entity, body=None):
        """Synthesize one completed incumbent relay through the hook
        the router would call (the diffing side sees real bytes)."""
        if body is None:
            body = json.dumps({"user": entity}).encode()
        out = json.dumps(
            {"itemScores": [{"item": "i1", "score": 1.0}]}
        ).encode()
        self.ctrl.observe(
            "POST", "/queries.json", body,
            {"content-type": "application/json"}, entity, "",
            self.incumbents[0].name, 200, out, 0.002,
        )

    def close(self):
        self.ctrl.stop()
        self.core.close()
        for m in self.incumbents + [self.candidate]:
            m.stop()


@pytest.fixture()
def fabric():
    f = _Fabric()
    try:
        yield f
    finally:
        f.close()


class TestStageMachine:
    def test_shadow_to_canary_to_promoted(self, fabric):
        ctrl, core = fabric.ctrl, fabric.core

        ctrl._deploy_candidate()
        # incumbent discovered from the members' own GET /deploy.json
        assert ctrl.incumbent_instance == "inc"
        assert ctrl.incumbent_shas == _shas(fabric.manifests["inc"])
        # the candidate member verified the pushed manifest and swapped
        assert fabric.candidate.instance == "cand"
        # aux: pooled but never in the incumbent ring
        assert core.has_member("cand0")
        assert "cand0" not in core.ring.members
        assert [m.name for m in core.ring_members()] == ["inc0"]

        ctrl._enter_shadow()
        assert ctrl.stage == "shadow"
        for i in range(3):
            fabric.observe_incumbent_relay(f"user{i}")
        assert _wait_for(
            lambda: ctrl.shadow_matches + ctrl.shadow_mismatches >= 2
        ), "mirror worker never diffed the sampled relays"
        assert ctrl.shadow_mismatches == 0

        assert ctrl.judge_once() == "canary"
        assert ctrl.stage == "canary"
        # fraction 1.0: every keyed pick fronts the candidate, with the
        # incumbent plan behind it as the transparent retry
        plan = [m.name for m in core.pick("user1")]
        assert plan[0] == "cand0" and "inc0" in plan[1:]
        status, _, body, member = core.forward(
            "POST", "/queries.json",
            json.dumps({"user": "user1"}).encode(),
            {"content-type": "application/json"}, entity_id="user1",
        )
        assert status == 200 and member == "cand0"
        assert _wait_for(lambda: ctrl.canary_requests >= 1)

        assert ctrl.judge_once() == "promoted"
        assert ctrl.stage == "promoted"
        # the ring member's generation flipped to the candidate —
        # verified, never blind
        assert fabric.incumbents[0].instance == "cand"
        assert core.member("inc0").generation == "cand"
        # candidate aux member released, hooks detached
        assert not core.has_member("cand0")
        assert core._observer is None and core._divert is None
        signals = [e["signal"] for e in ctrl.trail]
        assert signals == [
            "start", "candidate_verified", "shadow_clean",
            "canary_clean", "all_verified",
        ]

    def test_canary_fraction_zero_never_diverts(self):
        f = _Fabric(canary_fraction=0.0)
        try:
            f.ctrl._deploy_candidate()
            f.ctrl._enter_shadow()
            f.ctrl._enter_canary(0, 0.0)
            for k in KEYS[:50]:
                assert [m.name for m in f.core.pick(k)] == ["inc0"]
        finally:
            f.close()

    def test_payload_shape(self, fabric):
        body = fabric.ctrl.payload()
        assert body["stage"] == "pending"
        assert body["stageCode"] == STAGES["pending"]
        assert body["candidateInstance"] == "cand"
        assert body["config"]["canaryFraction"] == 1.0
        assert body["shadow"]["samples"] == 0
        assert body["judge"]["lastVerdict"] is None
        assert body["trail"] == []


class TestRollbackProperty:
    @pytest.mark.parametrize("inc_byte,cand_byte", [
        (b"\x01", b"\x7f"),
        (b"\x22", b"\x23"),
        (b"\xaa", b"\x55"),
    ])
    def test_rollback_restores_incumbent_byte_identically(
        self, inc_byte, cand_byte
    ):
        """The property the runbook leans on: after any rollback, every
        member that flipped is back on the incumbent manifest with the
        exact sha256 set recorded at rollout start, and its swap
        generation only ever moved forward."""
        f = _Fabric(n_incumbents=2, inc_byte=inc_byte,
                    cand_byte=cand_byte)
        # inc1 refuses the candidate: promote must fail halfway and the
        # controller must walk inc0 back
        f.incumbents[1].reject_deploys = True
        try:
            before = {m.name: _shas(m.manifest) for m in f.incumbents}
            f.ctrl._deploy_candidate()
            f.ctrl._enter_shadow()
            f.ctrl._promote(canaried=0)

            assert f.ctrl.stage == "rolled_back"
            rb = next(
                e for e in f.ctrl.trail if e["to"] == "rolling_back"
            )
            assert rb["signal"] == "promote_failed"
            for m in f.incumbents:
                # byte identity: the restored manifest's digest set is
                # exactly the one recorded before the rollout touched
                # anything (the store never changed, so equal digests
                # mean equal bytes)
                assert m.instance == "inc"
                assert _shas(m.manifest) == before[m.name]
                assert _shas(m.manifest) == f.ctrl.incumbent_shas
            # generation strictly monotone through flip + restore
            gens = [g for _, _, g in f.incumbents[0].swaps]
            assert gens == sorted(gens) and len(set(gens)) == len(gens)
            assert f.incumbents[0].swaps[-1][0] == "inc"
            # router generation view restored too
            assert f.core.member("inc0").generation == "inc"
            assert not f.core.has_member("cand0")
        finally:
            f.close()

    def test_candidate_deploy_rejection_rolls_back_before_traffic(self):
        f = _Fabric()
        f.candidate.reject_deploys = True
        try:
            f.ctrl._run()
            assert f.ctrl.stage == "rolled_back"
            rb = next(
                e for e in f.ctrl.trail if e["to"] == "rolling_back"
            )
            assert rb["signal"] == "candidate_deploy_failed"
            # the incumbent never flipped and no mirror ever started
            assert f.incumbents[0].instance == "inc"
            assert f.ctrl.shadow_matches + f.ctrl.shadow_mismatches == 0
        finally:
            f.close()


# ---------------------------------------------------------------------------
# routerd HTTP surface


class TestRolloutHTTP:
    def _service(self, members):
        svc = RouterService(
            [(m.name, m.url) for m in members], interval_s=5.0
        )
        server = JsonHTTPServer(
            svc.router, "127.0.0.1", 0, name="test-routerd"
        ).start()
        return svc, server, f"http://127.0.0.1:{server.port}"

    def test_rollout_json_idle_shape(self):
        store, manifests = _dual_store()
        inc = _Member("inc0", store, "inc", manifests["inc"])
        svc, server, base = self._service([inc])
        try:
            status, body, _ = http("GET", f"{base}/rollout.json")
            assert status == 200
            assert json.loads(body) == {
                "stage": "idle", "generation": 0, "trail": [],
            }
        finally:
            server.stop()
            svc.stop()
            inc.stop()

    def test_start_validation_and_conflict(self, monkeypatch):
        from pio_tpu.storage import Storage

        store, manifests = _dual_store()
        monkeypatch.setattr(
            Storage, "get_model_data_models", staticmethod(lambda: store)
        )
        inc = _Member("inc0", store, "inc", manifests["inc"])
        cand = _Member("cand0", store)
        svc, server, base = self._service([inc])
        try:
            # no candidate instance
            assert http("POST", f"{base}/rollout", {})[0] == 400
            # bad knob value
            assert http("POST", f"{base}/rollout", {
                "engineInstanceId": "cand",
                "targets": f"127.0.0.1:{cand.server.port}",
                "canaryFraction": 7.0,
            })[0] == 400
            # no targets
            assert http("POST", f"{base}/rollout", {
                "engineInstanceId": "cand",
            })[0] == 400
            # abort with nothing started
            assert http("POST", f"{base}/rollout/abort", {})[0] == 404
            assert http("POST", f"{base}/rollout/approve", {})[0] == 404

            status, body, _ = http("POST", f"{base}/rollout", {
                "engineInstanceId": "cand",
                "targets": f"127.0.0.1:{cand.server.port}",
                "incumbentInstance": "inc",
                "shadowHoldSeconds": 600.0,  # park in shadow
                "judgeIntervalSeconds": 0.05,
            })
            assert status == 202
            assert json.loads(body)["rollout"]["stage"] in (
                "pending", "deploying", "shadow",
            )
            assert _wait_for(
                lambda: json.loads(
                    http("GET", f"{base}/rollout.json")[1]
                )["stage"] == "shadow"
            )
            # one judged rollout at a time
            assert http("POST", f"{base}/rollout", {
                "engineInstanceId": "cand2",
                "targets": f"127.0.0.1:{cand.server.port}",
            })[0] == 409

            status, body, _ = http("POST", f"{base}/rollout/abort", {})
            assert status == 200
            out = json.loads(body)["rollout"]
            assert out["stage"] in ("rolling_back", "rolled_back")
            assert _wait_for(
                lambda: json.loads(
                    http("GET", f"{base}/rollout.json")[1]
                )["stage"] == "rolled_back"
            )
            trail = json.loads(
                http("GET", f"{base}/rollout.json")[1]
            )["trail"]
            assert any(e["signal"] == "operator_abort" for e in trail)
            # terminal: a new rollout may start again
            status, _, _ = http("POST", f"{base}/rollout", {
                "engineInstanceId": "cand",
                "targets": f"127.0.0.1:{cand.server.port}",
                "incumbentInstance": "inc",
                "shadowHoldSeconds": 600.0,
            })
            assert status == 202
        finally:
            server.stop()
            svc.stop()
            inc.stop()
            cand.stop()


# ---------------------------------------------------------------------------
# /fleet.json federation


class TestFleetFederation:
    def test_rollout_block_federates_compactly(self):
        rollout_doc = {
            "stage": "canary", "generation": 3,
            "candidateInstance": "cand", "incumbentInstance": "inc",
            "shadow": {"samples": 120, "mismatchRate": 0.01},
            "canary": {"requests": 7},
            "judge": {"lastVerdict": "ok"},
            "trail": [
                {"to": "shadow", "signal": "candidate_verified"},
                {"to": "canary", "signal": "shadow_clean"},
            ],
        }

        def fetch(url, timeout):
            if url.endswith("/metrics"):
                return b"pio_tpu_queries_total 1\n"
            if url.endswith("/router.json"):
                return json.dumps({"ring": {"size": 1}}).encode()
            if url.endswith("/rollout.json"):
                return json.dumps(rollout_doc).encode()
            raise OSError("no such surface")

        agg = FleetAggregator(
            [("r1", "http://r1")], MetricsRegistry(), interval_s=5.0,
            fetch=fetch,
        )
        agg.scrape_once()
        entry = next(
            m for m in agg.fleet_payload()["members"]
            if m["member"] == "r1"
        )
        assert entry["rollout"] == {
            "stage": "canary", "generation": 3,
            "candidateInstance": "cand", "incumbentInstance": "inc",
            "lastVerdict": "ok", "shadowSamples": 120,
            "mismatchRate": 0.01, "canaryRequests": 7,
            "lastTransition": {"to": "canary", "signal": "shadow_clean"},
        }

    def test_idle_rollout_is_omitted(self):
        def fetch(url, timeout):
            if url.endswith("/metrics"):
                return b"pio_tpu_queries_total 1\n"
            if url.endswith("/router.json"):
                return json.dumps({"ring": {"size": 1}}).encode()
            if url.endswith("/rollout.json"):
                return json.dumps(
                    {"stage": "idle", "generation": 0, "trail": []}
                ).encode()
            raise OSError("no such surface")

        agg = FleetAggregator(
            [("r1", "http://r1")], MetricsRegistry(), interval_s=5.0,
            fetch=fetch,
        )
        agg.scrape_once()
        entry = agg.fleet_payload()["members"][0]
        assert entry["rollout"] is None
