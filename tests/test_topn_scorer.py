"""DeviceTopNScorer — device-resident serving scorer (pio_tpu/ops/topn.py).

Device and host paths must agree exactly (same factors, same queries);
the device path is forced on the simulated CPU backend via prefer_device.
"""

import pickle

import numpy as np
import pytest

from pio_tpu.ops.topn import DeviceTopNScorer, _bucket


def _factors(n_rows=37, n_cols=53, k=8, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.normal(size=(n_rows, k)).astype(np.float32),
        rng.normal(size=(n_cols, k)).astype(np.float32),
    )


def test_bucket():
    assert _bucket(1, 512) == 1
    assert _bucket(3, 512) == 4
    assert _bucket(16, 512) == 16
    assert _bucket(700, 512) == 512


@pytest.mark.parametrize("device", [False, True])
def test_topn_matches_naive(device):
    rows, cols = _factors()
    s = DeviceTopNScorer(rows, cols, prefer_device=device)
    codes = np.array([0, 3, 36, 7], np.int32)
    idx, vals = s.top_n_batch(codes, 5)
    assert idx.shape == (4, 5) and vals.shape == (4, 5)
    full = rows[codes] @ cols.T
    for b in range(4):
        want = np.argsort(-full[b])[:5]
        np.testing.assert_array_equal(idx[b], want)
        np.testing.assert_allclose(vals[b], full[b][want], rtol=1e-5)


@pytest.mark.parametrize("device", [False, True])
def test_topn_exclusion(device):
    rows, cols = _factors()
    s = DeviceTopNScorer(rows, cols, prefer_device=device)
    codes = np.array([1, 2], np.int32)
    full = rows[codes] @ cols.T
    # exclude each row's natural top-1; pad second row's slots with the
    # sentinel (>= n_cols)
    top1 = np.argsort(-full, axis=1)[:, 0]
    excl = np.stack([
        [top1[0], int(np.argsort(-full[0])[1])],
        [top1[1], s.n_cols],  # sentinel slot
    ]).astype(np.int32)
    idx, vals = s.top_n_batch(codes, 3, exclude=excl)
    assert top1[0] not in idx[0]
    assert int(np.argsort(-full[0])[1]) not in idx[0]
    assert top1[1] not in idx[1]
    # row 1 keeps its rank-2 item (only top-1 excluded)
    assert int(np.argsort(-full[1])[1]) == idx[1][0]


@pytest.mark.parametrize("device", [False, True])
def test_large_batch_chunks_and_n_clamp(device):
    rows, cols = _factors(n_rows=600, n_cols=17)
    s = DeviceTopNScorer(rows, cols, prefer_device=device)
    codes = np.arange(600, dtype=np.int32) % 600
    # n > n_cols clamps to n_cols; B > _MAX_BATCH_BUCKET chunks internally
    idx, vals = s.top_n_batch(codes, 99)
    assert idx.shape == (600, 17)
    full = rows[codes] @ cols.T
    np.testing.assert_array_equal(idx[123], np.argsort(-full[123]))


@pytest.mark.parametrize("device", [False, True])
def test_pairs_and_scores(device):
    rows, cols = _factors()
    s = DeviceTopNScorer(rows, cols, prefer_device=device)
    rc = np.array([0, 5], np.int32)
    cc = np.array([7, 9], np.int32)
    np.testing.assert_allclose(
        s.score_pairs(rc, cc),
        np.einsum("bk,bk->b", rows[rc], cols[cc]),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        s.scores_batch(rc), rows[rc] @ cols.T, rtol=1e-5
    )


def test_adaptive_routing_by_link_speed():
    """Auto mode routes by batch size: a slow link sends small batches to
    the host mirror; a fast link sends everything to the device."""
    rows, cols = _factors()
    slow = DeviceTopNScorer(rows, cols, link_rtt_s=10.0)  # tunneled link
    assert slow.on_device
    assert slow.min_device_batch > 1_000  # B=1 stays on host
    assert not slow._route_to_device(1)
    fast = DeviceTopNScorer(rows, cols, link_rtt_s=0.0)  # local PCIe/ICI
    assert fast.min_device_batch == 1
    assert fast._route_to_device(1)
    # both produce identical results for the same query
    codes = np.array([4, 9], np.int32)
    i1, v1 = slow.top_n_batch(codes, 3)
    i2, v2 = fast.top_n_batch(codes, 3)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_allclose(v1, v2, rtol=1e-5)


def test_env_override_forces_host(monkeypatch):
    monkeypatch.setenv("PIO_TPU_SERVE_DEVICE", "0")
    rows, cols = _factors()
    s = DeviceTopNScorer(rows, cols)
    assert not s.on_device
    monkeypatch.setenv("PIO_TPU_SERVE_DEVICE", "1")
    s = DeviceTopNScorer(rows, cols)
    assert s.on_device and s.min_device_batch == 1


def test_pair_routing_stays_on_host_for_small_batches():
    """Pair dots are ~n_cols× cheaper than a score row on host, so their
    device break-even batch is much larger."""
    rows, cols = _factors()
    s = DeviceTopNScorer(rows, cols, link_rtt_s=1e-3)
    assert s.min_pair_batch >= s.min_device_batch
    np.testing.assert_allclose(
        s.score_pairs([1], [2]), [float(rows[1] @ cols[2])], rtol=1e-5
    )


def test_predict_num_zero_returns_empty():
    """query.num <= 0 must yield an empty result on the online path too
    (parity with the pre-scorer behavior and with batch_predict)."""
    from pio_tpu.data.bimap import BiMap
    from pio_tpu.models.als import ALSFactors
    from pio_tpu.templates.recommendation import ALSAlgorithm, ALSModel, Query

    rows, cols = _factors()
    m = ALSModel(
        ALSFactors(rows, cols),
        BiMap.string_int([f"u{i}" for i in range(len(rows))]),
        BiMap.string_int([f"i{i}" for i in range(len(cols))]),
    )
    algo = ALSAlgorithm(None)
    assert algo.predict(m, Query(user="u1", num=0)).item_scores == ()
    assert dict(algo.batch_predict(
        m, [(0, Query(user="u1", num=0))]
    ))[0].item_scores == ()


def test_pairs_beyond_chunk_cap():
    """score_pairs must chunk, not crash, past the 2^20 dispatch cap."""
    rows, cols = _factors(n_rows=50, n_cols=60)
    s = DeviceTopNScorer(rows, cols, prefer_device=True)
    rng = np.random.default_rng(1)
    B = (1 << 20) + 3
    rc = rng.integers(0, 50, B).astype(np.int32)
    cc = rng.integers(0, 60, B).astype(np.int32)
    got = s.score_pairs(rc, cc)
    assert got.shape == (B,)
    np.testing.assert_allclose(
        got[-5:], np.einsum("bk,bk->b", rows[rc[-5:]], cols[cc[-5:]]),
        rtol=1e-5,
    )


def test_exclusion_widths_share_compiles():
    """Exclusion width is bucketed: different raw E values give the same
    (correct) answer and reuse pow-2-bucketed jitted shapes."""
    rows, cols = _factors()
    s = DeviceTopNScorer(rows, cols, prefer_device=True)
    codes = np.array([3], np.int32)
    full = rows[3] @ cols.T
    top = np.argsort(-full)
    for E in (1, 2, 3, 5, 9):
        excl = np.array([top[:E]], np.int32)
        idx, _ = s.top_n_batch(codes, 3, exclude=excl)
        np.testing.assert_array_equal(idx[0], top[E:E + 3])


def test_batch_negative_num_matches_online():
    """num <= 0 gives an empty result on BOTH serving paths."""
    from pio_tpu.data.bimap import BiMap
    from pio_tpu.models.als import ALSFactors
    from pio_tpu.templates.recommendation import ALSAlgorithm, ALSModel, Query

    rows, cols = _factors()
    m = ALSModel(
        ALSFactors(rows, cols),
        BiMap.string_int([f"u{i}" for i in range(len(rows))]),
        BiMap.string_int([f"i{i}" for i in range(len(cols))]),
    )
    algo = ALSAlgorithm(None)
    q = Query(user="u2", num=-1)
    assert algo.predict(m, q).item_scores == ()
    got = dict(algo.batch_predict(m, [(0, Query(user="u1", num=5)), (1, q)]))
    assert got[1].item_scores == ()
    assert len(got[0].item_scores) == 5


def test_empty_batch():
    rows, cols = _factors()
    s = DeviceTopNScorer(rows, cols, prefer_device=True)
    idx, vals = s.top_n_batch(np.empty(0, np.int32), 5)
    assert idx.shape == (0, 5)


def test_rank_mismatch_rejected():
    rows, cols = _factors()
    with pytest.raises(ValueError):
        DeviceTopNScorer(rows, cols[:, :4])


def test_empty_factor_tables():
    """Zero-row/zero-col tables must construct (no host-probe indexing)
    and score to empty results instead of raising."""
    rows, cols = _factors()
    for r, c in [
        (np.empty((0, rows.shape[1]), np.float32), cols),
        (rows, np.empty((0, rows.shape[1]), np.float32)),
    ]:
        s = DeviceTopNScorer(r, c)  # auto mode: would probe if unguarded
        assert not s.on_device
        if s.n_cols == 0:
            idx, vals = s.top_n_batch(np.empty(0, np.int32), 5)
            assert idx.shape == (0, 0) and vals.shape == (0, 0)
        assert s.score_pairs(
            np.empty(0, np.int32), np.empty(0, np.int32)
        ).shape == (0,)


def test_model_pickle_drops_scorer():
    """Deployed models lazily cache a scorer; serialization must drop the
    device handles (they rebuild on the next host)."""
    from pio_tpu.data.bimap import BiMap
    from pio_tpu.models.als import ALSFactors
    from pio_tpu.templates.recommendation import ALSModel

    rows, cols = _factors()
    m = ALSModel(
        ALSFactors(rows, cols),
        BiMap.string_int([f"u{i}" for i in range(len(rows))]),
        BiMap.string_int([f"i{i}" for i in range(len(cols))]),
    )
    m.scorer(warmup=False)
    assert "_scorer" in m.__dict__
    m2 = pickle.loads(pickle.dumps(m))
    assert "_scorer" not in m2.__dict__
    # and the revived model still serves
    idx, vals = m2.scorer().top_n_batch(np.array([0], np.int32), 3)
    assert idx.shape == (1, 3)


def test_prepare_for_serving_attaches_scorer():
    """Engine.algorithms_with_models runs the deploy-time serving prep."""
    from pio_tpu.controller.engine import EngineParams
    from pio_tpu.data.bimap import BiMap
    from pio_tpu.models.als import ALSFactors
    from pio_tpu.templates.recommendation import (
        ALSModel, recommendation_engine,
    )

    rows, cols = _factors()
    model = ALSModel(
        ALSFactors(rows, cols),
        BiMap.string_int([f"u{i}" for i in range(len(rows))]),
        BiMap.string_int([f"i{i}" for i in range(len(cols))]),
    )
    engine = recommendation_engine()
    ep = EngineParams(algorithm_params_list=(("als", None),))
    pairs = engine.algorithms_with_models(ep, [model])
    assert "_scorer" in pairs[0][1].__dict__


class TestNativeHostScorer:
    """Fused native scan-and-select vs the numpy reference path."""

    @pytest.fixture(autouse=True)
    def _require_native(self):
        # without the toolchain both paths would be numpy — a parity
        # test against itself proves nothing
        from pio_tpu.native import NativeUnavailable, topn_host_lib

        try:
            topn_host_lib()
        except NativeUnavailable:
            pytest.skip("no C++ toolchain: native scorer not buildable")

    def test_parity_with_numpy_path(self):
        rng = np.random.default_rng(7)
        rows = rng.normal(size=(300, 8)).astype(np.float32)
        cols = rng.normal(size=(500, 8)).astype(np.float32)
        s = DeviceTopNScorer(rows, cols, prefer_device=False)
        codes = rng.integers(0, 300, 8).astype(np.int32)
        for n in (1, 5, 10, 500):  # incl. n == n_cols (full sort)
            i_nat, v_nat = s.top_n_batch(codes, n)
            native = s._top_n_host_native
            s._top_n_host_native = lambda c, k: None
            try:
                i_np, v_np = s.top_n_batch(codes, n)
            finally:
                s._top_n_host_native = native
            assert np.array_equal(i_nat, i_np), n
            assert np.allclose(v_nat, v_np), n

    def test_nan_scores_do_not_crash(self):
        """NaN factors (diverged model) must rank last, not crash the
        comparator (strict-weak-ordering UB in std::sort)."""
        rng = np.random.default_rng(10)
        rows = np.ones((4, 4), np.float32)
        cols = rng.normal(size=(200, 4)).astype(np.float32)
        cols[::3] = np.nan  # third of the table poisoned
        s = DeviceTopNScorer(rows, cols, prefer_device=False)
        idx, vals = s.top_n_batch(np.array([0], np.int32), 10)
        assert np.isfinite(vals).all()  # NaN rows never outrank real ones
        assert not (set(idx.flat) & set(range(0, 200, 3)))

    def test_exclusions_use_numpy_path(self):
        """The native kernel doesn't handle exclusions — masked queries
        must still produce masked results (numpy path)."""
        rng = np.random.default_rng(8)
        rows = rng.normal(size=(20, 4)).astype(np.float32)
        cols = rng.normal(size=(30, 4)).astype(np.float32)
        s = DeviceTopNScorer(rows, cols, prefer_device=False)
        codes = np.arange(3, dtype=np.int32)
        excl = np.tile(np.array([[0, 1, 2]], np.int32), (3, 1))
        idx, _ = s.top_n_batch(codes, 5, exclude=excl)
        assert not (set(idx.flat) & {0, 1, 2})

    def test_rank_zero_degenerate(self):
        """Rank-0 factor tables (0 == 0 passes the mismatch check) must
        score everything 0 and rank by index — no out-of-bounds read."""
        rows = np.empty((3, 0), np.float32)
        cols = np.empty((5, 0), np.float32)
        s = DeviceTopNScorer(rows, cols, prefer_device=False)
        idx, vals = s.top_n_batch(np.array([0, 2], np.int32), 3)
        assert np.array_equal(idx, [[0, 1, 2], [0, 1, 2]])
        assert np.all(vals == 0.0)

    def test_tiny_table_smaller_than_topn(self):
        rng = np.random.default_rng(9)
        rows = rng.normal(size=(4, 4)).astype(np.float32)
        cols = rng.normal(size=(3, 4)).astype(np.float32)
        s = DeviceTopNScorer(rows, cols, prefer_device=False)
        idx, vals = s.top_n_batch(np.array([1], np.int32), 10)
        assert idx.shape == (1, 3)  # clamped to n_cols
        assert sorted(idx[0].tolist()) == [0, 1, 2]
