"""Ready-made evaluations for the e-commerce, two-tower, and sequence
templates — with these, every bundled template is `pio eval`-able
(SURVEY.md §2.5: each reference template ships an Evaluation).

Also covers the e-commerce vectorized batch_predict (one matmul per batch
of known users, constraint snapshot per call) against the per-query loop.
"""

import datetime as dt

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers engine factories)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.storage import App, Storage
from pio_tpu.workflow import (
    build_engine,
    load_models_for_instance,
    run_evaluation,
    run_train,
    variant_from_dict,
)


@pytest.fixture(autouse=True)
def _home(tmp_home):
    return tmp_home


def _seed_grouped_views(app_id, n_users=12, n_items=8, per_user=8):
    """u views items of group u % 2 (tech/food split), repeatedly."""
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 4, 1, tzinfo=dt.timezone.utc)
    for i in range(n_items):
        le.insert(
            Event("$set", "item", f"i{i}",
                  properties={"categories": ["tech" if i < 4 else "food"]},
                  event_time=t0),
            app_id,
        )
    rng = np.random.default_rng(0)
    k = 0
    for u in range(n_users):
        lo = 0 if u % 2 == 0 else 4
        for _ in range(per_user):
            i = lo + int(rng.integers(0, 4))
            le.insert(
                Event("view", "user", f"u{u}", "item", f"i{i}",
                      event_time=t0 + dt.timedelta(minutes=k)),
                app_id,
            )
            k += 1


def _seed_cycles(app_id, n_users=12, V=6, length=9):
    """User u walks the item cycle starting at u % V — the next item is
    deterministic, so next-item eval has a learnable answer."""
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 4, 2, tzinfo=dt.timezone.utc)
    for u in range(n_users):
        for k in range(length):
            le.insert(
                Event("view", "user", f"u{u}", "item",
                      f"i{(u + k) % V}",
                      event_time=t0 + dt.timedelta(minutes=k)),
                app_id,
            )


class TestECommerceEvaluation:
    def test_eval_sweep(self):
        from pio_tpu.templates.ecommerce import ecommerce_evaluation

        Storage.get_meta_data_apps().insert(App(0, "ec-eval"))
        app_id = Storage.get_meta_data_apps().get_by_name("ec-eval").id
        _seed_grouped_views(app_id)
        ev = ecommerce_evaluation(
            app_name="ec-eval", eval_k=3, ranks=(4,), num_iterations=8,
            eval_num=2,
        )
        result = run_evaluation(
            ev, ev.engine_params_generator, ctx=ComputeContext.create()
        )
        # grouped data: held-out views come from the user's own 4-item
        # group, so HitRate@2 must clear random-over-catalog (2/8)
        assert result.best_score > 0.3, result.best_score
        insts = Storage.get_meta_data_evaluation_instances().get_all()
        assert insts[0].status == "COMPLETED"

    def test_eval_k1_rejected(self):
        from pio_tpu.templates.ecommerce import (
            DataSourceParams, ECommerceDataSource,
        )

        ds = ECommerceDataSource(
            DataSourceParams(app_name="x", eval_k=1)
        )
        with pytest.raises(ValueError, match="eval_k >= 2"):
            ds.read_eval(ComputeContext.local())


class TestTwoTowerEvaluation:
    def test_eval_sweep(self):
        from pio_tpu.templates.twotower import twotower_evaluation

        Storage.get_meta_data_apps().insert(App(0, "tt-eval"))
        app_id = Storage.get_meta_data_apps().get_by_name("tt-eval").id
        # the recommendation datasource reads rate/buy; seed buys
        le = Storage.get_levents()
        t0 = dt.datetime(2026, 4, 3, tzinfo=dt.timezone.utc)
        rng = np.random.default_rng(1)
        for u in range(12):
            lo = 0 if u % 2 == 0 else 4
            for k in range(8):
                le.insert(
                    Event("buy", "user", f"u{u}", "item",
                          f"i{lo + int(rng.integers(0, 4))}",
                          event_time=t0 + dt.timedelta(minutes=k)),
                    app_id,
                )
        ev = twotower_evaluation(
            app_name="tt-eval", eval_k=2, eval_num=4, out_dims=(8,),
            steps=80, batch_size=32,
        )
        result = run_evaluation(
            ev, ev.engine_params_generator, ctx=ComputeContext.create()
        )
        assert 0.0 <= result.best_score <= 1.0
        # HitRate@4 on an 8-item catalog: even weak retrieval beats 0
        assert result.best_score > 0.2, result.best_score

    def test_hitrate_mode_read_eval_shape(self):
        from pio_tpu.templates.recommendation import (
            DataSourceParams, RecommendationDataSource,
        )

        Storage.get_meta_data_apps().insert(App(0, "hr-shape"))
        app_id = Storage.get_meta_data_apps().get_by_name("hr-shape").id
        le = Storage.get_levents()
        t0 = dt.datetime(2026, 4, 4, tzinfo=dt.timezone.utc)
        for u in range(4):
            for i in range(4):
                # duplicate interactions: the dedup must keep the held-out
                # pair out of the training fold
                for _ in range(2):
                    le.insert(
                        Event("buy", "user", f"u{u}", "item", f"i{i}",
                              event_time=t0),
                        app_id,
                    )
        ds = RecommendationDataSource(DataSourceParams(
            app_name="hr-shape", eval_k=2, eval_mode="hitrate", eval_num=3,
        ))
        folds = ds.read_eval(ComputeContext.local())
        assert len(folds) == 2
        for td, _info, qa in folds:
            train_pairs = set(zip(td.user_ids, td.item_ids))
            for q, actual in qa:
                assert q.num == 3 and q.item == ""  # top-N, not pair-score
                # no cross-fold leakage even with duplicate events
                assert (q.user, actual) not in train_pairs
                # seen-exclusion: the query black-lists the user's
                # training-fold items, never the held-out answer
                assert actual not in q.black_list
                assert set(q.black_list) == {
                    i for u, i in train_pairs if u == q.user
                }

    def test_blacklist_never_serves_excluded_items(self):
        """When exclusions leave fewer than num finite items, the result
        shortens — black-listed slots must not surface as -inf scores."""
        import numpy as np

        from pio_tpu.data.bimap import BiMap
        from pio_tpu.models.als import ALSFactors
        from pio_tpu.templates.recommendation import (
            ALSAlgorithm, ALSModel, Query,
        )

        rng = np.random.default_rng(2)
        m = ALSModel(
            ALSFactors(
                rng.normal(size=(3, 4)).astype(np.float32),
                rng.normal(size=(4, 4)).astype(np.float32),
            ),
            BiMap.string_int([f"u{i}" for i in range(3)]),
            BiMap.string_int([f"i{i}" for i in range(4)]),
        )
        algo = ALSAlgorithm(None)
        q = Query(user="u0", num=4, black_list=("i0", "i1", "i2"))
        got = algo.predict(m, q)
        assert [s.item for s in got.item_scores] == ["i3"]
        assert all(np.isfinite(s.score) for s in got.item_scores)
        bat = dict(algo.batch_predict(m, [(0, q)]))[0]
        assert [s.item for s in bat.item_scores] == ["i3"]

    def test_reference_lambda_param_binds(self):
        """Reference engine.json uses the keyword 'lambda'; it must bind
        to the lambda_ field."""
        from pio_tpu.controller.params import params_from_dict
        from pio_tpu.templates.recommendation import ALSAlgorithmParams

        p = params_from_dict(
            ALSAlgorithmParams, {"rank": 4, "lambda": 0.5}
        )
        assert p.lambda_ == 0.5

    def test_blacklist_respected_in_serving(self):
        """Query.black_list must mask items on BOTH serving paths."""
        import numpy as np

        from pio_tpu.data.bimap import BiMap
        from pio_tpu.models.als import ALSFactors
        from pio_tpu.templates.recommendation import (
            ALSAlgorithm, ALSModel, Query,
        )

        rng = np.random.default_rng(0)
        m = ALSModel(
            ALSFactors(
                rng.normal(size=(5, 6)).astype(np.float32),
                rng.normal(size=(9, 6)).astype(np.float32),
            ),
            BiMap.string_int([f"u{i}" for i in range(5)]),
            BiMap.string_int([f"i{i}" for i in range(9)]),
        )
        algo = ALSAlgorithm(None)
        full = algo.predict(m, Query(user="u1", num=3))
        top1 = full.item_scores[0].item
        q = Query(user="u1", num=3, black_list=(top1, "ghost"))
        masked = algo.predict(m, q)
        assert top1 not in [s.item for s in masked.item_scores]
        bat = dict(algo.batch_predict(m, [(0, q)]))[0]
        assert [s.item for s in bat.item_scores] == [
            s.item for s in masked.item_scores
        ]

    def test_bad_eval_mode_rejected(self):
        from pio_tpu.templates.recommendation import (
            DataSourceParams, RecommendationDataSource,
        )

        ds = RecommendationDataSource(DataSourceParams(
            app_name="x", eval_k=2, eval_mode="nonsense",
        ))
        with pytest.raises(ValueError, match="eval_mode"):
            ds.read_eval(ComputeContext.local())


class TestSequenceEvaluation:
    def test_eval_sweep(self):
        from pio_tpu.templates.sequence import sequence_evaluation

        Storage.get_meta_data_apps().insert(App(0, "sq-eval"))
        app_id = Storage.get_meta_data_apps().get_by_name("sq-eval").id
        _seed_cycles(app_id)
        ev = sequence_evaluation(
            app_name="sq-eval", eval_k=3, eval_num=2, layer_grid=(1,),
            steps=120, d_model=16, max_len=16,
        )
        result = run_evaluation(
            ev, ev.engine_params_generator, ctx=ComputeContext.create()
        )
        # deterministic cycles: the next item is learnable; HitRate@2 on
        # a 6-item vocab must clear random (2/6)
        assert result.best_score > 0.34, result.best_score

    def test_leave_last_out_shapes(self):
        from pio_tpu.templates.sequence import (
            DataSourceParams, SequenceDataSource,
        )

        Storage.get_meta_data_apps().insert(App(0, "sq-shape"))
        app_id = Storage.get_meta_data_apps().get_by_name("sq-shape").id
        _seed_cycles(app_id, n_users=6, V=4, length=5)
        ds = SequenceDataSource(DataSourceParams(
            app_name="sq-shape", eval_k=2, eval_num=1,
        ))
        folds = ds.read_eval(ComputeContext.local())
        assert len(folds) == 2
        all_queried = 0
        for td, _info, qa in folds:
            for q, actual in qa:
                # the held-out item is the user's true last event...
                assert isinstance(actual, str)
                # ...and never appears at the end of any training history
                # row fed to this fold for that user
                assert len(q.history) == 4  # length-5 walk minus holdout
                all_queried += 1
        assert all_queried == 6  # every user evaluated exactly once


class TestECommerceBatchPredict:
    def test_batch_matches_loop(self):
        from pio_tpu.templates.ecommerce import Query

        Storage.get_meta_data_apps().insert(App(0, "ec-bp"))
        app_id = Storage.get_meta_data_apps().get_by_name("ec-bp").id
        _seed_grouped_views(app_id)
        # constraint entity: i0 is unavailable
        Storage.get_levents().insert(
            Event("$set", "constraint", "unavailableItems",
                  properties={"items": ["i0"]},
                  event_time=dt.datetime(2026, 4, 5,
                                         tzinfo=dt.timezone.utc)),
            app_id,
        )
        variant = variant_from_dict({
            "id": "ec-bp", "engineFactory": "templates.ecommerce",
            "datasource": {"params": {"app_name": "ec-bp"}},
            "algorithms": [{"name": "ecomm", "params": {
                "app_name": "ec-bp", "rank": 4, "num_iterations": 8,
                "unseen_only": True,
            }}],
        })
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        iid = run_train(engine, ep, variant, ctx=ctx)
        models = load_models_for_instance(iid, engine, ep, ctx)
        algo, model = engine.algorithms_with_models(ep, models)[0]
        queries = (
            [(i, Query(user=f"u{i % 12}", num=3)) for i in range(16)]
            + [(90, Query(user="u1", num=3, categories=("food",)))]
            + [(91, Query(user="coldshopper", num=3))]  # unknown user
        )
        loop = {i: algo.predict(model, q) for i, q in queries}
        bat = dict(algo.batch_predict(model, queries))
        assert set(loop) == set(bat)
        for i in loop:
            assert [s.item for s in loop[i].item_scores] == [
                s.item for s in bat[i].item_scores
            ], f"query {i}"
        # the constraint held in both paths
        for res in bat.values():
            assert all(s.item != "i0" for s in res.item_scores)
