"""Fixture engine with recording components (reference ``SampleEngine.scala``
pattern, SURVEY.md §4): tiny deterministic DASE components whose TD/PD/models
are dataclasses recording the params they saw — tests assert pipeline
plumbing, not ML quality.
"""

import dataclasses
from typing import List, Optional, Tuple

from pio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
    SanityCheck,
    Serving,
    register_engine,
)


@dataclasses.dataclass(frozen=True)
class DSParams(Params):
    id: int = 0
    fail_sanity: bool = False
    eval_folds: int = 0


@dataclasses.dataclass(frozen=True)
class PrepParams(Params):
    id: int = 0


@dataclasses.dataclass(frozen=True)
class AlgoParams(Params):
    id: int = 0
    mult: int = 1


@dataclasses.dataclass(frozen=True)
class ServParams(Params):
    id: int = 0


@dataclasses.dataclass
class TrainingData(SanityCheck):
    ds_id: int
    fail_sanity: bool = False
    sanity_checked: bool = False

    def sanity_check(self):
        self.sanity_checked = True
        if self.fail_sanity:
            raise ValueError("sanity check failed: empty training data")


@dataclasses.dataclass
class PreparedData:
    td: TrainingData
    prep_id: int


@dataclasses.dataclass
class FixtureModel:
    algo_id: int
    mult: int
    prep_id: int
    ds_id: int


class FixtureDataSource(DataSource):
    params_class = DSParams

    def read_training(self, ctx):
        return TrainingData(ds_id=self.params.id, fail_sanity=self.params.fail_sanity)

    def read_eval(self, ctx):
        folds = []
        for fold in range(self.params.eval_folds):
            td = TrainingData(ds_id=self.params.id)
            qa = [(q, q * 2) for q in range(3)]  # actual = query * 2
            folds.append((td, {"fold": fold}, qa))
        return folds


class FixturePreparator(Preparator):
    params_class = PrepParams

    def prepare(self, ctx, td):
        return PreparedData(td=td, prep_id=self.params.id)


class FixtureAlgo(Algorithm):
    params_class = AlgoParams

    def train(self, ctx, pd):
        return FixtureModel(
            algo_id=self.params.id,
            mult=self.params.mult,
            prep_id=pd.prep_id,
            ds_id=pd.td.ds_id,
        )

    def predict(self, model, query):
        return query * model.mult


class FixtureServing(Serving):
    params_class = ServParams

    def serve(self, query, predictions):
        return max(predictions)


@register_engine("fixture-engine")
def fixture_engine() -> Engine:
    return Engine(
        FixtureDataSource,
        FixturePreparator,
        {"algo": FixtureAlgo, "algo2": FixtureAlgo},
        FixtureServing,
    )
