"""Serving fabric tests (ISSUE 18): rendezvous ring math (partlog
co-location agreement, churn remaps only the affected keyspace),
router core pick/forward/retry/shed against live fake members,
manifest-verified deploys, hedged requests and headroom-aware
spreading (ISSUE 19), and the routerd HTTP surface including the
packed int8 passthrough."""

import json
import time
import urllib.error
import urllib.request

import pytest

from pio_tpu.obs import monotonic_s
from pio_tpu.obs.metrics import MetricsRegistry
from pio_tpu.router.core import ServingRouter, Shed, forward_headers
from pio_tpu.router.deploy import (
    DeployVerifyError,
    manifest_digests,
    verify_instance,
)
from pio_tpu.router.ring import Ring, hrw_score, slot_of
from pio_tpu.server.http import (
    PACKED_QUERY_CONTENT_TYPE,
    JsonHTTPServer,
    RawResponse,
    Router,
    metrics_response,
)
from pio_tpu.server.routerd import RouterService, entity_of

KEYS = [f"user{i}" for i in range(400)]


def http(method, url, body=None, headers=None, raw=None):
    data = raw if raw is not None else (
        json.dumps(body).encode() if body is not None else None
    )
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


# ---------------------------------------------------------------------------
# ring math


class TestRing:
    def test_slot_matches_partlog_crc32(self):
        """Co-location: the ring's partition slot is byte-for-byte the
        partlog event partition."""
        from pio_tpu.storage.partlog.partitioned import partition_of

        members = ["h3:8000", "h1:8000", "h2:8000"]
        ring = Ring(members, partitions=3)
        ordered = sorted(members)
        for k in KEYS:
            assert slot_of(k, 3) == partition_of(k, 3)
            assert ring.slot_owner(k) == ordered[partition_of(k, 3)]
            assert ring.rank(k)[0] == ordered[partition_of(k, 3)]

    def test_affinity_off_when_counts_differ(self):
        ring = Ring(["a", "b", "c"], partitions=4)
        assert ring.slot_owner("user1") is None

    def test_rank_is_a_permutation_and_deterministic(self):
        members = [f"m{i}" for i in range(5)]
        ring = Ring(members)
        for k in KEYS[:50]:
            order = ring.rank(k)
            assert sorted(order) == sorted(members)
            assert order == Ring(members).rank(k)

    def test_hrw_score_is_process_stable(self):
        # blake2b, not hash(): same score in every process
        assert hrw_score("m1", "user7") == hrw_score("m1", "user7")
        assert hrw_score("m1", "user7") != hrw_score("m2", "user7")

    def test_removal_remaps_only_failed_keyspace(self):
        """The HRW property: keys whose primary survives keep it."""
        members = [f"m{i}" for i in range(5)]
        ring = Ring(members)
        before = ring.keyspace(KEYS)
        for dead in members:
            live = [m for m in members if m != dead]
            after = ring.keyspace(KEYS, routable=live)
            for k in KEYS:
                if before[k] != dead:
                    assert after[k] == before[k]
                else:
                    assert after[k] != dead

    def test_affine_removal_remaps_only_failed_slot(self):
        """With partition affinity engaged, killing one member moves
        only its slot's keys; reviving it moves them straight back."""
        members = [f"m{i}" for i in range(4)]
        ring = Ring(members, partitions=4)
        before = ring.keyspace(KEYS)
        dead = "m2"
        live = [m for m in members if m != dead]
        after = ring.keyspace(KEYS, routable=live)
        moved = [k for k in KEYS if before[k] != after[k]]
        assert moved, "some keys must have lived on the dead member"
        for k in moved:
            assert before[k] == dead
        # recovery: the full ring reproduces the original placement
        assert ring.keyspace(KEYS) == before

    def test_addition_steals_only_own_keyspace(self):
        members = [f"m{i}" for i in range(4)]
        grown = members + ["m9"]
        before = Ring(members).keyspace(KEYS)
        after = Ring(grown).keyspace(KEYS)
        for k in KEYS:
            if after[k] != before[k]:
                assert after[k] == "m9"

    def test_spread_is_roughly_uniform(self):
        counts = {}
        ring = Ring([f"m{i}" for i in range(4)])
        for k, m in ring.keyspace(KEYS).items():
            counts[m] = counts.get(m, 0) + 1
        assert min(counts.values()) > len(KEYS) / 4 / 3


# ---------------------------------------------------------------------------
# fake serving members


class _FakeMember:
    """Minimal member: /queries.json echoes which member answered (and
    the wire it saw), /metrics is a real registry render."""

    def __init__(self, name):
        self.name = name
        self.delay_s = 0.0
        self.obs = MetricsRegistry()
        router = Router()
        router.add("POST", "/queries\\.json", self.query)
        router.add("GET", "/metrics", self.metrics)
        router.add("POST", "/deploy\\.json", self.deploy)
        self.deploy_outcome = (200, {"verified": True})
        self.server = JsonHTTPServer(
            router, "127.0.0.1", 0, name=f"fake-{name}"
        ).start()
        self.port = self.server.port

    def query(self, req):
        if self.delay_s:
            time.sleep(self.delay_s)
        if req.packed is not None:
            return 200, RawResponse(
                bytes(req.packed),
                content_type=PACKED_QUERY_CONTENT_TYPE,
                headers={"X-Fake-Member": self.name},
            )
        return 200, {
            "member": self.name,
            "echo": req.body,
            "priority": req.header("X-Pio-Priority"),
        }

    def deploy(self, req):
        status, body = self.deploy_outcome
        return status, dict(body, member=self.name)

    def metrics(self, req):
        return 200, metrics_response(self.obs.render())

    def stop(self):
        self.server.stop()


@pytest.fixture()
def two_members():
    members = [_FakeMember("a"), _FakeMember("b")]
    try:
        yield members
    finally:
        for m in members:
            m.stop()


def _targets(members):
    return [
        (m.name, f"http://127.0.0.1:{m.port}") for m in members
    ]


# ---------------------------------------------------------------------------
# router core


class TestServingRouter:
    def test_forward_reaches_a_member_and_counts(self, two_members):
        sr = ServingRouter(_targets(two_members), MetricsRegistry())
        try:
            status, reply, body, member = sr.forward(
                "POST", "/queries.json", json.dumps({"user": "u1"}).encode(),
                {"content-type": "application/json"}, entity_id="u1",
            )
            assert status == 200
            assert json.loads(body)["member"] == member
            assert sr._forwarded.value(member) == 1.0
            # affinity: the same entity lands on the same member
            for _ in range(3):
                assert sr.forward(
                    "POST", "/queries.json", b"{}", {}, entity_id="u1"
                )[3] == member
        finally:
            sr.close()

    def test_dead_member_retries_once_and_leaves_ring(self, two_members):
        sr = ServingRouter(
            _targets(two_members), MetricsRegistry(), forced_down_s=60.0
        )
        try:
            # find an entity whose primary is member "a", then kill "a"
            entity = next(
                k for k in KEYS if sr.ring.rank(k)[0] == "a"
            )
            two_members[0].stop()
            status, _, body, member = sr.forward(
                "POST", "/queries.json", b"{}", {}, entity_id=entity,
            )
            assert status == 200 and member == "b"
            assert sr._retried.value("b") == 1.0
            assert sr._forward_errors.value("a") == 1.0
            # passive health: "a" is out of the ring for every next pick
            assert [m.name for m in sr.pick(entity)] == ["b"]
            snap = sr.snapshot()
            assert snap["ring"]["routable"] == ["b"]
        finally:
            sr.close()

    def test_all_members_dead_sheds_503(self, two_members):
        sr = ServingRouter(
            _targets(two_members), MetricsRegistry(), forced_down_s=60.0
        )
        try:
            for m in two_members:
                m.stop()
            with pytest.raises(Shed) as ei:
                sr.forward("POST", "/queries.json", b"{}", {})
            assert ei.value.status == 503
            with pytest.raises(Shed) as ei:
                sr.pick("u1")
            assert ei.value.reason == "no_members"
        finally:
            sr.close()

    def test_burning_replica_demoted(self, two_members):
        sr = ServingRouter(_targets(two_members), MetricsRegistry())
        try:
            entity = next(k for k in KEYS if sr.ring.rank(k)[0] == "a")
            sr.ingest_fleet({"members": [
                {"member": "a", "status": "up",
                 "slo": {"worstBurn": 9.0}},
                {"member": "b", "status": "up",
                 "slo": {"worstBurn": 0.1}},
            ]})
            # affinity says "a", the burn demotion says "b"
            assert [m.name for m in sr.pick(entity)] == ["b", "a"]
        finally:
            sr.close()

    def test_all_burning_sheds_by_priority_floor(self, two_members):
        sr = ServingRouter(_targets(two_members), MetricsRegistry())
        try:
            sr.ingest_fleet({"members": [
                {"member": "a", "status": "up",
                 "slo": {"worstBurn": 5.0}},
                {"member": "b", "status": "up",
                 "slo": {"worstBurn": 3.0}},
            ]})
            with pytest.raises(Shed) as ei:
                sr.pick("u1", priority="batchpredict")
            assert ei.value.reason == "slo_burn"
            with pytest.raises(Shed):
                sr.pick("u1", priority="shadow")
            # interactive still rides, least-burning first
            assert [m.name for m in sr.pick("u1", "interactive")] == \
                ["b", "a"]
        finally:
            sr.close()

    def test_scrape_down_member_leaves_ring(self, two_members):
        sr = ServingRouter(_targets(two_members), MetricsRegistry())
        try:
            sr.ingest_fleet({"members": [
                {"member": "a", "status": "down"},
                {"member": "b", "status": "up"},
            ]})
            assert [m.name for m in sr.pick("u1")] == ["b"]
            assert sr.obs.gauge(
                "pio_tpu_router_ring_size", ""
            ).value() == 1.0
        finally:
            sr.close()

    def test_headroom_exhausted_member_demoted(self, two_members):
        """Satellite (ISSUE 19): a member whose device budget headroom
        hit zero demotes behind healthy ones before its SLO burns."""
        sr = ServingRouter(_targets(two_members), MetricsRegistry())
        try:
            entity = next(k for k in KEYS if sr.ring.rank(k)[0] == "a")
            sr.ingest_fleet({"members": [
                {"member": "a", "status": "up",
                 "devices": {"headroomBytes": 0}},
                {"member": "b", "status": "up",
                 "devices": {"headroomBytes": 1 << 30}},
            ]})
            # affinity says "a", the exhausted headroom says "b"
            assert [m.name for m in sr.pick(entity)] == ["b", "a"]
            snap = sr.snapshot()
            by = {m["member"]: m for m in snap["members"]}
            assert by["a"]["headroomBytes"] == 0.0
            assert by["b"]["headroomBytes"] == float(1 << 30)
        finally:
            sr.close()

    def test_headroom_and_burn_both_shed_non_interactive(
        self, two_members
    ):
        sr = ServingRouter(_targets(two_members), MetricsRegistry())
        try:
            sr.ingest_fleet({"members": [
                {"member": "a", "status": "up",
                 "devices": {"headroomBytes": 0}},
                {"member": "b", "status": "up",
                 "slo": {"worstBurn": 5.0}},
            ]})
            with pytest.raises(Shed) as ei:
                sr.pick("u1", priority="batchpredict")
            assert ei.value.reason == "slo_burn"
            # interactive still rides the least-pressured replica
            assert len(sr.pick("u1", "interactive")) == 2
        finally:
            sr.close()

    def test_hedge_fires_after_budget_and_wins(self, two_members):
        """Satellite (ISSUE 19): with PIO_TPU_ROUTER_HEDGE_MS armed, an
        interactive request whose primary outlives the budget races the
        next replica; the faster answer wins and is counted."""
        sr = ServingRouter(
            _targets(two_members), MetricsRegistry(), hedge_ms=40.0
        )
        try:
            entity = next(k for k in KEYS if sr.ring.rank(k)[0] == "a")
            two_members[0].delay_s = 0.4
            t0 = monotonic_s()
            status, _, _, member = sr.forward(
                "POST", "/queries.json", b"{}", {}, entity_id=entity,
                priority="interactive",
            )
            elapsed = monotonic_s() - t0
            assert status == 200 and member == "b"
            assert elapsed < 0.35  # did not wait out the slow primary
            assert sr._hedged.value("hedge_won") == 1.0
            assert sr._retried.value("b") == 1.0
        finally:
            sr.close()

    def test_hedge_primary_wins_race(self, two_members):
        sr = ServingRouter(
            _targets(two_members), MetricsRegistry(), hedge_ms=30.0
        )
        try:
            entity = next(k for k in KEYS if sr.ring.rank(k)[0] == "a")
            two_members[0].delay_s = 0.1   # slower than the budget...
            two_members[1].delay_s = 0.4   # ...but faster than the hedge
            status, _, _, member = sr.forward(
                "POST", "/queries.json", b"{}", {}, entity_id=entity,
            )
            assert status == 200 and member == "a"
            assert sr._hedged.value("primary_won") == 1.0
            assert sr._hedged.value("hedge_won") == 0.0
        finally:
            sr.close()

    def test_hedge_skipped_for_non_interactive(self, two_members):
        sr = ServingRouter(
            _targets(two_members), MetricsRegistry(), hedge_ms=30.0
        )
        try:
            entity = next(k for k in KEYS if sr.ring.rank(k)[0] == "a")
            two_members[0].delay_s = 0.1
            status, _, _, member = sr.forward(
                "POST", "/queries.json", b"{}", {}, entity_id=entity,
                priority="batchpredict",
            )
            assert status == 200 and member == "a"
            for outcome in ("primary_won", "hedge_won", "error"):
                assert sr._hedged.value(outcome) == 0.0
        finally:
            sr.close()

    def test_hedge_off_by_default(self, two_members):
        sr = ServingRouter(_targets(two_members), MetricsRegistry())
        try:
            assert sr.hedge_s == 0.0
            assert sr.snapshot()["policy"]["hedgeMs"] == 0.0
        finally:
            sr.close()

    def test_removed_member_pool_sockets_close(self, two_members):
        """Satellite (ISSUE 19): removing a member (or forcing it down)
        closes its keep-alive pool sockets immediately — no FD may keep
        pointing at a corpse."""
        sr = ServingRouter(_targets(two_members), MetricsRegistry())
        try:
            entity = next(k for k in KEYS if sr.ring.rank(k)[0] == "a")
            assert sr.forward(
                "POST", "/queries.json", b"{}", {}, entity_id=entity
            )[3] == "a"
            pool = sr._pools["a"]
            assert pool._idle, "keep-alive should have parked a conn"
            socks = [c.sock for c in pool._idle if c.sock is not None]
            assert socks
            sr.remove_member("a")
            assert pool._idle == []
            assert all(s.fileno() == -1 for s in socks)  # really closed
            assert not sr.has_member("a")
            assert "a" not in sr.ring.members
        finally:
            sr.close()

    def test_forced_down_member_pool_sockets_close(self, two_members):
        sr = ServingRouter(
            _targets(two_members), MetricsRegistry(), forced_down_s=60.0
        )
        try:
            assert sr.forward(
                "POST", "/queries.json", b"{}", {},
                entity_id=next(
                    k for k in KEYS if sr.ring.rank(k)[0] == "b"
                ),
            )[3] == "b"
            pool = sr._pools["b"]
            socks = [c.sock for c in pool._idle if c.sock is not None]
            assert socks
            sr.note_failure("b")
            assert pool._idle == []
            assert all(s.fileno() == -1 for s in socks)
            assert [m.name for m in sr.pick("u1")] == ["a"]
        finally:
            sr.close()

    def test_aux_member_takes_no_ring_traffic(self, two_members):
        sr = ServingRouter(_targets(two_members), MetricsRegistry())
        aux = _FakeMember("aux0")
        try:
            sr.add_member("aux0", f"http://127.0.0.1:{aux.port}",
                          aux=True)
            assert sr.has_member("aux0")
            assert "aux0" not in sr.ring.members
            for k in KEYS[:50]:
                assert "aux0" not in [m.name for m in sr.pick(k)]
            # but it is directly reachable over its pool
            status, _, body = sr.upstream_request(
                "aux0", "POST", "/queries.json", b"{}",
                {"content-type": "application/json"},
            )
            assert status == 200
            assert json.loads(body)["member"] == "aux0"
            snap = sr.snapshot()
            by = {m["member"]: m for m in snap["members"]}
            assert by["aux0"]["aux"] is True
            assert snap["ring"]["size"] == 2
        finally:
            sr.remove_member("aux0")
            aux.stop()
            sr.close()

    def test_forward_headers_allowlist(self):
        out = forward_headers({
            "x-pio-priority": "shadow",
            "x-pio-deadline-ms": "50",
            "content-type": "application/json",
            "connection": "keep-alive",
            "host": "router:8500",
            "content-length": "17",
        })
        assert set(out) == {
            "x-pio-priority", "x-pio-deadline-ms", "content-type"
        }


# ---------------------------------------------------------------------------
# manifest-verified deploys


class _Rec:
    def __init__(self, models):
        self.models = models


class _Store(dict):
    def get(self, k, default=None):  # models-store duck type
        return dict.get(self, k, default)


def _sharded_store(instance_id="inst1"):
    import hashlib

    from pio_tpu.workflow.shard_store import SHARD_MANIFEST_SUFFIX

    shard_a = b"\x01" * 64
    shard_b = b"\x02" * 96
    manifest = {
        "version": 1,
        "n_shards": 2,
        "mesh_shape": [2],
        "algos": [{
            "template": "als",
            "arrays": [{
                "name": "emb", "shape": [4, 40], "dtype": "int8",
                "spec": [["rows"]],
                "shards": [
                    {"id": f"{instance_id}.shard0",
                     "sha256": hashlib.sha256(shard_a).hexdigest(),
                     "size": len(shard_a), "rows": [0, 2]},
                    {"id": f"{instance_id}.shard1",
                     "sha256": hashlib.sha256(shard_b).hexdigest(),
                     "size": len(shard_b), "rows": [2, 4]},
                ],
            }],
        }],
    }
    store = _Store()
    store[instance_id + SHARD_MANIFEST_SUFFIX] = _Rec(
        json.dumps(manifest).encode()
    )
    store[f"{instance_id}.shard0"] = _Rec(shard_a)
    store[f"{instance_id}.shard1"] = _Rec(shard_b)
    return store, manifest


class TestDeployVerify:
    def test_verifies_clean_store(self):
        store, manifest = _sharded_store()
        report = verify_instance(store, "inst1", expected=manifest)
        assert report["sharded"] and report["shards"] == 2
        assert report["bytes"] == 160

    def test_corrupt_shard_rejected(self):
        store, manifest = _sharded_store()
        store["inst1.shard1"] = _Rec(b"\x02" * 95 + b"\xff")
        with pytest.raises(DeployVerifyError, match="checksum"):
            verify_instance(store, "inst1", expected=manifest)

    def test_missing_shard_rejected(self):
        store, manifest = _sharded_store()
        del store["inst1.shard0"]
        with pytest.raises(DeployVerifyError, match="missing shard"):
            verify_instance(store, "inst1")

    def test_manifest_divergence_rejected(self):
        store, manifest = _sharded_store()
        pushed = json.loads(json.dumps(manifest))
        pushed["algos"][0]["arrays"][0]["shards"][0]["sha256"] = "0" * 64
        with pytest.raises(DeployVerifyError, match="disagrees"):
            verify_instance(store, "inst1", expected=pushed)

    def test_unsharded_blob_needs_record(self):
        store = _Store()
        with pytest.raises(DeployVerifyError, match="absent"):
            verify_instance(store, "plain")
        store["plain"] = _Rec(b"blob")
        report = verify_instance(store, "plain")
        assert report == {
            "instanceId": "plain", "sharded": False,
            "shards": 0, "bytes": 4,
        }

    def test_pushed_manifest_but_local_store_empty(self):
        store, manifest = _sharded_store()
        empty = _Store()
        empty["inst1"] = _Rec(b"blob")
        with pytest.raises(DeployVerifyError, match="store has none"):
            verify_instance(empty, "inst1", expected=manifest)

    def test_manifest_digests_walks_all_arrays(self):
        _, manifest = _sharded_store()
        digs = manifest_digests(manifest)
        assert set(digs) == {"inst1.shard0", "inst1.shard1"}


# ---------------------------------------------------------------------------
# routerd HTTP surface


class TestRouterd:
    def _service(self, members, **kw):
        svc = RouterService(
            _targets(members), interval_s=5.0, **kw
        )
        server = JsonHTTPServer(
            svc.router, "127.0.0.1", 0, name="test-routerd"
        ).start()
        return svc, server

    def test_entity_of(self):
        assert entity_of({"user": "u1"}) == "u1"
        assert entity_of({"entityId": 7}) == "7"
        assert entity_of({"items": [1]}) is None
        assert entity_of("not a dict") is None

    def test_readyz_gates_on_first_scrape(self, two_members):
        svc, server = self._service(two_members)
        try:
            base = f"http://127.0.0.1:{server.port}"
            assert http("GET", f"{base}/readyz")[0] == 503
            svc.agg.scrape_once()
            assert http("GET", f"{base}/readyz")[0] == 200
        finally:
            server.stop()
            svc.stop()

    def test_relay_json_and_router_header(self, two_members):
        svc, server = self._service(two_members)
        try:
            base = f"http://127.0.0.1:{server.port}"
            status, body, headers = http(
                "POST", f"{base}/queries.json", {"user": "u1"},
                headers={"X-Pio-Priority": "interactive"},
            )
            assert status == 200
            out = json.loads(body)
            assert out["echo"] == {"user": "u1"}
            assert headers["X-Pio-Router-Member"] == out["member"]
            assert out["priority"] == "interactive"
        finally:
            server.stop()
            svc.stop()

    def test_packed_passthrough_bytes_identical(self, two_members):
        from pio_tpu.server.batchlane import pack_query_i8

        svc, server = self._service(two_members)
        try:
            base = f"http://127.0.0.1:{server.port}"
            frame = pack_query_i8([1, -2, 3, 127])
            req = urllib.request.Request(
                f"{base}/queries.json", data=frame, method="POST"
            )
            req.add_header("Content-Type", PACKED_QUERY_CONTENT_TYPE)
            with urllib.request.urlopen(req, timeout=15) as resp:
                echoed = resp.read()
                member = resp.headers["X-Pio-Router-Member"]
            assert echoed == frame
            assert member in ("a", "b")
        finally:
            server.stop()
            svc.stop()

    def test_router_json_shape(self, two_members):
        svc, server = self._service(two_members)
        try:
            svc.agg.scrape_once()
            svc.core.ingest_fleet(svc.agg.fleet_payload())
            base = f"http://127.0.0.1:{server.port}"
            status, body, _ = http("GET", f"{base}/router.json")
            assert status == 200
            snap = json.loads(body)
            assert snap["ring"]["size"] == 2
            assert snap["scrape"]["passes"] == 1
            assert {m["member"] for m in snap["members"]} == {"a", "b"}
            assert all(m["routable"] for m in snap["members"])
        finally:
            server.stop()
            svc.stop()

    def test_chaos_kill_under_relay(self, two_members):
        """SIGKILL-shaped: stop member 'a' mid-traffic; the router must
        answer every request (one transparent retry), force 'a' out of
        the ring, and keep zero non-inflight 5xx."""
        svc, server = self._service(two_members)
        try:
            base = f"http://127.0.0.1:{server.port}"
            for i in range(4):
                assert http(
                    "POST", f"{base}/queries.json", {"user": f"u{i}"}
                )[0] == 200
            two_members[0].stop()
            # an in-process stop closes the listener but not already-
            # established keep-alives; sever the router's pooled conns
            # like the real SIGKILL would (smoke.sh covers that end)
            svc.core._pools["a"].close()
            statuses = [
                http("POST", f"{base}/queries.json", {"user": k})[0]
                for k in KEYS[:20]
            ]
            assert statuses == [200] * 20
            snap = json.loads(http("GET", f"{base}/router.json")[1])
            assert snap["ring"]["routable"] == ["b"]
            status, body, _ = http("GET", f"{base}/metrics")
            text = body.decode()
            assert 'pio_tpu_router_retried_total{member="b"}' in text
            assert "pio_tpu_router_ring_size 1" in text
        finally:
            server.stop()
            svc.stop()

    def test_deploy_flips_generation_only_when_verified(
        self, two_members, monkeypatch
    ):
        from pio_tpu.storage import Storage

        store, manifest = _sharded_store()
        monkeypatch.setattr(
            Storage, "get_model_data_models", staticmethod(lambda: store)
        )
        two_members[0].deploy_outcome = (200, {"verified": True})
        two_members[1].deploy_outcome = (
            409, {"message": "deploy verification failed: checksum"}
        )
        svc, server = self._service(two_members)
        try:
            base = f"http://127.0.0.1:{server.port}"
            status, body, _ = http(
                "POST", f"{base}/deploy", {"engineInstanceId": "inst1"}
            )
            assert status == 502  # one member failed verification
            report = json.loads(body)
            by_member = {r["member"]: r for r in report["members"]}
            assert by_member["a"]["outcome"] == "verified"
            assert by_member["b"]["outcome"] == "rejected"
            snap = svc.core.snapshot()
            gens = {m["member"]: m["generation"] for m in snap["members"]}
            assert gens == {"a": "inst1", "b": None}
            assert svc.core._deploys.value("a", "verified") == 1.0
            assert svc.core._deploys.value("b", "rejected") == 1.0
        finally:
            server.stop()
            svc.stop()

    def test_deploy_requires_instance_id(self, two_members):
        svc, server = self._service(two_members)
        try:
            base = f"http://127.0.0.1:{server.port}"
            assert http("POST", f"{base}/deploy", {})[0] == 400
        finally:
            server.stop()
            svc.stop()
