"""2-process jax.distributed smoke test (pio_tpu/parallel/distributed.py).

The multi-host story is SPMD: every host runs the same program and
``maybe_initialize`` forms the group from the PIO_TPU_* env contract.
This test actually forms a 2-process group on CPU — subprocess pair,
coordinator handshake, a cross-process psum, and a
``host_local_to_global`` assembly — the closest a single machine gets to
the reference's multi-node paths (which its suite never tests at all;
SURVEY.md §4 "what is NOT tested").

Skip policy (deliberately narrow): skip only when loopback sockets are
unavailable (verified by a preflight connect, the sandboxed-CI case) or
when jax explicitly reports distributed is not available. A timeout or a
connection error on a machine WITH working sockets is a real regression
and fails — a permissive benign-error list would silently convert future
regressions into skips.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import os, sys
sys.path.insert(0, {repo!r})

# maybe_initialize must run BEFORE any backend touch (its documented
# contract) — only stdlib + the wrapper first
from pio_tpu.parallel.distributed import (
    maybe_initialize, is_coordinator, host_local_to_global,
)

joined = maybe_initialize()
assert joined, "PIO_TPU_COORDINATOR was set; group must form"

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

rank = jax.process_index()
assert jax.process_count() == 2, jax.process_count()
assert is_coordinator() == (rank == 0)

# one device per process -> a 2-device global mesh spanning both processes
mesh = jax.sharding.Mesh(np.array(jax.devices()).reshape(2), ("data",))

# each process contributes its own rows; the global array spans both
local = np.full((3, 4), float(rank + 1), np.float32)
g = host_local_to_global(mesh, P("data"), local)
assert g.shape == (6, 4), g.shape

# cross-process collective: psum over the data axis sees BOTH hosts' rows
def body(x):
    return jax.lax.psum(x.sum(), "data")

total = jax.jit(
    jax.shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P())
)(g)
expect = 3 * 4 * 1.0 + 3 * 4 * 2.0  # rank0 ones + rank1 twos
got = float(np.asarray(total))
assert got == expect, (got, expect)
print(f"RANK{{rank}}_OK", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _loopback_works() -> bool:
    """Preflight: can this machine actually connect over loopback?"""
    try:
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        cli = socket.socket()
        cli.settimeout(5)
        cli.connect(srv.getsockname())
        conn, _ = srv.accept()
        conn.close()
        cli.close()
        srv.close()
        return True
    except OSError:
        return False


@pytest.mark.slow
def test_two_process_group_psum(tmp_path):
    if not _loopback_works():
        pytest.skip("loopback sockets unavailable (sandboxed environment)")
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=REPO))

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # one device per process, no simulation
        env["JAX_PLATFORMS"] = "cpu"
        env["PIO_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
        env["PIO_TPU_NUM_PROCESSES"] = "2"
        env["PIO_TPU_PROCESS_ID"] = str(rank)
        env["PYTHONPATH"] = REPO
        procs.append(subprocess.Popen(
            [sys.executable, str(script)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        ))

    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # loopback works (preflight) — a hang here is a real regression
        raise AssertionError(
            "distributed group formation timed out on a machine with "
            "working loopback sockets"
        )

    combined = "\n---\n".join(outs)
    if any(p.returncode != 0 for p in procs):
        # the ONLY benign failure: a jax build without distributed support
        if "distributed is not available" in combined:
            pytest.skip(f"jax distributed unavailable: {combined[-500:]}")
        raise AssertionError(combined[-4000:])
    assert "RANK0_OK" in combined and "RANK1_OK" in combined, combined[-2000:]
