"""Dashboard + Admin API route tests over real HTTP (reference
tools/dashboard + tools/admin; SURVEY.md §2.4)."""

import datetime as dt
import json
import urllib.error
import urllib.request

import pytest

from pio_tpu.data import Event
from pio_tpu.server import create_admin_server, create_dashboard
from pio_tpu.storage import App, RunStatus, Storage
from pio_tpu.storage.records import EvaluationInstance


@pytest.fixture(autouse=True)
def isolated_storage(tmp_home):
    Storage.reset()
    yield
    Storage.reset()


def http(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            if "json" in ctype:
                return resp.status, json.loads(raw or b"null"), resp.headers
            return resp.status, raw.decode(), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null"), e.headers


def _eval_instance(iid, status=RunStatus.COMPLETED, **kw):
    t = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    return EvaluationInstance(
        id=iid,
        status=status,
        start_time=t,
        end_time=t + dt.timedelta(minutes=5),
        evaluation_class="my.Evaluation",
        evaluator_results=kw.get("results", "metric=0.9"),
        evaluator_results_json=kw.get("json_", '{"best": {"score": 0.9}}'),
        evaluator_results_html=kw.get("html", "<html><b>ok</b></html>"),
    )


@pytest.fixture()
def dashboard():
    server = create_dashboard(host="127.0.0.1", port=0).start()
    yield f"http://127.0.0.1:{server.port}"
    server.stop()


@pytest.fixture()
def admin():
    server = create_admin_server(host="127.0.0.1", port=0).start()
    yield f"http://127.0.0.1:{server.port}"
    server.stop()


class TestDashboard:
    def test_index_lists_completed_only(self, dashboard):
        evals = Storage.get_meta_data_evaluation_instances()
        evals.insert(_eval_instance("done-1"))
        evals.insert(_eval_instance("running-1", status=RunStatus.RUNNING))
        status, body, headers = http("GET", dashboard + "/")
        assert status == 200
        assert "done-1" in body and "running-1" not in body
        assert headers["Access-Control-Allow-Origin"] == "*"
        assert "text/html" in headers["Content-Type"]

    def test_instances_json(self, dashboard):
        Storage.get_meta_data_evaluation_instances().insert(
            _eval_instance("done-2")
        )
        status, body, _ = http("GET", dashboard + "/instances.json")
        assert status == 200
        assert [i["id"] for i in body] == ["done-2"]
        assert body[0]["evaluationClass"] == "my.Evaluation"

    def test_instance_detail_json_and_html(self, dashboard):
        Storage.get_meta_data_evaluation_instances().insert(
            _eval_instance("d3")
        )
        status, body, _ = http("GET", dashboard + "/instances/d3.json")
        assert status == 200
        assert body["results"] == {"best": {"score": 0.9}}
        status, page, _ = http("GET", dashboard + "/instances/d3.html")
        assert status == 200 and "<b>ok</b>" in page

    def test_missing_instance_404(self, dashboard):
        status, _, _ = http("GET", dashboard + "/instances/nope.json")
        assert status == 404

    def test_metrics_round_trip(self, dashboard):
        from pio_tpu.obs.promparse import parse_prometheus_text

        http("GET", dashboard + "/")  # one pageview
        status, text, headers = http("GET", dashboard + "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        pm = parse_prometheus_text(text)
        assert pm.types["pio_tpu_dashboard_pageviews_total"] == "counter"
        assert pm.value("pio_tpu_dashboard_pageviews_total", page="index") == 1

    def test_serving_view_unreachable_upstream(self, dashboard):
        """/serving.html degrades gracefully when no query server is up:
        still a 200 HTML page, with the scrape error surfaced."""
        status, page, headers = http(
            "GET", dashboard + "/serving.html?url=http://127.0.0.1:1"
        )
        assert status == 200
        assert "text/html" in headers["Content-Type"]
        assert "Serving" in page

    def test_probes_and_logs(self, dashboard):
        status, report, _ = http("GET", dashboard + "/healthz")
        assert status == 200 and report["status"] == "ok"
        status, report, _ = http("GET", dashboard + "/readyz")
        assert status == 200 and report["status"] == "ready"
        assert report["checks"]["storage"]["ok"]
        status, body, _ = http("GET", dashboard + "/logs.json")
        assert status == 200 and "logs" in body and "ringCapacity" in body
        assert http("GET", dashboard + "/logs.json?n=-1")[0] == 400
        assert http("GET", dashboard + "/logs.json?level=loud")[0] == 400

    def test_serving_view_slo_panel_and_log_tail(self, dashboard, tmp_home):
        """With SLOs declared on the query server, /serving.html renders
        the error-budget table and a structured-log tail."""
        import pio_tpu.templates  # noqa: F401
        from tests.test_servers import _train
        from pio_tpu.server import create_query_server

        app_id = Storage.get_meta_data_apps().insert(App(0, "srv-test"))
        variant, ctx, _ = _train(app_id)
        server, _ = create_query_server(
            variant, host="127.0.0.1", port=0, ctx=ctx,
            slos=["p99=50ms:99.9"],
        )
        server.start()
        try:
            qurl = f"http://127.0.0.1:{server.port}"
            assert http(
                "POST", qurl + "/queries.json", {"user": "u1", "num": 2}
            )[0] == 200
            status, page, _ = http(
                "GET", dashboard + f"/serving.html?url={qurl}"
            )
            assert status == 200
            assert "latency_p99" in page        # SLO table row
            assert "budget left" in page        # budget column header
            assert "Recent logs" in page
            assert "served query" in page       # the request's log line
        finally:
            server.stop()

    def test_serving_view_renders_stage_table(self, dashboard, tmp_home):
        """Point the dashboard at a live query server and check the
        pool-wide totals + per-stage latency table are rendered."""
        import pio_tpu.templates  # noqa: F401
        from tests.test_servers import VARIANT, _train
        from pio_tpu.server import create_query_server

        app_id = Storage.get_meta_data_apps().insert(App(0, "srv-test"))
        variant, ctx, _ = _train(app_id)
        server, _ = create_query_server(
            variant, host="127.0.0.1", port=0, ctx=ctx
        )
        server.start()
        try:
            qurl = f"http://127.0.0.1:{server.port}"
            for _ in range(2):
                http("POST", qurl + "/queries.json", {"user": "u1", "num": 2})
            status, page, _ = http(
                "GET", dashboard + f"/serving.html?url={qurl}"
            )
            assert status == 200
            assert "execute" in page and "serialize" in page
            assert "queue" in page
        finally:
            server.stop()


class TestAdmin:
    def test_alive(self, admin):
        status, body, _ = http("GET", admin + "/")
        assert status == 200 and body["status"] == "alive"

    def test_status_ok(self, admin):
        status, body, _ = http("GET", admin + "/cmd/status")
        assert status == 200 and body["status"] == "ok"

    def test_app_lifecycle(self, admin):
        # create
        status, body, _ = http("POST", admin + "/cmd/app", {"name": "shop"})
        assert status == 201
        assert body["name"] == "shop" and len(body["accessKeys"]) == 1
        # duplicate rejected
        status, _, _ = http("POST", admin + "/cmd/app", {"name": "shop"})
        assert status == 409
        # list
        status, body, _ = http("GET", admin + "/cmd/app")
        assert [a["name"] for a in body["apps"]] == ["shop"]
        assert len(body["apps"][0]["accessKeys"]) == 1
        # seed an event, then data-delete clears it
        app = Storage.get_meta_data_apps().get_by_name("shop")
        Storage.get_levents().insert(
            Event("view", "user", "u1", "item", "i1"), app.id
        )
        status, _, _ = http("DELETE", admin + f"/cmd/app/shop/data")
        assert status == 200
        assert Storage.get_pevents().find(app.id) == []
        # full delete removes the app
        status, _, _ = http("DELETE", admin + "/cmd/app/shop")
        assert status == 200
        assert Storage.get_meta_data_apps().get_by_name("shop") is None

    def test_bad_create_body(self, admin):
        status, _, _ = http("POST", admin + "/cmd/app", {"nom": "x"})
        assert status == 400

    def test_non_numeric_id_is_400(self, admin):
        status, body, _ = http(
            "POST", admin + "/cmd/app", {"name": "x", "id": "abc"}
        )
        assert status == 400 and "integer" in body["message"]

    def test_delete_missing_app_404(self, admin):
        status, _, _ = http("DELETE", admin + "/cmd/app/ghost")
        assert status == 404
