"""Test configuration.

Tests run on CPU with 8 simulated XLA devices so multi-chip sharding paths
are exercised without TPU hardware (the reference's analog: running Spark
suites on ``local[*]`` — SURVEY.md §4). Must run before the first jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def tmp_home(tmp_path, monkeypatch):
    """Isolated PIO home directory for storage/metadata tests."""
    monkeypatch.setenv("PIO_TPU_HOME", str(tmp_path))
    return tmp_path
