"""Test configuration.

Tests run on CPU with 8 simulated XLA devices so multi-chip sharding paths
are exercised without TPU hardware (the reference's analog: running Spark
suites on ``local[*]`` — SURVEY.md §4). Must run before the first jax import.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The session image pins the experimental `axon` TPU platform in a way that
# ignores the JAX_PLATFORMS env var — jax.config.update is the only override
# that sticks (must happen before any backend touch). Set
# PIO_TPU_TEST_PLATFORM to run the suite on real hardware instead.
import jax  # noqa: E402

jax.config.update(
    "jax_platforms", os.environ.get("PIO_TPU_TEST_PLATFORM", "cpu")
)

import pytest  # noqa: E402


@pytest.fixture()
def tmp_home(tmp_path, monkeypatch):
    """Isolated PIO home directory for storage/metadata tests."""
    monkeypatch.setenv("PIO_TPU_HOME", str(tmp_path))
    return tmp_path


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-process integration scenarios (quickstart lifecycle);"
        " runs by default, deselect quick runs with -m 'not slow'",
    )
