"""Event core tests: Event validation, DataMap, BiMap, aggregation fold.

Mirrors the reference's DataMapSpec / BiMapSpec / EventJson4sSupport
round-trip specs and LEventAggregator semantics (SURVEY.md §4).
"""

import datetime as dt

import pytest

from pio_tpu.data import (
    BiMap,
    DataMap,
    Event,
    EventValidationError,
    aggregate_properties,
    fold_properties,
    validate_event,
)
from pio_tpu.data.datamap import DataMapError


def T(h, m=0, s=0):
    return dt.datetime(2026, 1, 1, h, m, s, tzinfo=dt.timezone.utc)


# ---------------------------------------------------------------- DataMap
class TestDataMap:
    def test_typed_get(self):
        d = DataMap({"a": 1, "b": "x", "c": 2.5, "d": [1, 2], "e": {"k": 1}, "f": True})
        assert d.get("a", int) == 1
        assert d.get_string("b") == "x"
        assert d.get_double("c") == 2.5
        assert d.get_double("a") == 1.0  # int coerces to float
        assert d.get("d", list) == [1, 2]
        assert d.get("f", bool) is True

    def test_missing_and_null(self):
        d = DataMap({"a": None})
        with pytest.raises(DataMapError):
            d.get("zzz")
        with pytest.raises(DataMapError):
            d.get("a")
        assert d.get_opt("a") is None
        assert d.get_opt("zzz") is None
        assert d.get_or_else("zzz", 7) == 7

    def test_type_mismatch(self):
        d = DataMap({"a": "str"})
        with pytest.raises(DataMapError):
            d.get("a", int)

    def test_union_minus(self):
        d = DataMap({"a": 1, "b": 2})
        assert d.union({"b": 3, "c": 4}).to_dict() == {"a": 1, "b": 3, "c": 4}
        assert d.minus(["b"]).to_dict() == {"a": 1}

    def test_json_roundtrip(self):
        d = DataMap({"a": 1, "b": [1, "x"], "c": {"n": None}})
        assert DataMap.from_json(d.to_json()) == d

    def test_string_list(self):
        assert DataMap({"a": ["x", "y"]}).get_string_list("a") == ["x", "y"]
        with pytest.raises(DataMapError):
            DataMap({"a": ["x", 1]}).get_string_list("a")


# ---------------------------------------------------------------- BiMap
class TestBiMap:
    def test_bidirectional(self):
        m = BiMap({"a": 1, "b": 2})
        assert m["a"] == 1
        assert m.inverse[2] == "b"
        assert m.inverse.inverse["a"] == 1

    def test_duplicate_values_rejected(self):
        with pytest.raises(ValueError):
            BiMap({"a": 1, "b": 1})

    def test_string_int_deterministic(self):
        m = BiMap.string_int(["u3", "u1", "u2", "u1"])
        assert m.to_dict() == {"u1": 0, "u2": 1, "u3": 2}
        assert BiMap.string_long(["u3", "u1", "u2"]) == m

    def test_string_int_by_frequency(self):
        """Popularity ordering: descending count, lexicographic ties —
        deterministic, bijective, same key set as string_int."""
        keys = ["i2", "i9", "i2", "i2", "i9", "i5"]
        m = BiMap.string_int_by_frequency(keys)
        assert m.to_dict() == {"i2": 0, "i9": 1, "i5": 2}
        # tie-break is lexicographic, not insertion order
        t = BiMap.string_int_by_frequency(["b", "a"])
        assert t.to_dict() == {"a": 0, "b": 1}
        assert set(m.to_dict()) == set(BiMap.string_int(keys).to_dict())

    def test_get_and_contains(self):
        m = BiMap.string_int(["x"])
        assert "x" in m and m.get("y") is None and len(m) == 1


# ---------------------------------------------------------------- Validation
class TestEventValidation:
    def test_basic_ok(self):
        validate_event(Event("rate", "user", "u1", "item", "i1"))

    def test_empty_fields(self):
        for kwargs in (
            dict(event="", entity_type="user", entity_id="u1"),
            dict(event="rate", entity_type="", entity_id="u1"),
            dict(event="rate", entity_type="user", entity_id=""),
        ):
            with pytest.raises(EventValidationError):
                validate_event(Event(**kwargs))

    def test_target_entity_pairing(self):
        with pytest.raises(EventValidationError):
            validate_event(Event("rate", "user", "u1", target_entity_type="item"))
        with pytest.raises(EventValidationError):
            validate_event(Event("rate", "user", "u1", target_entity_id="i1"))

    def test_dollar_names_reserved(self):
        with pytest.raises(EventValidationError):
            validate_event(Event("$foo", "user", "u1"))
        validate_event(Event("$set", "user", "u1", properties={"a": 1}))

    def test_special_event_rules(self):
        with pytest.raises(EventValidationError):  # $set with target entity
            validate_event(Event("$set", "user", "u1", "item", "i1"))
        with pytest.raises(EventValidationError):  # $unset empty properties
            validate_event(Event("$unset", "user", "u1"))
        with pytest.raises(EventValidationError):  # $delete with properties
            validate_event(Event("$delete", "user", "u1", properties={"a": 1}))
        validate_event(Event("$delete", "user", "u1"))

    def test_reserved_prefixes(self):
        with pytest.raises(EventValidationError):
            validate_event(Event("rate", "pio_user", "u1"))
        with pytest.raises(EventValidationError):
            validate_event(Event("rate", "user", "u1", properties={"pio_x": 1}))
        with pytest.raises(EventValidationError):
            validate_event(Event("rate", "user", "u1", properties={"$x": 1}))
        # builtin entity type allowed
        validate_event(Event("predict", "pio_pr", "p1"))

    def test_api_roundtrip(self):
        e = Event(
            "buy", "user", "u1", "item", "i42",
            properties={"price": 9.99},
            event_time=T(12), tags=("t1",), pr_id="pr9",
            event_id="abc",
        )
        d = e.to_api_dict()
        e2 = Event.from_api_dict(d)
        assert e2.event == "buy" and e2.entity_id == "u1"
        assert e2.target_entity_id == "i42"
        assert e2.properties.get_double("price") == 9.99
        assert e2.event_time == T(12)
        assert e2.tags == ("t1",) and e2.pr_id == "pr9" and e2.event_id == "abc"

    def test_api_parse_errors(self):
        with pytest.raises(EventValidationError):
            Event.from_api_dict({"event": "x"})
        with pytest.raises(EventValidationError):
            Event.from_api_dict(
                {"event": "x", "entityType": "u", "entityId": "1", "eventTime": "nope"}
            )

    def test_naive_datetime_becomes_utc(self):
        e = Event("rate", "user", "u1", event_time=dt.datetime(2026, 1, 1))
        assert e.event_time.tzinfo is dt.timezone.utc


# ---------------------------------------------------------------- Aggregation
def ev(name, t, props=None, eid="u1"):
    return Event(name, "user", eid, properties=props or {}, event_time=t)


class TestAggregation:
    def test_set_last_write_wins(self):
        pm = fold_properties(
            [
                ev("$set", T(1), {"a": 1, "b": 1}),
                ev("$set", T(3), {"a": 3}),
                ev("$set", T(2), {"a": 2, "c": 2}),
            ]
        )
        assert pm.to_dict() == {"a": 3, "b": 1, "c": 2}
        assert pm.first_updated == T(1)
        assert pm.last_updated == T(3)

    def test_unset_removes_keys(self):
        pm = fold_properties(
            [
                ev("$set", T(1), {"a": 1, "b": 1}),
                ev("$unset", T(2), {"a": None}),
            ]
        )
        assert pm.to_dict() == {"b": 1}
        assert pm.last_updated == T(2)

    def test_delete_clears_and_restarts_watermark(self):
        pm = fold_properties(
            [
                ev("$set", T(1), {"a": 1}),
                ev("$delete", T(2)),
                ev("$set", T(3), {"b": 2}),
            ]
        )
        assert pm.to_dict() == {"b": 2}
        assert pm.first_updated == T(3)

    def test_final_delete_yields_none(self):
        assert fold_properties([ev("$set", T(1), {"a": 1}), ev("$delete", T(2))]) is None
        assert fold_properties([ev("$unset", T(1), {"a": None})]) is None

    def test_aggregate_groups_entities(self):
        out = aggregate_properties(
            [
                ev("$set", T(1), {"a": 1}, eid="u1"),
                ev("$set", T(1), {"a": 2}, eid="u2"),
                ev("$delete", T(2), eid="u2"),
                ev("rate", T(3), {"r": 5}, eid="u1"),  # non-special ignored
            ]
        )
        assert set(out) == {("user", "u1")}
        assert out[("user", "u1")].to_dict() == {"a": 1}
