"""Native event-log backend specifics (beyond the shared conformance suite).

The C++ engine (pio_tpu/native/event_log.cpp) is exercised through its
ctypes wrapper; the conformance fixtures in tests/test_storage.py already
run the full LEvents/PEvents spec over it.
"""

import datetime as dt
import os

import numpy as np
import pytest

from pio_tpu.data.event import Event

try:
    # the build happens lazily on first library load, not at module import,
    # so force it here to turn "no toolchain" into a module-level skip
    from pio_tpu.native import event_log_lib

    event_log_lib()
    from pio_tpu.storage.eventlog import EventLogEvents
except Exception as e:  # pragma: no cover - no toolchain
    pytest.skip(f"native eventlog unavailable: {e}", allow_module_level=True)


def T(h):
    return dt.datetime(2026, 1, 1, h, tzinfo=dt.timezone.utc)


@pytest.fixture()
def backend(tmp_path):
    return EventLogEvents(str(tmp_path / "log"))


class TestPersistence:
    def test_reopen_sees_data(self, tmp_path):
        root = str(tmp_path / "log")
        b1 = EventLogEvents(root)
        eid = b1.insert(
            Event(event="rate", entity_type="user", entity_id="u1",
                  properties={"rating": 3.0}, event_time=T(1)),
            app_id=7,
        )
        b2 = EventLogEvents(root)  # fresh handle, same files
        got = b2.get(eid, 7)
        assert got is not None
        assert got.properties.get_double("rating") == 3.0

    def test_tombstone_survives_reopen(self, tmp_path):
        root = str(tmp_path / "log")
        b1 = EventLogEvents(root)
        eid = b1.insert(
            Event(event="rate", entity_type="user", entity_id="u1",
                  event_time=T(1)),
            app_id=1,
        )
        assert b1.delete(eid, 1)
        b2 = EventLogEvents(root)
        assert b2.get(eid, 1) is None
        assert b2.count(1) == 0

    def test_channels_are_separate_files(self, backend, tmp_path):
        backend.insert(
            Event(event="a", entity_type="u", entity_id="1",
                  event_time=T(1)), 1
        )
        backend.insert(
            Event(event="b", entity_type="u", entity_id="1",
                  event_time=T(1)), 1, channel_id=4
        )
        files = sorted(os.listdir(backend.root))
        assert files == ["app_1.pel", "app_1_ch4.pel"]
        assert [e.event for e in backend.find(1)] == ["a"]
        assert [e.event for e in backend.find(1, channel_id=4)] == ["b"]


class TestLastWriteWins:
    """Upsert/delete semantics must match the SQLite and memory backends."""

    def test_reinsert_after_delete_resurrects(self, backend):
        e = Event(event="rate", entity_type="user", entity_id="u1",
                  event_time=T(1), event_id="X")
        backend.insert(e, 1)
        assert backend.delete("X", 1)
        backend.insert(e, 1)
        assert backend.get("X", 1) is not None
        assert backend.count(1) == 1

    def test_insert_same_id_replaces(self, backend):
        backend.insert(
            Event(event="rate", entity_type="user", entity_id="u1",
                  properties={"rating": 3.0}, event_time=T(1),
                  event_id="X"),
            1,
        )
        backend.insert(
            Event(event="rate", entity_type="user", entity_id="u1",
                  properties={"rating": 5.0}, event_time=T(2),
                  event_id="X"),
            1,
        )
        assert backend.count(1) == 1
        evs = backend.find(1)
        assert len(evs) == 1
        assert evs[0].properties.get_double("rating") == 5.0
        assert backend.get("X", 1).properties.get_double("rating") == 5.0

    def test_delete_bulk_batches(self, backend):
        ids = [
            backend.insert(
                Event(event="rate", entity_type="user", entity_id=f"u{i}",
                      event_time=T(1)),
                1,
            )
            for i in range(10)
        ]
        backend.delete_bulk(ids[:7] + ["missing-id"], 1)
        assert backend.count(1) == 3
        assert {e.event_id for e in backend.find(1)} == set(ids[7:])


class TestCompaction:
    def test_compact_drops_dead_records(self, backend):
        for i in range(20):
            backend.insert(
                Event(event="rate", entity_type="user", entity_id=f"u{i}",
                      properties={"rating": float(i % 5)},
                      event_time=T(1), event_id=f"E{i}"),
                2,
            )
        # shadow half by upsert, delete a quarter
        for i in range(0, 20, 2):
            backend.insert(
                Event(event="rate", entity_type="user", entity_id=f"u{i}",
                      properties={"rating": 9.0},
                      event_time=T(2), event_id=f"E{i}"),
                2,
            )
        for i in range(0, 20, 4):
            backend.delete(f"E{i}", 2)
        before = os.path.getsize(backend._path(2))
        pre = {e.event_id: e.properties.to_dict() for e in backend.find(2)}
        reclaimed = backend.compact(2)
        assert reclaimed > 0
        assert os.path.getsize(backend._path(2)) == before - reclaimed
        post = {e.event_id: e.properties.to_dict() for e in backend.find(2)}
        assert post == pre  # observable state unchanged
        assert backend.count(2) == len(pre)
        # idempotent: second pass reclaims nothing
        assert backend.compact(2) == 0
        # log still appendable after the rewrite
        backend.insert(
            Event(event="rate", entity_type="user", entity_id="u99",
                  event_time=T(3)),
            2,
        )
        assert backend.count(2) == len(pre) + 1

    def test_compact_missing_file_is_noop(self, backend):
        assert backend.compact(42) == 0


class TestRobustness:
    def test_unreadable_file_is_an_error_not_empty(self, backend):
        import stat

        from pio_tpu.storage.base import StorageError

        eid = backend.insert(
            Event(event="a", entity_type="u", entity_id="1",
                  event_time=T(1)),
            3,
        )
        path = backend._path(3)
        os.chmod(path, 0)
        if os.access(path, os.R_OK):  # running as root: chmod is a no-op
            os.chmod(path, stat.S_IRUSR | stat.S_IWUSR)
            pytest.skip("cannot make file unreadable under this uid")
        try:
            with pytest.raises(StorageError):
                backend.find(3)
            with pytest.raises(StorageError):
                backend.count(3)
        finally:
            os.chmod(path, stat.S_IRUSR | stat.S_IWUSR)
        assert backend.get(eid, 3) is not None

    def test_corrupt_file_raises_storage_error(self, backend):
        import struct

        from pio_tpu.storage.base import StorageError

        # a fully-present record whose internal string lengths disagree
        # with its framed length — real corruption, not a torn tail
        with open(backend._path(9), "wb") as f:
            f.write(
                b"PEL1\0\0\0\0" + struct.pack("<I", 37) + b"\xff" * 37
            )
        with pytest.raises(StorageError, match="corrupt"):
            backend.find(9)

    def test_bad_magic_raises_storage_error(self, backend):
        from pio_tpu.storage.base import StorageError

        with open(backend._path(8), "wb") as f:
            f.write(b"NOTMAGIC" + b"\x00" * 32)
        with pytest.raises(StorageError, match="corrupt"):
            backend.find(8)

    def test_torn_tail_is_tolerated_and_repaired(self, backend):
        """A crash mid-append must not brick the log (torn-tail recovery)."""
        eids = [
            backend.insert(
                Event(event="rate", entity_type="user", entity_id=f"u{i}",
                      event_time=T(i + 1)),
                5,
            )
            for i in range(3)
        ]
        path = backend._path(5)
        clean_size = os.path.getsize(path)
        with open(path, "ab") as f:  # simulate a partial final record
            f.write(b"\x80\x00\x00\x00" + b"partial-payload")
        # committed records stay readable through the torn tail
        assert backend.count(5) == 3
        assert [e.event_id for e in backend.find(5)] == eids
        # next append repairs (truncates) the tail, then lands cleanly
        backend._repaired.discard(path)
        eid4 = backend.insert(
            Event(event="rate", entity_type="user", entity_id="u9",
                  event_time=T(9)),
            5,
        )
        assert backend.count(5) == 4
        assert backend.get(eid4, 5) is not None
        assert os.path.getsize(path) > clean_size

    def test_unicode_and_empty_fields(self, backend):
        eid = backend.insert(
            Event(event="$set", entity_type="usér", entity_id="ü–1",
                  properties={"名前": "値", "n": 1},
                  event_time=T(1)),
            1,
        )
        got = backend.get(eid, 1)
        assert got.entity_type == "usér"
        assert got.properties.to_dict()["名前"] == "値"
        assert got.target_entity_id is None

    def test_large_batch_scan(self, backend):
        evs = [
            Event(event="rate", entity_type="user", entity_id=f"u{i % 50}",
                  target_entity_type="item", target_entity_id=f"i{i % 20}",
                  properties={"rating": float(i % 5)},
                  event_time=T(1) + dt.timedelta(seconds=i))
            for i in range(5000)
        ]
        backend.write(evs, 1)
        assert backend.count(1) == 5000
        frame = backend.find_frame(1, event_names=["rate"],
                                   entity_type="user")
        assert len(frame.event) == 5000
        # time-ordered ascending
        assert (np.diff(frame.event_time_us) >= 0).all()
        sub = backend.find(1, entity_id="u7")
        assert len(sub) == 100


class TestConcurrency:
    def test_parallel_writers_readers_compactors(self, backend):
        """Threads hammering insert/find/count/compact on one app must
        never see a torn read ("corrupt event log") or lose a write —
        the per-file lock contract."""
        import threading

        errors = []
        written = [0] * 4

        def writer(t):
            try:
                for k in range(120):
                    backend.insert(
                        Event(event="rate", entity_type="user",
                              entity_id=f"w{t}_{k}",
                              properties={"rating": float(k % 5)},
                              event_time=T(1)),
                        7,
                    )
                    written[t] += 1
            except Exception as e:  # pragma: no cover
                errors.append(("writer", t, repr(e)))

        def reader():
            try:
                for _ in range(60):
                    backend.find(7, event_names=["rate"], limit=50)
                    backend.count(7)
            except Exception as e:  # pragma: no cover
                errors.append(("reader", repr(e)))

        def compactor():
            try:
                for _ in range(10):
                    backend.compact(7)
            except Exception as e:  # pragma: no cover
                errors.append(("compactor", repr(e)))

        threads = (
            [threading.Thread(target=writer, args=(t,)) for t in range(4)]
            + [threading.Thread(target=reader) for _ in range(2)]
            + [threading.Thread(target=compactor)]
        )
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert not errors, errors
        assert backend.count(7) == sum(written) == 480


class TestRegistryWiring:
    def test_eventlog_type_serves_both_spis(self, tmp_path, monkeypatch):
        from pio_tpu.storage import Storage

        monkeypatch.setenv("PIO_TPU_HOME", str(tmp_path))
        monkeypatch.setenv(
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "NLOG"
        )
        monkeypatch.setenv("PIO_STORAGE_SOURCES_NLOG_TYPE", "eventlog")
        monkeypatch.setenv(
            "PIO_STORAGE_SOURCES_NLOG_PATH", str(tmp_path / "nlog")
        )
        Storage.reset()
        try:
            le = Storage.get_levents()
            pe = Storage.get_pevents()
            eid = le.insert(
                Event(event="buy", entity_type="user", entity_id="u1",
                      target_entity_type="item", target_entity_id="i1",
                      event_time=T(1)),
                1,
            )
            assert le.get(eid, 1) is not None
            frame = pe.find_frame(1, event_names=["buy"])
            assert list(frame.target_entity_id) == ["i1"]
        finally:
            Storage.reset()
