"""Latency attribution (docs/observability.md "Latency attribution"):
cross-process trace propagation via X-Pio-Trace, hot-path budget math,
histogram exemplars round-tripped through promparse, slow-trace capture,
the group-commit trace join, and profiler re-arming.

Unit tiers run against bare Tracer/GroupCommitter instances; the HTTP
tier uses the (cheap, training-free) event server. The query-server and
pool-mode propagation paths are covered in test_servers.py and
test_worker_pool.py, which already pay for model training."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from pio_tpu.obs import MetricsRegistry, Tracer, monotonic_s
from pio_tpu.obs.hotpath import hotpath_payload
from pio_tpu.obs.profile import DeviceProfileHook
from pio_tpu.obs.promparse import parse_prometheus_text
from pio_tpu.obs.tracing import (
    TRACE_HEADER,
    add_active_span,
    format_trace_header,
    parse_trace_header,
)
from pio_tpu.storage import AccessKey, App, Storage
from pio_tpu.storage.groupcommit import COMMIT_TRACER, GroupCommitter


class TestTraceHeader:
    def test_round_trip(self):
        assert format_trace_header("query-7") == "query-7"
        assert parse_trace_header("query-7") == ("query-7", None)

    def test_parent_span_round_trip(self):
        v = format_trace_header("query-7", "execute")
        assert v == "query-7/execute"
        assert parse_trace_header(v) == ("query-7", "execute")

    @pytest.mark.parametrize("raw", [
        None, "", "   ", "has space", "-leads-with-punct", "a" * 200,
        'inject="label"',
    ])
    def test_malformed_is_fresh_trace_not_400(self, raw):
        assert parse_trace_header(raw) == (None, None)

    def test_bad_parent_dropped_id_kept(self):
        assert parse_trace_header("ok-1/bad parent") == ("ok-1", None)
        assert parse_trace_header("ok-1/") == ("ok-1", None)


class TestTracerPropagation:
    def test_adopts_inherited_id_and_parent(self):
        tracer = Tracer("query")
        with tracer.trace("query", trace_id="up-1", parent="dispatch") as tr:
            assert tr.trace_id == "up-1"
        d = tracer.find("up-1")
        assert d is not None and d["parent"] == "dispatch"

    def test_worker_namespaced_minted_ids(self):
        tracer = Tracer("query")
        tracer.set_worker(3)
        with tracer.trace("query") as tr:
            assert tr.trace_id.startswith("query-w3-")
        assert tracer.recent(1)[0]["worker"] == 3

    def test_rebase_extends_waterfall_backward(self):
        tracer = Tracer("query")
        with tracer.trace("query") as tr:
            tr.add_span("parse", 0.001, rel_start_s=0.0)
            tr.rebase(0.5)  # 500 ms of accept/admit before the trace
            tr.add_span("accept", 0.5, rel_start_s=0.0)
        d = tracer.recent(1)[0]
        spans = {s["stage"]: s for s in d["spans"]}
        assert spans["accept"]["startMs"] == 0.0
        assert spans["parse"]["startMs"] == pytest.approx(500, abs=5)
        assert d["totalMs"] >= 500

    def test_extend_total_restamps_after_close(self):
        tracer = Tracer("query")
        with tracer.trace("query") as tr:
            pass
        closed_ms = tracer.recent(1)[0]["totalMs"]
        time.sleep(0.01)
        tr.add_span("write", 0.01)  # the post-flush response write
        tr.extend_total()
        assert tracer.recent(1)[0]["totalMs"] > closed_ms

    def test_add_active_span_reaches_open_trace(self):
        tracer = Tracer("query")
        add_active_span("execute.device", 1.0)  # no active trace: no-op
        with tracer.trace("query"):
            add_active_span("execute.device", 0.002)
        spans = [s["stage"] for s in tracer.recent(1)[0]["spans"]]
        assert spans == ["execute.device"]

    def test_links_and_meta(self):
        tracer = Tracer("query")
        with tracer.trace("microbatch", links=["m-1", "m-2"], batch=2) as tr:
            tr.link("m-3")
        d = tracer.recent(1)[0]
        assert d["links"] == ["m-1", "m-2", "m-3"]
        assert d["meta"]["batch"] == 2


class TestSlowRing:
    def test_breaches_are_captured_and_findable(self):
        tracer = Tracer("query")
        tracer.slow_threshold_fn = lambda: 0.0  # everything breaches
        with tracer.trace("query", trace_id="slow-1"):
            pass
        got = tracer.slow(5)
        assert [t["id"] for t in got] == ["slow-1"]
        assert got[0]["slow"] is True
        assert tracer.find("slow-1")["id"] == "slow-1"

    def test_no_threshold_no_capture(self):
        tracer = Tracer("query")
        with tracer.trace("query"):
            pass
        assert tracer.slow(5) == []

    def test_extend_total_rechecks_threshold(self):
        # fast at close, slow once the response write is accounted
        tracer = Tracer("query")
        tracer.slow_threshold_fn = lambda: 10.0
        with tracer.trace("query") as tr:
            pass
        assert tracer.slow(5) == []
        tracer.slow_threshold_fn = lambda: 0.0
        tr.extend_total()
        assert len(tracer.slow(5)) == 1

    def test_ring_bounded(self):
        tracer = Tracer("query", slow_ring=4)
        tracer.slow_threshold_fn = lambda: 0.0
        for _ in range(9):
            with tracer.trace("query"):
                pass
        assert len(tracer.slow(100)) == 4


class TestExemplars:
    def test_exposition_and_promparse_round_trip(self):
        reg = MetricsRegistry()
        h = reg.histogram(
            "pio_tpu_ex_seconds", "test", ("stage",),
            buckets=(0.005, 0.05),
        )
        h.labels("parse").observe(0.004, exemplar="query-42")
        h.labels("parse").observe(0.004)  # exemplar-less keeps the last id
        text = "\n".join(reg.render())
        assert '# {trace_id="query-42"} 0.004' in text
        parsed = parse_prometheus_text(text)
        got = parsed.exemplar(
            "pio_tpu_ex_seconds_bucket", stage="parse", le="0.005"
        )
        assert got == ({"trace_id": "query-42"}, 0.004)
        # the sample value itself still parses normally
        assert parsed.value(
            "pio_tpu_ex_seconds_bucket", stage="parse", le="0.005"
        ) == 2

    def test_no_exemplar_no_suffix(self):
        reg = MetricsRegistry()
        h = reg.histogram(
            "pio_tpu_noex_seconds", "test", buckets=(0.005, 0.05)
        )
        h.observe(0.004)
        text = "\n".join(reg.render())
        assert "trace_id" not in text
        assert parse_prometheus_text(text).exemplar(
            "pio_tpu_noex_seconds_bucket", le="0.005"
        ) is None

    def test_hostile_exemplar_id_escaped(self):
        reg = MetricsRegistry()
        h = reg.histogram(
            "pio_tpu_esc_seconds", "test", buckets=(0.005,)
        )
        h.observe(0.001, exemplar='a"b\\c')
        got = parse_prometheus_text("\n".join(reg.render())).exemplar(
            "pio_tpu_esc_seconds_bucket", le="0.005"
        )
        assert got[0] == {"trace_id": 'a"b\\c'}


class TestHotpathPayload:
    def _observed_path(self, n=10):
        reg = MetricsRegistry()
        tracer = Tracer("query", registry=reg,
                        stages=("parse", "execute", "execute.device"))
        e2e = reg.histogram(
            "pio_tpu_e2e_seconds", "test", ("engine_id",)
        ).labels("e")
        for _ in range(n):
            with tracer.trace("query") as tr:
                tr.add_span("parse", 0.002, rel_start_s=0.0)
                tr.add_span("execute", 0.008, rel_start_s=0.002)
                tr.add_span("execute.device", 0.006, rel_start_s=0.003)
            e2e.observe(0.010)
        return tracer, e2e

    def test_budget_attributes_stage_sums(self):
        tracer, e2e = self._observed_path()
        p = hotpath_payload(tracer, e2e, stage_order=("parse", "execute"),
                            pool=False)
        assert p["requestCount"] == 10
        assert p["e2e"]["avgMs"] == pytest.approx(10.0)
        by = {s["stage"]: s for s in p["stages"]}
        assert list(by) == ["parse", "execute"]  # declared order kept
        assert by["parse"]["avgMs"] == pytest.approx(2.0)
        assert by["execute"]["avgMs"] == pytest.approx(8.0)
        assert p["attributedMsPerRequest"] == pytest.approx(10.0)
        assert p["attributedFraction"] == pytest.approx(1.0, abs=0.01)
        assert p["residualMsPerRequest"] == pytest.approx(0.0, abs=0.1)

    def test_substages_reported_but_excluded_from_sum(self):
        tracer, e2e = self._observed_path()
        p = hotpath_payload(tracer, e2e, pool=False)
        subs = {s["stage"] for s in p["substages"]}
        assert subs == {"execute.device"}
        # counting execute.device would push attribution to 1.6
        assert p["attributedFraction"] == pytest.approx(1.0, abs=0.01)

    def test_partial_stage_amortized_over_all_requests(self):
        # a stage that ran for 5 of 10 requests costs half per request
        reg = MetricsRegistry()
        tracer = Tracer("query", registry=reg, stages=("queue",))
        e2e = reg.histogram("pio_tpu_e2e_seconds", "test")._default_cell()
        for i in range(10):
            with tracer.trace("query") as tr:
                if i % 2 == 0:
                    tr.add_span("queue", 0.004, rel_start_s=0.0)
            e2e.observe(0.010)
        p = hotpath_payload(tracer, e2e, pool=False)
        by = {s["stage"]: s for s in p["stages"]}
        assert by["queue"]["count"] == 5
        assert by["queue"]["avgMs"] == pytest.approx(2.0)

    def test_empty_path_and_threshold_passthrough(self):
        reg = MetricsRegistry()
        tracer = Tracer("query", registry=reg, stages=("parse",))
        e2e = reg.histogram("pio_tpu_e2e_seconds", "test")._default_cell()
        p = hotpath_payload(tracer, e2e, pool=False, slow_threshold_s=0.25)
        assert p["requestCount"] == 0
        assert p["e2e"]["avgMs"] is None
        assert p["slowThresholdMs"] == 250.0
        assert "attributedFraction" not in p


class TestGroupCommitTraceJoin:
    def test_submitter_and_leader_waterfalls_join(self):
        tracer = Tracer("event")
        gc = GroupCommitter(lambda batch: list(range(len(batch))),
                            store="attr-test")
        with tracer.trace("event", trace_id="evt-join-1"):
            assert gc.submit({"n": 1}) == 0
        d = tracer.find("evt-join-1")
        stages = [s["stage"] for s in d["spans"]]
        assert "store.flush" in stages
        commit_id = d["meta"]["commit"]
        cd = COMMIT_TRACER.find(commit_id)
        assert cd is not None
        assert "evt-join-1" in cd["links"]
        assert [s["stage"] for s in cd["spans"]] == ["store.flush"]
        assert cd["meta"]["store"] == "attr-test"

    def test_commit_wait_attributed_behind_leader(self):
        entered, release = threading.Event(), threading.Event()

        def flush(batch):
            if not entered.is_set():
                entered.set()
                release.wait(5)
            return [None] * len(batch)

        gc = GroupCommitter(flush, store="attr-wait")
        tracer = Tracer("event")
        leader = threading.Thread(target=gc.submit, args=("a",))
        leader.start()
        assert entered.wait(5)

        def follower():
            with tracer.trace("event", trace_id="evt-follow-1"):
                gc.submit("b")

        f = threading.Thread(target=follower)
        f.start()
        time.sleep(0.15)  # let the follower queue behind the held lock
        release.set()
        leader.join(5)
        f.join(5)
        d = tracer.find("evt-follow-1")
        spans = {s["stage"]: s for s in d["spans"]}
        assert "store.commit_wait" in spans
        assert spans["store.commit_wait"]["durMs"] >= 100
        assert "store.flush" in spans


class TestDeviceProfileRestart:
    def test_restart_unconfigured_refuses(self):
        out = DeviceProfileHook("").restart()
        assert out["restarted"] is False

    def test_restart_rotates_and_rearms(self, tmp_path):
        hook = DeviceProfileHook(str(tmp_path / "prof"), first_n=2)
        hook._seen, hook._done = 2, True  # first window spent
        assert not hook.enabled
        out = hook.restart()
        assert out["restarted"] and out["armed"]
        assert out["captures"] == 1
        assert hook.directory.endswith("capture-0001")
        assert hook._seen == 0 and hook.enabled
        out2 = hook.restart(first_n=5)
        assert out2["firstN"] == 5
        # rotation replaces the capture suffix instead of nesting it
        assert hook.directory.endswith("capture-0002")
        assert "capture-0001" not in hook.directory


# ---------------------------------------------------------------------------
# HTTP tier: the event server end to end (memory storage, no training)

@pytest.fixture()
def mem_storage(tmp_home, monkeypatch):
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "MEM")
    Storage.reset()
    yield
    Storage.reset()


@pytest.fixture()
def eventserver(mem_storage):
    from pio_tpu.server import create_event_server

    server = create_event_server(host="127.0.0.1", port=0).start()
    yield f"http://127.0.0.1:{server.port}"
    server.stop()


@pytest.fixture()
def access_key(mem_storage):
    app_id = Storage.get_meta_data_apps().insert(App(0, "attr-test"))
    return Storage.get_meta_data_access_keys().insert(AccessKey("", app_id))


EV = {
    "event": "rate",
    "entityType": "user",
    "entityId": "u1",
    "targetEntityType": "item",
    "targetEntityId": "i1",
    "properties": {"rating": 4.5},
    "eventTime": "2026-03-01T10:00:00Z",
}


def _http(method, url, body=None, headers=None):
    """(status, json_body, response_headers_lowercased)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return (resp.status, json.loads(resp.read() or b"null"),
                    {k.lower(): v for k, v in resp.getheaders()})
    except urllib.error.HTTPError as e:
        return (e.code, json.loads(e.read() or b"null"),
                {k.lower(): v for k, v in e.headers.items()})


def _find_trace(base_url, trace_id, want_stage=None, tries=100):
    """Poll /traces.json?id= until the trace (and optionally one stage
    recorded by the post-flush write hook) is visible."""
    for _ in range(tries):
        status, body, _ = _http("GET", f"{base_url}/traces.json?id={trace_id}")
        if status == 200:
            t = body["traces"][0]
            stages = {s["stage"] for s in t["spans"]}
            if want_stage is None or want_stage in stages:
                return t
        time.sleep(0.01)
    raise AssertionError(f"trace {trace_id} (stage {want_stage}) "
                         f"never became visible")


class TestEventServerLatencyAttribution:
    def test_inbound_header_adopted_and_echoed(self, eventserver, access_key):
        status, body, hdrs = _http(
            "POST", f"{eventserver}/events.json?accessKey={access_key}",
            EV, {TRACE_HEADER: "up-evt-7/dispatch"},
        )
        assert status == 201 and "eventId" in body
        assert hdrs.get(TRACE_HEADER.lower()) == "up-evt-7"
        t = _find_trace(eventserver, "up-evt-7", want_stage="write")
        assert t["parent"] == "dispatch"
        stages = {s["stage"] for s in t["spans"]}
        assert {"accept", "admit", "parse", "store", "write"} <= stages
        # accept opens the waterfall at offset zero
        accepts = [s for s in t["spans"] if s["stage"] == "accept"]
        assert accepts[0]["startMs"] == 0.0

    def test_malformed_header_mints_fresh_id(self, eventserver, access_key):
        status, _, hdrs = _http(
            "POST", f"{eventserver}/events.json?accessKey={access_key}",
            EV, {TRACE_HEADER: "not a valid id!"},
        )
        assert status == 201
        minted = hdrs.get(TRACE_HEADER.lower())
        assert minted and minted != "not a valid id!"
        assert minted.startswith("event-")

    def test_hotpath_budget_over_live_requests(self, eventserver, access_key):
        for _ in range(5):
            status, _, hdrs = _http(
                "POST", f"{eventserver}/events.json?accessKey={access_key}", EV
            )
            assert status == 201
        # e2e lands in the post-flush write hook — poll until counted
        for _ in range(100):
            _, p, _ = _http("GET", f"{eventserver}/debug/hotpath.json")
            if p["requestCount"] >= 5:
                break
            time.sleep(0.01)
        assert p["requestCount"] >= 5
        stages = {s["stage"] for s in p["stages"]}
        assert {"accept", "admit", "parse", "store", "write"} <= stages
        assert not any("." in s for s in stages)
        assert all("." in s["stage"] for s in p["substages"])
        assert p["e2e"]["avgMs"] > 0
        assert 0 < p["attributedFraction"] <= 1.5

    def test_slow_ring_capture_via_env_threshold(self, eventserver,
                                                 access_key, monkeypatch):
        # 1e-4 ms = 100 ns: every request breaches (read per trace)
        monkeypatch.setenv("PIO_TPU_SLOW_TRACE_MS", "0.0001")
        status, _, hdrs = _http(
            "POST", f"{eventserver}/events.json?accessKey={access_key}",
            EV, {TRACE_HEADER: "evt-slow-1"},
        )
        assert status == 201
        for _ in range(100):
            _, body, _ = _http("GET", f"{eventserver}/traces.json?slow=1")
            ids = {t["id"] for t in body["traces"]}
            if "evt-slow-1" in ids:
                break
            time.sleep(0.01)
        assert "evt-slow-1" in ids
        got = next(t for t in body["traces"] if t["id"] == "evt-slow-1")
        assert got["slow"] is True

    def test_commit_ring_merged_into_traces(self, eventserver, access_key):
        with COMMIT_TRACER.trace(
            "commit", trace_id="commit-merge-1", links=["evt-x"],
            store="attr-merge", batch=1,
        ) as ctr:
            ctr.add_span("store.flush", 0.001, rel_start_s=0.0)
        _, body, _ = _http("GET", f"{eventserver}/traces.json?n=64")
        assert "commit-merge-1" in {t["id"] for t in body["traces"]}
        # ?commits=0 restricts to request traces
        _, body, _ = _http("GET", f"{eventserver}/traces.json?n=64&commits=0")
        assert "commit-merge-1" not in {t["id"] for t in body["traces"]}
        # by-id lookup reaches into the commit ring
        status, body, _ = _http(
            "GET", f"{eventserver}/traces.json?id=commit-merge-1"
        )
        assert status == 200
        assert body["traces"][0]["links"] == ["evt-x"]

    def test_unknown_trace_id_404(self, eventserver):
        status, body, _ = _http(
            "GET", f"{eventserver}/traces.json?id=never-existed"
        )
        assert status == 404
