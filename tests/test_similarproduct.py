"""Similar-Product + E-Commerce template tests.

Mirror the reference's similarproduct / ecommercerecommendation quickstart
behavior (SURVEY.md §4): view events + item $set categories → implicit ALS →
similar-item / personalized queries with business-rule filters.
"""

import datetime as dt

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers engine factories)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.storage import App, Storage
from pio_tpu.templates import ecommerce, similarproduct
from pio_tpu.workflow import (
    build_engine,
    load_models_for_instance,
    run_train,
    variant_from_dict,
)


@pytest.fixture(autouse=True)
def isolated_storage(tmp_home):
    Storage.reset()
    yield
    Storage.reset()


def _seed_views(app_id: int, n_users=12, n_items=8):
    """Two view blocks: u0-5 view i0-3 ('tech'), u6-11 view i4-7 ('food')."""
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    for i in range(n_items):
        cat = "tech" if i < 4 else "food"
        le.insert(
            Event(
                "$set", "item", f"i{i}",
                properties={"categories": [cat]},
                event_time=t0,
            ),
            app_id,
        )
    k = 0
    for u in range(n_users):
        for i in range(n_items):
            if (u < 6) == (i < 4):
                le.insert(
                    Event(
                        "view", "user", f"u{u}", "item", f"i{i}",
                        event_time=t0 + dt.timedelta(minutes=k),
                    ),
                    app_id,
                )
                k += 1


def _train(factory, algo, app_name="sp-test"):
    variant = variant_from_dict({
        "id": "sp-e2e",
        "engineFactory": factory,
        "datasource": {"params": {"app_name": app_name}},
        "algorithms": [algo],
    })
    engine, ep = build_engine(variant)
    ctx = ComputeContext.create(seed=0)
    instance_id = run_train(engine, ep, variant, ctx=ctx)
    models = load_models_for_instance(instance_id, engine, ep, ctx)
    serving = engine.make_serving(ep)
    pairs = engine.algorithms_with_models(ep, models)

    def serve(q):
        return serving.serve(q, [a.predict(m, q) for a, m in pairs])

    return serve


SP_ALGO = {
    "name": "als",
    "params": {"rank": 6, "num_iterations": 10, "lambda_": 0.05, "seed": 1},
}


class TestSimilarProduct:
    def _serve(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "sp-test"))
        _seed_views(app_id)
        return _train("templates.similarproduct", SP_ALGO)

    def test_similar_items_stay_in_block(self):
        serve = self._serve()
        res = serve(similarproduct.Query(items=("i0",), num=3))
        items = {s.item for s in res.item_scores}
        assert items == {"i1", "i2", "i3"}  # same co-view block, sans i0
        scores = [s.score for s in res.item_scores]
        assert scores == sorted(scores, reverse=True)

    def test_category_filter(self):
        serve = self._serve()
        res = serve(
            similarproduct.Query(items=("i0",), num=8, categories=("food",))
        )
        assert {s.item for s in res.item_scores} <= {"i4", "i5", "i6", "i7"}

    def test_white_and_black_list(self):
        serve = self._serve()
        res = serve(
            similarproduct.Query(
                items=("i0",), num=8,
                white_list=("i1", "i2"), black_list=("i2",),
            )
        )
        assert {s.item for s in res.item_scores} == {"i1"}

    def test_unknown_basket_empty(self):
        serve = self._serve()
        assert serve(similarproduct.Query(items=("nope",))).item_scores == ()

    def test_multi_item_basket(self):
        serve = self._serve()
        res = serve(similarproduct.Query(items=("i4", "i5"), num=2))
        assert {s.item for s in res.item_scores} == {"i6", "i7"}


EC_ALGO = {
    "name": "ecomm",
    "params": {
        "app_name": "ec-test",
        "rank": 6,
        "num_iterations": 10,
        "lambda_": 0.05,
        "seed": 1,
    },
}


class TestECommerce:
    def _setup(self, algo=EC_ALGO):
        app_id = Storage.get_meta_data_apps().insert(App(0, "ec-test"))
        _seed_views(app_id)
        return app_id, _train("templates.ecommerce", algo, app_name="ec-test")

    def test_personalized_block(self):
        _, serve = self._setup()
        res = serve(ecommerce.Query(user="u0", num=4))
        assert {s.item for s in res.item_scores} == {"i0", "i1", "i2", "i3"}

    def test_cold_user_falls_back_to_recent_views(self):
        app_id, serve = self._setup()
        # "newbie" never made it into training, but viewed food items since
        le = Storage.get_levents()
        t = dt.datetime(2026, 3, 2, tzinfo=dt.timezone.utc)
        for i in (4, 5):
            le.insert(
                Event("view", "user", "newbie", "item", f"i{i}",
                      event_time=t),
                app_id,
            )
        res = serve(ecommerce.Query(user="newbie", num=8))
        assert res.item_scores  # fallback produced recs
        top2 = {s.item for s in res.item_scores[:2]}
        assert top2 <= {"i4", "i5", "i6", "i7"}

    def test_cold_user_no_history_empty(self):
        _, serve = self._setup()
        assert serve(ecommerce.Query(user="ghost")).item_scores == ()

    def test_unavailable_items_filtered_live(self):
        app_id, serve = self._setup()
        res = serve(ecommerce.Query(user="u0", num=4))
        assert "i0" in {s.item for s in res.item_scores}
        # ops marks i0 unavailable — no retrain needed
        Storage.get_levents().insert(
            Event(
                "$set", "constraint", "unavailableItems",
                properties={"items": ["i0"]},
                event_time=dt.datetime(2026, 3, 3, tzinfo=dt.timezone.utc),
            ),
            app_id,
        )
        res = serve(ecommerce.Query(user="u0", num=4))
        assert "i0" not in {s.item for s in res.item_scores}

    def test_unseen_only_excludes_seen(self):
        algo = dict(EC_ALGO, params=dict(
            EC_ALGO["params"], unseen_only=True, num_recent_events=50
        ))
        _, serve = self._setup(algo)
        # u0 has viewed i0..i3 → with unseen_only those are excluded
        res = serve(ecommerce.Query(user="u0", num=8))
        assert {s.item for s in res.item_scores} <= {"i4", "i5", "i6", "i7"}

    def test_blacklist(self):
        _, serve = self._setup()
        res = serve(ecommerce.Query(user="u0", num=4, black_list=("i1",)))
        assert "i1" not in {s.item for s in res.item_scores}


class TestShippedEvaluation:
    def test_similarproduct_evaluation_sweep(self):
        from pio_tpu.templates.similarproduct import (
            similarproduct_evaluation,
        )
        from pio_tpu.workflow import run_evaluation

        app_id = Storage.get_meta_data_apps().insert(App(0, "sp-eval"))
        _seed_views(app_id)
        # eval_num=1 on the 8-item catalog keeps the metric
        # discriminative (HitRate@1; random chance ~1/7 per query)
        ev = similarproduct_evaluation(
            app_name="sp-eval", eval_k=3, ranks=(4,), num_iterations=8,
            eval_num=1,
        )
        result = run_evaluation(
            ev, ev.engine_params_generator, ctx=ComputeContext.create()
        )
        assert result.best_score > 0.4, result.best_score
        insts = Storage.get_meta_data_evaluation_instances().get_all()
        assert insts[0].status == "COMPLETED"


class TestBatchPredict:
    def test_batch_matches_loop(self):
        from pio_tpu.templates.similarproduct import Query

        app_id = Storage.get_meta_data_apps().insert(App(0, "sp-test"))
        _seed_views(app_id)
        variant = variant_from_dict({
            "id": "sp-bp", "engineFactory": "templates.similarproduct",
            "datasource": {"params": {"app_name": "sp-test"}},
            "algorithms": [{"name": "als",
                            "params": {"rank": 6, "num_iterations": 8}}],
        })
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        iid = run_train(engine, ep, variant, ctx=ctx)
        models = load_models_for_instance(iid, engine, ep, ctx)
        algo, model = engine.algorithms_with_models(ep, models)[0]
        queries = (
            [(i, Query(items=(f"i{i % 8}",), num=3)) for i in range(16)]
            + [(90, Query(items=("i1",), num=3, categories=("food",)))]
            + [(91, Query(items=("ghost",), num=3))]  # unknown basket
        )
        loop = {i: algo.predict(model, q) for i, q in queries}
        bat = dict(algo.batch_predict(model, queries))
        assert set(loop) == set(bat)
        for i in loop:
            assert [s.item for s in loop[i].item_scores] == [
                s.item for s in bat[i].item_scores
            ], i
