"""Device telemetry plane (ISSUE 17): sampler ledger/live modes,
compile-site attribution, headroom math, the ``/device.json`` surface
on both daemons, fleet federation, and the ``pio top`` one-shot.
"""

import json
import urllib.error
import urllib.request

import pytest

import pio_tpu.templates  # noqa: F401
from pio_tpu.obs import devicewatch
from pio_tpu.obs.devicewatch import DeviceWatch
from pio_tpu.obs.metrics import MetricsRegistry


def _watch(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return DeviceWatch(**kw)


# ----------------------------------------------------------------- ledger


class TestLedger:
    def test_place_release_and_peak_retention(self):
        w = _watch()
        w.ledger_place("resident", "m1", 1000, name="model one")
        w.ledger_place("donated", "m1", 200)
        rows = w.sample()
        assert w.ledger_bytes() == 1200
        assert rows[0]["source"] == "ledger"
        assert rows[0]["bytesInUse"] == 1200
        assert rows[0]["peakBytes"] == 1200
        w.ledger_release("resident", "m1")
        rows = w.sample()
        # bytes fall with the release; the high-water mark survives it
        assert rows[0]["bytesInUse"] == 200
        assert rows[0]["peakBytes"] == 1200

    def test_replace_same_key_is_resize(self):
        w = _watch()
        w.ledger_place("shard", "shard_params", 500)
        w.ledger_place("shard", "shard_params", 900)
        assert w.ledger_bytes() == 900

    def test_stream_carry_floors_at_zero(self):
        w = _watch()
        w.stream_carry(300)
        w.stream_carry(200)
        assert w.ledger_bytes() == 500
        w.stream_carry(-10_000)
        assert w.ledger_bytes() == 0

    def test_generation_restamps_unknown_rows(self):
        w = _watch()
        w.ledger_place("resident", "pre", 10)
        w.set_generation(3)
        w.ledger_place("resident", "post", 20)
        gens = {
            p["key"]: p["generation"] for p in w.payload()["placements"]
        }
        assert gens == {"pre": 3, "post": 3}
        w.set_generation(4)
        w.ledger_place("resident", "later", 30)
        gens = {
            p["key"]: p["generation"] for p in w.payload()["placements"]
        }
        # only never-stamped rows are restamped — history is kept
        assert gens == {"pre": 3, "post": 3, "later": 4}


# -------------------------------------------------------- live stats mode


def _live_stats(in_use, peak=None, limit=2**20, label="tpu:0"):
    return [(
        label,
        {"bytes_in_use": in_use,
         "peak_bytes_in_use": peak if peak is not None else in_use,
         "bytes_limit": limit},
        0,
    )]


class TestLiveMode:
    def test_memory_stats_rows_and_drift(self):
        w = _watch(stats_fn=lambda: _live_stats(5000, peak=8000))
        w.ledger_place("resident", "m", 4000)
        rows = w.sample()
        assert rows[0]["source"] == "memory_stats"
        assert rows[0]["bytesInUse"] == 5000
        assert rows[0]["limitBytes"] == 2**20
        # drift = measured - booked: the estimate-honesty gauge input
        assert rows[0]["driftBytes"] == 1000
        assert w.measured_bytes() == 5000

    def test_no_drift_without_ledger(self):
        w = _watch(stats_fn=lambda: _live_stats(5000))
        assert w.sample()[0]["driftBytes"] is None

    def test_ledger_mode_measures_nothing(self):
        w = _watch()
        w.ledger_place("resident", "m", 4000)
        w.sample()
        assert w.measured_bytes() is None

    def test_headroom_against_budget(self):
        w = _watch(
            stats_fn=lambda: _live_stats(600) + [
                ("tpu:1", {"bytes_in_use": 900,
                           "peak_bytes_in_use": 900,
                           "bytes_limit": None}, 1),
            ],
            budget_bytes=1000,
        )
        w.sample()
        p = w.payload()
        # budget minus the BUSIEST device, not the sum
        assert p["headroomBytes"] == 100
        assert p["budgetBytes"] == 1000

    def test_no_budget_no_headroom(self):
        w = _watch(stats_fn=lambda: _live_stats(600))
        p = w.payload()
        assert p["budgetBytes"] is None and p["headroomBytes"] is None


# -------------------------------------------------- compile attribution


class TestCompileAttribution:
    def test_span_dedups_by_site_key(self):
        w = _watch()
        with w.span("resident_scorer", key=("b", 4)) as fresh:
            assert fresh
        with w.span("resident_scorer", key=("b", 4)) as fresh:
            assert not fresh
        with w.span("resident_scorer", key=("b", 8)) as fresh:
            assert fresh
        # same key under a DIFFERENT site is its own program cache
        with w.span("train_step", key=("b", 4)) as fresh:
            assert fresh
        assert w.compile_counts() == {
            "resident_scorer": 2, "train_step": 1,
        }

    def test_none_key_always_fresh(self):
        w = _watch()
        for _ in range(3):
            with w.span("bucket_warmup") as fresh:
                assert fresh
        assert w.compile_counts() == {"bucket_warmup": 3}

    def test_record_carries_seconds_and_histogram(self):
        w = _watch()
        w.record_compile("train_step", 0.25, trace_id="t-1")
        w.record_compile("train_step", 0.05)
        sites = w.payload()["compiles"]["sites"]
        row = sites["train_step"]
        assert row["count"] == 2
        assert row["seconds"] == pytest.approx(0.30)
        assert row["lastS"] == pytest.approx(0.05)
        assert row["lastTraceId"] == "t-1"
        text = "\n".join(w.registry.render())
        assert 'pio_tpu_xla_compile_total{site="train_step"} 2' in text
        assert 'pio_tpu_xla_compile_seconds_count{site="train_step"} 2' \
            in text

    def test_module_hooks_route_to_active_watch(self):
        w = _watch()
        # a service fixture elsewhere in the suite may have left its
        # watch active — clear it so the no-op path is actually no-op
        devicewatch.deactivate()
        # inactive: the hooks are no-ops
        devicewatch.record_compile("stream_dispatch")
        with devicewatch.compile_span("stream_dispatch", key=1) as fresh:
            assert not fresh
        with devicewatch.watching(w, sample=False):
            devicewatch.record_compile("stream_dispatch")
            with devicewatch.compile_span(
                "stream_dispatch", key=devicewatch.shape_key([1, 2])
            ) as fresh:
                assert fresh
            devicewatch.ledger_place("shard", "k", 64)
            devicewatch.stream_carry(32)
        assert w.compile_counts()["stream_dispatch"] == 2
        assert w.ledger_bytes() == 96
        # deactivated again: nothing lands
        devicewatch.record_compile("stream_dispatch")
        assert w.compile_counts()["stream_dispatch"] == 2
        assert devicewatch.last_watch() is w

    def test_shape_key_distinguishes_leaf_shapes(self):
        import numpy as np

        a = devicewatch.shape_key([np.zeros((2, 3)), np.zeros(4)])
        b = devicewatch.shape_key([np.zeros((2, 3)), np.zeros(5)])
        assert a != b and a == devicewatch.shape_key(
            [np.ones((2, 3)), np.ones(4)]
        )


# ------------------------------------------------- service integration
# Same fixture shape as tests/test_batch_buckets.py: memory storage,
# a tiny trained classification instance with residency forced on, then
# the service's /device.json driven directly (handlers take Request|None).

import datetime as dt  # noqa: E402

from pio_tpu.controller import ComputeContext  # noqa: E402
from pio_tpu.data import Event  # noqa: E402
from pio_tpu.server.query_server import QueryServerService  # noqa: E402
from pio_tpu.storage import App, Storage  # noqa: E402
from pio_tpu.workflow import (  # noqa: E402
    build_engine,
    run_train,
    variant_from_dict,
)

VARIANT = {
    "id": "cls-devwatch",
    "engineFactory": "templates.classification",
    "datasource": {"params": {"app_name": "devwatch-test"}},
    "algorithms": [{"name": "logreg", "params": {}}],
}


@pytest.fixture()
def mem_storage(tmp_home, monkeypatch):
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "MEM")
    Storage.reset()
    yield
    Storage.reset()


def _train_classification():
    import numpy as np

    app_id = Storage.get_meta_data_apps().insert(App(0, "devwatch-test"))
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 5, 1, tzinfo=dt.timezone.utc)
    rng = np.random.default_rng(7)
    n = 0
    for plan, hot in (("basic", 0), ("premium", 1), ("pro", 2)):
        for _ in range(8):
            attrs = rng.integers(0, 3, size=3)
            attrs[hot] += 6
            props = {f"attr{j}": int(attrs[j]) for j in range(3)}
            props["plan"] = plan
            le.insert(
                Event("$set", "user", f"u{n}", properties=props,
                      event_time=t0 + dt.timedelta(minutes=n)),
                app_id,
            )
            n += 1
    variant = variant_from_dict(VARIANT)
    engine, ep = build_engine(variant)
    ctx = ComputeContext.local()
    run_train(engine, ep, variant, ctx=ctx)
    return variant, ctx


@pytest.fixture()
def resident_service(mem_storage, monkeypatch):
    monkeypatch.setenv("PIO_TPU_DEVICE_RESIDENT", "1")
    monkeypatch.setenv("PIO_TPU_BATCH_BUCKETS", "1,2,4")
    monkeypatch.setenv("PIO_TPU_BUCKET_WARMUP", "1")
    monkeypatch.setenv(devicewatch.SAMPLER_ENV, "0")  # sample on demand
    variant, ctx = _train_classification()
    svc = QueryServerService(variant, ctx=ctx)
    yield svc
    svc.devwatch.stop()
    devicewatch.deactivate(svc.devwatch)


class TestServiceDeviceJson:
    def test_payload_books_residency_and_warmup(self, resident_service):
        svc = resident_service
        assert svc._resident  # residency placed, or the test is vacuous
        status, body = svc.get_device(None)
        assert status == 200
        assert body["mode"] == "ledger"  # CPU: no memory_stats
        cats = body["ledger"]["byCategory"]
        assert cats.get("resident", 0) > 0    # scorer params booked
        assert cats.get("donated", 0) > 0     # prealloc'd logits buffers
        assert body["generation"] == 1
        assert body["devices"][0]["bytesInUse"] == body["ledger"][
            "totalBytes"
        ]
        # the deploy-time warmup sweep is the only compile activity
        sites = body["compiles"]["sites"]
        assert sites["bucket_warmup"]["count"] == 3
        assert "bucket_dispatch" not in sites

    def test_queries_attribute_scorer_compiles_once(self, resident_service):
        svc = resident_service
        from pio_tpu.templates.classification import Query

        before = svc.devwatch.compile_counts()
        for _ in range(4):
            svc._predict_one(Query(attrs=(9.0, 1.0, 1.0)))
        after = svc.devwatch.compile_counts()
        # the warmup sweep already owns every program for warmed shapes:
        # a steady query window must not move any site counter
        assert after == before

    def test_hot_swap_bumps_generation_compiles_flat(self, resident_service):
        svc = resident_service
        before = svc.devwatch.compile_counts()
        status, body = svc.get_device(None)
        assert body["generation"] == 1
        svc._load(None)                       # the /reload path
        status, body = svc.get_device(None)
        assert body["generation"] == 2
        # re-warm over the unchanged bucket ladder hits the global jit
        # cache — the attribution must NOT recount it
        assert svc.devwatch.compile_counts() == before

    def test_retire_releases_ledger_bytes(self, resident_service):
        svc = resident_service
        in_use = svc.get_device(None)[1]["ledger"]["totalBytes"]
        assert in_use > 0
        for sc in list(svc._resident):
            sc.retire()
        after = svc.get_device(None)[1]["ledger"]["byCategory"]
        assert after.get("resident", 0) == 0
        assert after.get("donated", 0) == 0
        # the peak survives the retirement (high-water semantics)
        peak = svc.get_device(None)[1]["devices"][0]["peakBytes"]
        assert peak >= in_use

    def test_stats_json_measured_beside_estimated(self, resident_service):
        from pio_tpu.server.http import Request

        svc = resident_service
        status, stats = svc.get_stats(
            Request("GET", "/stats.json", {}, None)
        )
        assert status == 200
        res = stats["residency"]
        assert "measuredBytes" in res and "paramBytes" in res
        assert res["measuredBytes"] is None   # ledger mode on CPU
        # the disabled sharding block stays minimal — measuredBytes only
        # rides an enabled mesh placement
        assert "measuredBytes" not in stats["sharding"]


# ------------------------------------------------- trainer sidecar + top


def _http(url):
    try:
        with urllib.request.urlopen(url, timeout=15) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


class TestTrainSidecar:
    def test_device_json_503_without_watch_then_200(self):
        from pio_tpu.server.fleetd import create_train_status_server

        devicewatch.deactivate()
        server = create_train_status_server().start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            assert _http(base + "/device.json")[0] == 503
            w = _watch(budget_bytes=1000)
            w.ledger_place("stream", "chunk", 400)
            with devicewatch.watching(w, sample=False):
                status, body = _http(base + "/device.json")
                assert status == 200
                assert body["ledger"]["totalBytes"] == 400
                assert body["headroomBytes"] == 600
            assert _http(base + "/device.json")[0] == 503
        finally:
            server.stop()

    def test_pio_top_once_renders_snapshot(self, capsys):
        from pio_tpu.server.fleetd import create_train_status_server
        from pio_tpu.tools import cli

        server = create_train_status_server().start()
        base = f"http://127.0.0.1:{server.port}"
        w = _watch()
        w.ledger_place("resident", "m", 2 * 1048576)
        w.record_compile("train_step", 0.1)
        try:
            with devicewatch.watching(w, sample=False):
                rc = cli.main(["top", "--once", "--url", base])
            out = capsys.readouterr().out
        finally:
            server.stop()
        assert rc == 0
        assert "\x1b[" not in out             # --once never clears
        assert "mode ledger" in out
        assert "2.0" in out                   # MiB rendering
        assert "compiles total 1" in out
        assert "train_step" in out

    def test_pio_top_once_unreachable_exits_nonzero(self, capsys):
        from pio_tpu.tools import cli

        rc = cli.main(
            ["top", "--once", "--url", "http://127.0.0.1:1"]
        )
        assert rc == 1


# ------------------------------------------------------ fleet federation

from pio_tpu.obs.fleet import FleetAggregator, parse_targets  # noqa: E402


class _FakeFleet:
    def __init__(self, members):
        self.members = dict(members)

    def fetch(self, url, timeout):
        name = url.split("://", 1)[1].split("/", 1)[0]
        path = "/" + url.split("://", 1)[1].split("/", 1)[1]
        endpoints = self.members.get(name)
        if endpoints is None:
            raise OSError(f"connection refused: {name}")
        if path not in endpoints:
            raise urllib.error.HTTPError(url, 404, "nope", {}, None)
        body = endpoints[path]
        return body.encode() if isinstance(body, str) else body


def _member_device_json(in_use, budget=None, generation=1):
    return json.dumps({
        "mode": "ledger",
        "budgetBytes": budget,
        "headroomBytes": budget - in_use if budget else None,
        "generation": generation,
        "devices": [{"device": "cpu:0", "bytesInUse": in_use,
                     "peakBytes": in_use, "limitBytes": None}],
        "compiles": {"total": 4, "sites": {}},
    })


METRICS = "# TYPE pio_tpu_q_total counter\npio_tpu_q_total 1\n"


class TestFleetDevices:
    def test_member_rows_and_tightest_rollup(self):
        fake = _FakeFleet({
            "a:1": {"/metrics": METRICS,
                    "/device.json": _member_device_json(
                        100, budget=1000, generation=2)},
            "b:2": {"/metrics": METRICS,
                    "/device.json": _member_device_json(
                        900, budget=1000)},
            "c:3": {"/metrics": METRICS},     # no device surface
        })
        agg = FleetAggregator(
            parse_targets("a:1,b:2,c:3"), registry=MetricsRegistry(),
            fetch=fake.fetch, interval_s=0.05,
        )
        assert agg.scrape_once() == 3
        payload = agg.fleet_payload()
        by = {e["member"]: e for e in payload["members"]}
        assert by["a:1"]["devices"]["bytesInUse"] == 100
        assert by["a:1"]["devices"]["generation"] == 2
        assert by["a:1"]["devices"]["compiles"] == 4
        assert by["c:3"]["devices"] is None
        roll = payload["devices"]
        assert set(roll["members"]) == {"a:1", "b:2"}
        # b is the memory-tightest member — the eviction-policy signal
        assert roll["tightest"] == {
            "member": "b:2", "headroomBytes": 100,
        }

    def test_snapshot_retained_across_member_death(self):
        fake = _FakeFleet({
            "a:1": {"/metrics": METRICS,
                    "/device.json": _member_device_json(100, budget=500)},
        })
        agg = FleetAggregator(
            parse_targets("a:1"), registry=MetricsRegistry(),
            fetch=fake.fetch, interval_s=0.05,
        )
        assert agg.scrape_once() == 1
        fake.members["a:1"] = None            # member dies
        agg.scrape_once()
        entry = agg.fleet_payload()["members"][0]
        assert entry["devices"]["bytesInUse"] == 100  # last-seen kept
