"""End-to-end slice (SURVEY.md §7.5): import events → DataSource(find→arrays)
→ Preparator(BiMap) → ALS train via run_train → model store → reload →
top-N query. The quickstart_test.py analog of the reference's integration
tier, minus the HTTP servers (covered in server tests)."""

import datetime as dt
import json

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers the engine factory)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.storage import App, RunStatus, Storage
from pio_tpu.templates.recommendation import PredictedResult, Query
from pio_tpu.workflow import (
    build_engine,
    load_models_for_instance,
    run_train,
    variant_from_dict,
)


@pytest.fixture(autouse=True)
def isolated_storage(tmp_home):
    Storage.reset()
    yield
    Storage.reset()


def _seed_events(app_id: int, n_users=12, n_items=8):
    """Block structure: users u0..5 love items i0..3; u6..11 love i4..7."""
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    events = []
    for u in range(n_users):
        for i in range(n_items):
            in_block = (u < 6) == (i < 4)
            rating = 5.0 if in_block else 1.0
            events.append(
                Event(
                    "rate",
                    "user",
                    f"u{u}",
                    "item",
                    f"i{i}",
                    properties={"rating": rating},
                    event_time=t0 + dt.timedelta(minutes=u * 60 + i),
                )
            )
    # one buy event (implicit 4.0) and one unrelated event type
    events.append(Event("buy", "user", "u0", "item", "i3", event_time=t0))
    events.append(Event("view", "user", "u0", "item", "i7", event_time=t0))
    for e in events:
        le.insert(e, app_id)


VARIANT = {
    "id": "rec-e2e",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "rec-test"}},
    "algorithms": [
        {
            "name": "als",
            "params": {"rank": 6, "num_iterations": 10, "lambda_": 0.05, "seed": 1},
        }
    ],
}


class TestRecommendationEndToEnd:
    def test_full_lifecycle(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "rec-test"))
        _seed_events(app_id)

        variant = variant_from_dict(VARIANT)
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        instance_id = run_train(engine, ep, variant, ctx=ctx)

        inst = Storage.get_meta_data_engine_instances().get(instance_id)
        assert inst.status == RunStatus.COMPLETED

        # reload from the model store, as deploy would
        models = load_models_for_instance(instance_id, engine, ep, ctx)
        serving = engine.make_serving(ep)
        pairs = engine.algorithms_with_models(ep, models)

        def query(user, num=4):
            q = Query(user=user, num=num)
            preds = [algo.predict(m, q) for algo, m in pairs]
            return serving.serve(q, preds)

        res = query("u0")
        assert isinstance(res, PredictedResult)
        assert len(res.item_scores) == 4
        # u0 is in the first block: its top items must be i0..i3
        top_items = {s.item for s in res.item_scores}
        assert top_items == {"i0", "i1", "i2", "i3"}
        # scores sorted descending
        scores = [s.score for s in res.item_scores]
        assert scores == sorted(scores, reverse=True)

        # second-block user prefers i4..7
        res2 = query("u11")
        assert {s.item for s in res2.item_scores} == {"i4", "i5", "i6", "i7"}

        # unknown user → empty result, JSON-able
        assert query("stranger") == PredictedResult()
        assert json.loads(json.dumps(res.to_dict()))["itemScores"][0]["item"]

    def test_empty_app_fails_sanity(self):
        Storage.get_meta_data_apps().insert(App(0, "rec-test"))
        variant = variant_from_dict(VARIANT)
        engine, ep = build_engine(variant)
        with pytest.raises(ValueError, match="TrainingData is empty"):
            run_train(engine, ep, variant, ctx=ComputeContext.local())
        insts = Storage.get_meta_data_engine_instances().get_all()
        assert insts[0].status == RunStatus.FAILED

    def test_eval_folds(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "rec-test"))
        _seed_events(app_id)
        variant = variant_from_dict(
            {
                **VARIANT,
                "datasource": {
                    "params": {"app_name": "rec-test", "eval_k": 3}
                },
                # held-out folds are tiny: rank 2 + stronger reg keeps the
                # normal equations well-conditioned (rank 6 overfits them)
                "algorithms": [
                    {
                        "name": "als",
                        "params": {"rank": 2, "num_iterations": 15,
                                   "lambda_": 0.1, "seed": 1},
                    }
                ],
            }
        )
        engine, ep = build_engine(variant)
        folds = engine.eval(ComputeContext.create(seed=0), ep)
        assert len(folds) == 3
        # rating predictions on held-out pairs should beat a constant-3 guess
        sq_err, sq_base, n = 0.0, 0.0, 0
        for _, qpa in folds:
            for q, p, actual in qpa:
                if p.item_scores:
                    sq_err += (p.item_scores[0].score - actual) ** 2
                    sq_base += (3.0 - actual) ** 2
                    n += 1
        assert n > 50
        assert sq_err / n < sq_base / n


class TestShippedEvaluation:
    def test_recommendation_evaluation_sweep(self):
        from pio_tpu.templates.recommendation import (
            recommendation_evaluation,
        )
        from pio_tpu.workflow import run_evaluation

        app_id = Storage.get_meta_data_apps().insert(App(0, "rec-eval"))
        _seed_events(app_id)
        ev = recommendation_evaluation(
            app_name="rec-eval", eval_k=3, ranks=(2,), lambdas=(0.1, 0.3),
            num_iterations=10,
        )
        result = run_evaluation(
            ev, ev.engine_params_generator, ctx=ComputeContext.create()
        )
        # MSE (lower better): must beat predicting a constant 3 everywhere
        assert result.best_score < 2.0
        insts = Storage.get_meta_data_evaluation_instances().get_all()
        assert insts[0].status == "COMPLETED"


class TestBatchPredict:
    def test_batch_matches_loop(self):
        from pio_tpu.templates.recommendation import Query

        app_id = Storage.get_meta_data_apps().insert(App(0, "rec-test"))
        _seed_events(app_id)
        variant = variant_from_dict(VARIANT)
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        iid = run_train(engine, ep, variant, ctx=ctx)
        models = load_models_for_instance(iid, engine, ep, ctx)
        algo, model = engine.algorithms_with_models(ep, models)[0]
        queries = (
            [(i, Query(user=f"u{i % 10}", num=4)) for i in range(20)]
            + [(90, Query(user="u1", num=1, item="i2"))]  # single-item
            + [(91, Query(user="ghost", num=4))]          # unknown user
        )
        loop = {i: algo.predict(model, q) for i, q in queries}
        bat = dict(algo.batch_predict(model, queries))
        assert set(loop) == set(bat)
        for i in loop:
            assert [s.item for s in loop[i].item_scores] == [
                s.item for s in bat[i].item_scores
            ], i
