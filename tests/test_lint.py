"""Golden fixtures for `pio lint` — one bad/clean pair per rule — plus
the runtime lock-order detector's seeded-inversion tests.

The bad code lives inside string literals written out to tmp files, so
the linter parsing THIS file (the tier-1 clean gate runs over tests/)
only sees string constants and stays clean.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
import threading

import pytest

from pio_tpu.analysis import run_lint
from pio_tpu.analysis.core import all_rules


def lint_src(tmp_path, source, *, name="fixture.py", rules=None, catalog=None):
    """Write ``source`` to a tmp module and lint it, returning findings."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return run_lint([str(p)], rule_ids=rules, catalog=catalog)


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# framework basics


class TestFramework:
    def test_rule_registry_has_at_least_eight_rules(self):
        rules = all_rules().values()
        assert len(rules) >= 8
        families = {r.family for r in rules}
        assert families >= {"concurrency", "convention", "hotpath", "layout"}

    def test_parse_error_is_a_finding(self, tmp_path):
        findings = lint_src(tmp_path, "def broken(:\n")
        assert rule_ids(findings) == ["parse-error"]

    def test_unknown_rule_id_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            lint_src(tmp_path, "x = 1\n", rules=["no-such-rule"])

    def test_line_suppression(self, tmp_path):
        src = """
        import time

        def f():
            t = time.time()  # pio: disable=wallclock-duration
            return t
        """
        assert lint_src(tmp_path, src, rules=["wallclock-duration"]) == []

    def test_standalone_comment_suppresses_next_line(self, tmp_path):
        src = """
        import time

        def f():
            # pio: disable=wallclock-duration
            return time.time()
        """
        assert lint_src(tmp_path, src, rules=["wallclock-duration"]) == []

    def test_whole_file_suppression(self, tmp_path):
        src = """
        # pio: disable-file=wallclock-duration
        import time

        def f():
            return time.time()
        """
        assert lint_src(tmp_path, src, rules=["wallclock-duration"]) == []

    def test_suppression_marker_inside_string_is_inert(self, tmp_path):
        src = '''
        import time

        def f():
            s = "# pio: disable=wallclock-duration"
            return time.time(), s
        '''
        findings = lint_src(tmp_path, src, rules=["wallclock-duration"])
        assert rule_ids(findings) == ["wallclock-duration"]

    def test_json_reporter_round_trips(self, tmp_path):
        from pio_tpu.analysis.core import render_json

        src = "import time\n\nx = time.time()\n"
        findings = lint_src(tmp_path, src, rules=["wallclock-duration"])
        doc = json.loads(render_json(findings))
        assert doc["count"] == len(findings) == 1
        assert doc["findings"][0]["rule"] == "wallclock-duration"

    def test_cli_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import time\nx = time.time()\n")
        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        rc_bad = subprocess.run(
            [sys.executable, "-m", "pio_tpu.tools.cli", "lint", str(bad)],
            capture_output=True,
        ).returncode
        rc_good = subprocess.run(
            [sys.executable, "-m", "pio_tpu.tools.cli", "lint", str(good)],
            capture_output=True,
        ).returncode
        assert (rc_bad, rc_good) == (1, 0)


# ---------------------------------------------------------------------------
# concurrency family


class TestLockBlockingCall:
    BAD = """
    import threading
    import time

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def f(self):
            with self._lock:
                time.sleep(1.0)
    """

    CLEAN = """
    import threading
    import time

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def f(self):
            with self._lock:
                x = 1
            time.sleep(1.0)
            return x
    """

    def test_bad_flagged(self, tmp_path):
        findings = lint_src(tmp_path, self.BAD, rules=["lock-blocking-call"])
        assert rule_ids(findings) == ["lock-blocking-call"]

    def test_clean_passes(self, tmp_path):
        assert lint_src(tmp_path, self.CLEAN,
                        rules=["lock-blocking-call"]) == []

    def test_subprocess_under_lock_flagged(self, tmp_path):
        src = """
        import subprocess
        import threading

        guard = threading.Lock()

        def f():
            with guard:
                subprocess.run(["true"])
        """
        findings = lint_src(tmp_path, src, rules=["lock-blocking-call"])
        assert rule_ids(findings) == ["lock-blocking-call"]

    def test_nested_def_resets_lock_context(self, tmp_path):
        # the closure is DEFINED under the lock but runs later
        src = """
        import threading
        import time

        guard = threading.Lock()

        def f():
            with guard:
                def later():
                    time.sleep(1.0)
            return later
        """
        assert lint_src(tmp_path, src, rules=["lock-blocking-call"]) == []

    # interprocedural pair: the blocking call is one frame below the
    # lock body, visible only through the effect summaries
    BAD_DEEP = """
    import threading
    import time

    def slow_flush():
        time.sleep(0.5)

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def f(self):
            with self._lock:
                slow_flush()
    """

    CLEAN_DEEP = """
    import threading
    import time

    def slow_flush():
        time.sleep(0.5)

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def f(self):
            with self._lock:
                x = 1
            slow_flush()
            return x
    """

    def test_blocking_one_frame_down_flagged(self, tmp_path):
        findings = lint_src(tmp_path, self.BAD_DEEP,
                            rules=["lock-blocking-call"])
        assert rule_ids(findings) == ["lock-blocking-call"]
        assert "slow_flush" in findings[0].message
        assert "time.sleep" in findings[0].message

    def test_blocking_one_frame_down_clean(self, tmp_path):
        assert lint_src(tmp_path, self.CLEAN_DEEP,
                        rules=["lock-blocking-call"]) == []


class TestCvWaitOutsideLoop:
    BAD = """
    import threading

    class C:
        def __init__(self):
            self._cv = threading.Condition()
            self.ready = False

        def f(self):
            with self._cv:
                if not self.ready:
                    self._cv.wait()
    """

    CLEAN = """
    import threading

    class C:
        def __init__(self):
            self._cv = threading.Condition()
            self.ready = False

        def f(self):
            with self._cv:
                while not self.ready:
                    self._cv.wait()
    """

    def test_bad_flagged(self, tmp_path):
        findings = lint_src(tmp_path, self.BAD, rules=["cv-wait-outside-loop"])
        assert rule_ids(findings) == ["cv-wait-outside-loop"]

    def test_clean_passes(self, tmp_path):
        assert lint_src(tmp_path, self.CLEAN,
                        rules=["cv-wait-outside-loop"]) == []

    def test_wait_for_is_exempt(self, tmp_path):
        src = """
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self.ready = False

            def f(self):
                with self._cv:
                    self._cv.wait_for(lambda: self.ready)
        """
        assert lint_src(tmp_path, src, rules=["cv-wait-outside-loop"]) == []


class TestCvNotifyUnlocked:
    BAD = """
    import threading

    class C:
        def __init__(self):
            self._cv = threading.Condition()

        def f(self):
            self._cv.notify_all()
    """

    CLEAN = """
    import threading

    class C:
        def __init__(self):
            self._cv = threading.Condition()

        def f(self):
            with self._cv:
                self._cv.notify_all()
    """

    def test_bad_flagged(self, tmp_path):
        findings = lint_src(tmp_path, self.BAD, rules=["cv-notify-unlocked"])
        assert rule_ids(findings) == ["cv-notify-unlocked"]

    def test_clean_passes(self, tmp_path):
        assert lint_src(tmp_path, self.CLEAN,
                        rules=["cv-notify-unlocked"]) == []


class TestLockOrderCycle:
    BAD = """
    import threading

    class C:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def ab(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def ba(self):
            with self._b_lock:
                with self._a_lock:
                    pass
    """

    CLEAN = """
    import threading

    class C:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def ab(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def ab_again(self):
            with self._a_lock:
                with self._b_lock:
                    pass
    """

    def test_same_module_ab_ba_flagged(self, tmp_path):
        findings = lint_src(tmp_path, self.BAD, rules=["lock-order-cycle"])
        assert rule_ids(findings) == ["lock-order-cycle"]

    def test_consistent_order_passes(self, tmp_path):
        assert lint_src(tmp_path, self.CLEAN,
                        rules=["lock-order-cycle"]) == []

    def test_cycle_through_call_edge_flagged(self, tmp_path):
        # ab() holds A and calls helper() which takes B; ba() nests B->A
        # directly — the cycle only exists through the call summary.
        src = """
        import threading

        class C:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def helper(self):
                with self._b_lock:
                    pass

            def ab(self):
                with self._a_lock:
                    self.helper()

            def ba(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """
        findings = lint_src(tmp_path, src, rules=["lock-order-cycle"])
        assert rule_ids(findings) == ["lock-order-cycle"]

    def test_two_module_import_cycle_flagged(self, tmp_path):
        # the cycle only exists across the import boundary: moda holds A
        # and calls into modb (which takes B); modb holds B and calls
        # back into moda (which takes A)
        (tmp_path / "moda.py").write_text(textwrap.dedent("""
            import threading

            import modb

            a_lock = threading.Lock()

            def take_a():
                with a_lock:
                    pass

            def a_then_b():
                with a_lock:
                    modb.take_b()
        """))
        (tmp_path / "modb.py").write_text(textwrap.dedent("""
            import threading

            import moda

            b_lock = threading.Lock()

            def take_b():
                with b_lock:
                    pass

            def b_then_a():
                with b_lock:
                    moda.take_a()
        """))
        findings = run_lint([str(tmp_path)], rule_ids=["lock-order-cycle"])
        assert rule_ids(findings) == ["lock-order-cycle"]


# ---------------------------------------------------------------------------
# convention family


class TestReleaseInFinally:
    BAD = """
    def handler(gate, req):
        admission = gate.admit(req)
        do_work(req)
        admission.release()
    """

    CLEAN = """
    def handler(gate, req):
        admission = gate.admit(req)
        try:
            do_work(req)
        finally:
            admission.release()
    """

    TRANSFER = """
    def admit_then_auth(gate, req):
        admission = gate.admit(req)
        check_auth(req)
        return admission
    """

    def test_bad_flagged(self, tmp_path):
        findings = lint_src(tmp_path, self.BAD, rules=["release-in-finally"])
        assert rule_ids(findings) == ["release-in-finally"]

    def test_clean_passes(self, tmp_path):
        assert lint_src(tmp_path, self.CLEAN,
                        rules=["release-in-finally"]) == []

    def test_ownership_transfer_passes(self, tmp_path):
        assert lint_src(tmp_path, self.TRANSFER,
                        rules=["release-in-finally"]) == []


class TestMetricName:
    CATALOG = {"pio_tpu_good_total", "pio_tpu_depth"}

    def test_bad_prefix_flagged(self, tmp_path):
        src = """
        def setup(reg):
            return reg.counter("requests_total", "desc")
        """
        findings = lint_src(tmp_path, src, rules=["metric-name"],
                            catalog=self.CATALOG)
        assert rule_ids(findings) == ["metric-name"]

    def test_counter_missing_total_suffix_flagged(self, tmp_path):
        src = """
        def setup(reg):
            return reg.counter("pio_tpu_requests", "desc")
        """
        findings = lint_src(tmp_path, src, rules=["metric-name"],
                            catalog=self.CATALOG)
        assert rule_ids(findings) == ["metric-name"]

    def test_gauge_with_total_suffix_flagged(self, tmp_path):
        src = """
        def setup(reg):
            return reg.gauge("pio_tpu_depth_total", "desc")
        """
        findings = lint_src(tmp_path, src, rules=["metric-name"],
                            catalog=self.CATALOG)
        assert rule_ids(findings) == ["metric-name"]

    def test_uncatalogued_name_flagged(self, tmp_path):
        src = """
        def setup(reg):
            return reg.counter("pio_tpu_undocumented_total", "desc")
        """
        findings = lint_src(tmp_path, src, rules=["metric-name"],
                            catalog=self.CATALOG)
        assert rule_ids(findings) == ["metric-name"]

    def test_catalogued_names_pass(self, tmp_path):
        src = """
        def setup(reg):
            c = reg.counter("pio_tpu_good_total", "desc")
            g = reg.gauge("pio_tpu_depth", "desc")
            return c, g
        """
        assert lint_src(tmp_path, src, rules=["metric-name"],
                        catalog=self.CATALOG) == []


class TestFailpointName:
    def test_duplicate_name_flagged(self, tmp_path):
        src = """
        from pio_tpu.faults import failpoint

        def a():
            failpoint("storage.write")

        def b():
            failpoint("storage.write")
        """
        findings = lint_src(tmp_path, src, rules=["failpoint-name"])
        assert rule_ids(findings) == ["failpoint-name"]

    def test_bad_namespace_flagged(self, tmp_path):
        src = """
        from pio_tpu.faults import failpoint

        def a():
            failpoint("mystuff.write")
        """
        findings = lint_src(tmp_path, src, rules=["failpoint-name"])
        assert rule_ids(findings) == ["failpoint-name"]

    def test_unique_namespaced_names_pass(self, tmp_path):
        src = """
        from pio_tpu.faults import failpoint

        def a():
            failpoint("storage.write")

        def b(store):
            failpoint(f"groupcommit.flush.{store}")
        """
        assert lint_src(tmp_path, src, rules=["failpoint-name"]) == []


class TestEnvHardening:
    BAD = """
    import os

    def knob():
        return float(os.environ.get("PIO_TPU_KNOB", "1.5"))
    """

    CLEAN = """
    from pio_tpu.utils.envutil import env_float

    def knob():
        return env_float("PIO_TPU_KNOB", 1.5)
    """

    def test_bad_flagged(self, tmp_path):
        findings = lint_src(tmp_path, self.BAD, rules=["env-hardening"])
        assert rule_ids(findings) == ["env-hardening"]

    def test_clean_passes(self, tmp_path):
        assert lint_src(tmp_path, self.CLEAN, rules=["env-hardening"]) == []


class TestWallclockDuration:
    BAD = """
    import time

    def elapsed(fn):
        t0 = time.monotonic()
        fn()
        return time.monotonic() - t0
    """

    CLEAN = """
    from pio_tpu.obs import monotonic_s

    def elapsed(fn):
        t0 = monotonic_s()
        fn()
        return monotonic_s() - t0
    """

    def test_bad_flagged(self, tmp_path):
        findings = lint_src(tmp_path, self.BAD, rules=["wallclock-duration"])
        assert len(findings) == 2
        assert rule_ids(findings) == ["wallclock-duration"]

    def test_clean_passes(self, tmp_path):
        assert lint_src(tmp_path, self.CLEAN,
                        rules=["wallclock-duration"]) == []


# ---------------------------------------------------------------------------
# envutil behaviour backing the env-hardening rule


class TestEnvUtil:
    def test_garbage_warns_and_defaults(self, monkeypatch):
        from pio_tpu.utils.envutil import env_float

        monkeypatch.setenv("PIO_TPU_LINT_T_KNOB", "banana")
        with pytest.warns(RuntimeWarning, match="PIO_TPU_LINT_T_KNOB"):
            assert env_float("PIO_TPU_LINT_T_KNOB", 2.5) == 2.5

    def test_positive_rejects_nonpositive(self, monkeypatch):
        from pio_tpu.utils.envutil import env_int

        monkeypatch.setenv("PIO_TPU_LINT_T_KNOB", "-3")
        with pytest.warns(RuntimeWarning):
            assert env_int("PIO_TPU_LINT_T_KNOB", 7, positive=True) == 7

    def test_good_value_parses_silently(self, monkeypatch):
        from pio_tpu.utils.envutil import env_float

        monkeypatch.setenv("PIO_TPU_LINT_T_KNOB", "0.25")
        assert env_float("PIO_TPU_LINT_T_KNOB", 9.0) == 0.25


# ---------------------------------------------------------------------------
# runtime lock-order detector


class TestRuntimeDetector:
    @pytest.fixture(autouse=True)
    def armed(self, monkeypatch):
        from pio_tpu.analysis.runtime import sync_debugger

        monkeypatch.setenv("PIO_TPU_DEBUG_SYNC", "1")
        sync_debugger().reset()
        yield
        sync_debugger().reset()

    def test_seeded_ab_ba_inversion_raises(self):
        from pio_tpu.analysis.runtime import (
            LockOrderInversion, make_lock,
        )

        a = make_lock("lint_t.a")
        b = make_lock("lint_t.b")
        with a:
            with b:
                pass
        with pytest.raises(LockOrderInversion, match="lint_t"):
            with b:
                with a:
                    pass

    def test_inversion_backs_out_the_lock(self):
        from pio_tpu.analysis.runtime import (
            LockOrderInversion, make_lock,
        )

        a = make_lock("lint_t.a")
        b = make_lock("lint_t.b")
        with a:
            with b:
                pass
        with pytest.raises(LockOrderInversion):
            with b:
                with a:
                    pass
        # the raising acquire must not strand either lock
        assert a.acquire(blocking=False)
        a.release()
        assert b.acquire(blocking=False)
        b.release()

    def test_log_mode_records_without_raising(self, monkeypatch):
        from pio_tpu.analysis.runtime import make_lock, sync_debugger

        monkeypatch.setenv("PIO_TPU_DEBUG_SYNC", "log")
        a = make_lock("lint_t.a")
        b = make_lock("lint_t.b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert any("lint_t" in s for s in sync_debugger().inversions())

    def test_consistent_order_is_silent(self):
        from pio_tpu.analysis.runtime import make_lock, sync_debugger

        a = make_lock("lint_t.a")
        b = make_lock("lint_t.b")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert sync_debugger().inversions() == []

    def test_cross_thread_inversion_detected(self):
        from pio_tpu.analysis.runtime import (
            LockOrderInversion, make_lock, sync_debugger,
        )

        a = make_lock("lint_t.a")
        b = make_lock("lint_t.b")

        def ab():
            with a:
                with b:
                    pass

        t = threading.Thread(target=ab)
        t.start()
        t.join()
        with pytest.raises(LockOrderInversion):
            with b:
                with a:
                    pass
        assert len(sync_debugger().inversions()) == 1

    def test_condition_wait_tracks_through_wrapper(self):
        from pio_tpu.analysis.runtime import make_condition, sync_debugger

        cv = make_condition("lint_t.cv")
        done = []

        def waiter():
            with cv:
                while not done:
                    cv.wait(timeout=5.0)

        t = threading.Thread(target=waiter)
        t.start()
        with cv:
            done.append(True)
            cv.notify_all()
        t.join()
        assert sync_debugger().inversions() == []

    def test_rlock_reentry_records_nothing(self):
        from pio_tpu.analysis.runtime import make_rlock, sync_debugger

        r = make_rlock("lint_t.r")
        with r:
            with r:
                pass
        assert sync_debugger().edges() == []

    def test_disarmed_returns_plain_primitives(self, monkeypatch):
        from pio_tpu.analysis.runtime import make_lock, make_rlock

        monkeypatch.setenv("PIO_TPU_DEBUG_SYNC", "0")
        assert type(make_lock("x")) is type(threading.Lock())
        assert type(make_rlock("x")) is type(threading.RLock())
