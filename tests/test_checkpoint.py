"""Checkpoint/resume tests — orbax snapshots of (sharded) train state.

Capability beyond the reference (SURVEY.md §5: "no mid-training
checkpointing"); the contract tested here: interrupting a run and resuming
from the newest snapshot produces the SAME final params as an
uninterrupted run (determinism: full-batch/fixed-slice training).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pio_tpu.parallel.mesh import MeshSpec, build_mesh
from pio_tpu.workflow.checkpoint import CheckpointManager, run_chunked_steps


def _toy_chunk_fn():
    """y = step-count accumulator: state = (step, value)."""
    import functools

    @functools.partial(jax.jit, static_argnums=1)
    def chunk(state, n):
        step0, v = state

        def body(carry, i):
            return carry + 1.0, None

        v, _ = jax.lax.scan(body, v, jnp.arange(n))
        return step0 + n, v

    return chunk


class TestCheckpointManager:
    def test_save_restore_roundtrip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "ck"))
        state = {"a": jnp.arange(4.0), "b": (jnp.int32(7),)}
        assert mgr.restore(template=state) is None
        mgr.save(3, state)
        step, got = mgr.restore(template=state)
        assert step == 3
        np.testing.assert_array_equal(got["a"], state["a"])
        assert int(got["b"][0]) == 7

    def test_keep_prunes_old_steps(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
        state = {"x": jnp.zeros(2)}
        for s in (1, 2, 3):
            mgr.save(s, {"x": jnp.full(2, float(s))})
        assert mgr.latest_step() == 3
        step, got = mgr.restore(template=state)
        assert step == 3
        np.testing.assert_array_equal(got["x"], [3.0, 3.0])

    def test_sharded_state_roundtrip(self, tmp_path):
        mesh = build_mesh(MeshSpec(data=4, model=2))
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(mesh, P("data", "model"))
        arr = jax.device_put(
            np.arange(32, dtype=np.float32).reshape(8, 4), sh
        )
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, {"w": arr})
        _, got = mgr.restore(template={"w": arr})
        np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(arr))
        assert got["w"].sharding == sh


class TestRunChunkedSteps:
    def test_no_checkpoint_single_chunk(self):
        chunk = _toy_chunk_fn()
        step, v = run_chunked_steps((jnp.int32(0), jnp.float32(0)), 10, chunk)
        assert int(step) == 10 and float(v) == 10.0

    def test_chunked_equals_unchunked(self, tmp_path):
        chunk = _toy_chunk_fn()
        mgr = CheckpointManager(str(tmp_path / "ck"))
        step, v = run_chunked_steps(
            (jnp.int32(0), jnp.float32(0)), 10, chunk,
            checkpoint=mgr, checkpoint_every=4,
        )
        assert int(step) == 10 and float(v) == 10.0
        assert mgr.latest_step() == 10

    def test_resume_from_snapshot(self, tmp_path):
        chunk = _toy_chunk_fn()
        mgr = CheckpointManager(str(tmp_path / "ck"))
        # first run: "crashes" after 8 of 10 steps (simulate by stopping)
        run_chunked_steps(
            (jnp.int32(0), jnp.float32(0)), 8, chunk,
            checkpoint=mgr, checkpoint_every=4,
        )
        assert mgr.latest_step() == 8
        # second run resumes at 8 and only does 2 more
        calls = []

        def counting_chunk(state, n):
            calls.append(n)
            return chunk(state, n)

        step, v = run_chunked_steps(
            (jnp.int32(0), jnp.float32(0)), 10, counting_chunk,
            checkpoint=mgr, checkpoint_every=4,
        )
        assert int(step) == 10 and float(v) == 10.0
        assert calls == [2]

    def test_resume_past_total_is_noop(self, tmp_path):
        chunk = _toy_chunk_fn()
        mgr = CheckpointManager(str(tmp_path / "ck"))
        run_chunked_steps(
            (jnp.int32(0), jnp.float32(0)), 10, chunk,
            checkpoint=mgr, checkpoint_every=5,
        )
        step, v = run_chunked_steps(
            (jnp.int32(0), jnp.float32(0)), 10,
            lambda s, n: (_ for _ in ()).throw(AssertionError("ran")),
            checkpoint=mgr, checkpoint_every=5,
        )
        assert int(step) == 10 and float(v) == 10.0


class TestTrainerCheckpointing:
    def test_two_tower_resume_matches_uninterrupted(self, tmp_path, caplog):
        import logging

        from pio_tpu.models.two_tower import TwoTowerConfig, train_two_tower

        rng = np.random.default_rng(0)
        u = rng.integers(0, 12, 400).astype(np.int32)
        i = rng.integers(0, 10, 400).astype(np.int32)
        cfg = TwoTowerConfig(
            embed_dim=8, hidden=16, out_dim=8, steps=20, batch_size=32
        )
        base = train_two_tower(None, u, i, 12, 10, cfg)

        # interrupted at 12/20, then resumed to 20
        mgr = CheckpointManager(str(tmp_path / "tt"))
        train_two_tower(
            None, u, i, 12, 10,
            TwoTowerConfig(
                embed_dim=8, hidden=16, out_dim=8, steps=12, batch_size=32
            ),
            checkpoint=mgr, checkpoint_every=6,
        )
        assert mgr.latest_step() == 12  # saves actually landed
        with caplog.at_level(
            logging.INFO, logger="pio_tpu.workflow.checkpoint"
        ):
            resumed = train_two_tower(
                None, u, i, 12, 10, cfg, checkpoint=mgr, checkpoint_every=6
            )
        # the resume must RESTORE (not vacuously retrain from scratch)
        assert any("restored" in r.message for r in caplog.records)
        assert not any("mismatch" in r.message for r in caplog.records)
        assert mgr.latest_step() == 20
        np.testing.assert_allclose(
            resumed.item_vectors, base.item_vectors, rtol=1e-4, atol=1e-5
        )

    def test_seqrec_resume_matches_uninterrupted(self, tmp_path, caplog):
        import logging

        from pio_tpu.models.seqrec import SeqRecConfig, train_seqrec

        rng = np.random.default_rng(1)
        seqs = np.zeros((8, 8), np.int32)
        for r in range(8):
            seqs[r, :6] = [(r + j) % 5 + 1 for j in range(6)]
        cfg = SeqRecConfig(
            d_model=16, n_heads=2, n_layers=2, ffn=32, max_len=8, steps=20
        )
        base = train_seqrec(None, seqs, 5, cfg)

        mgr = CheckpointManager(str(tmp_path / "sr"))
        train_seqrec(
            None, seqs, 5,
            SeqRecConfig(
                d_model=16, n_heads=2, n_layers=2, ffn=32, max_len=8,
                steps=10,
            ),
            checkpoint=mgr, checkpoint_every=5,
        )
        assert mgr.latest_step() == 10
        with caplog.at_level(
            logging.INFO, logger="pio_tpu.workflow.checkpoint"
        ):
            resumed = train_seqrec(
                None, seqs, 5, cfg, checkpoint=mgr, checkpoint_every=5
            )
        assert any("restored" in r.message for r in caplog.records)
        assert not any("mismatch" in r.message for r in caplog.records)
        for k in ("emb", "pos"):
            np.testing.assert_allclose(
                resumed.params[k], base.params[k], rtol=1e-4, atol=1e-5
            )

    def test_stale_dir_purged_and_reused(self, tmp_path):
        """Fingerprint mismatch wipes the dir; the new run then snapshots
        normally (orbax would otherwise skip steps ≤ the stale latest)."""
        from pio_tpu.models.two_tower import TwoTowerConfig, train_two_tower

        rng = np.random.default_rng(2)
        u = rng.integers(0, 12, 300).astype(np.int32)
        i = rng.integers(0, 10, 300).astype(np.int32)
        cfg = TwoTowerConfig(
            embed_dim=8, hidden=16, out_dim=8, steps=10, batch_size=32
        )
        mgr = CheckpointManager(str(tmp_path / "tt"))
        train_two_tower(None, u, i, 12, 10, cfg,
                        checkpoint=mgr, checkpoint_every=5)
        assert mgr.latest_step() == 10

        # "data changed": different pairs → different fingerprint
        u2 = rng.integers(0, 12, 300).astype(np.int32)
        i2 = rng.integers(0, 10, 300).astype(np.int32)
        train_two_tower(None, u2, i2, 12, 10, cfg,
                        checkpoint=mgr, checkpoint_every=5)
        # stale snapshots were purged and the new run's landed
        assert mgr.latest_step() == 10
        import json

        with open(mgr._fingerprint_path) as f:
            fp2 = json.load(f)["fingerprint"]
        # rerunning with the ORIGINAL data now mismatches the NEW record
        train_two_tower(None, u, i, 12, 10, cfg,
                        checkpoint=mgr, checkpoint_every=5)
        with open(mgr._fingerprint_path) as f:
            assert json.load(f)["fingerprint"] != fp2
