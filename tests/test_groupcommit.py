"""Leader/follower group commit (pio_tpu/storage/groupcommit.py) and its
wiring into the sqlite + native-eventlog single-insert paths."""

import threading

import pytest

from pio_tpu.storage.groupcommit import GroupCommitter


class TestGroupCommitter:
    def test_serial_submits_flush_individually(self):
        batches = []

        def flush(ps):
            batches.append(list(ps))
            return [p * 10 for p in ps]

        gc = GroupCommitter(flush)
        assert gc.submit(1) == 10
        assert gc.submit(2) == 20
        # serial traffic: no artificial batching, no waiting
        assert batches == [[1], [2]]

    def test_concurrent_submits_coalesce(self):
        """Block the first leader mid-flush; everyone who arrives
        meanwhile must ride ONE follow-up flush."""
        release = threading.Event()
        in_flush = threading.Event()
        batches = []

        def flush(ps):
            batches.append(list(ps))
            if len(batches) == 1:
                in_flush.set()
                release.wait(10)
            return list(ps)

        gc = GroupCommitter(flush)
        t0 = threading.Thread(target=lambda: gc.submit(0))
        t0.start()
        in_flush.wait(10)
        followers = [
            threading.Thread(target=lambda i=i: gc.submit(i))
            for i in range(1, 9)
        ]
        for t in followers:
            t.start()
        # wait until every follower is queued, then release the leader
        for _ in range(1000):
            with gc._qlock:
                if len(gc._q) == 8:
                    break
            threading.Event().wait(0.005)
        release.set()
        t0.join(10)
        for t in followers:
            t.join(10)
        assert batches[0] == [0]
        # all 8 followers coalesced into one (or at most two) flushes
        assert len(batches) <= 3
        assert sorted(p for b in batches[1:] for p in b) == list(range(1, 9))

    def test_poisoned_payload_isolated(self):
        """A failing payload in a batch must fail ONLY its own submit;
        batch-mates retry individually and succeed."""
        release = threading.Event()
        in_flush = threading.Event()
        calls = []

        def flush(ps):
            calls.append(list(ps))
            if len(calls) == 1:
                in_flush.set()
                release.wait(10)
            if any(p == "bad" for p in ps):
                raise ValueError("poison")
            return list(ps)

        gc = GroupCommitter(flush)
        results = {}

        def run(p):
            try:
                results[p] = ("ok", gc.submit(p))
            except ValueError as e:
                results[p] = ("err", str(e))

        t0 = threading.Thread(target=run, args=("warm",))
        t0.start()
        in_flush.wait(10)
        ts = [threading.Thread(target=run, args=(p,))
              for p in ("a", "bad", "b")]
        for t in ts:
            t.start()
        for _ in range(1000):
            with gc._qlock:
                if len(gc._q) == 3:
                    break
            threading.Event().wait(0.005)
        release.set()
        t0.join(10)
        for t in ts:
            t.join(10)
        assert results["a"] == ("ok", "a")
        assert results["b"] == ("ok", "b")
        assert results["bad"] == ("err", "poison")


def test_partial_flush_outcomes_not_retried():
    """A flush that raises PartialFlushOutcome (non-atomic backend, e.g.
    multi-file appends) must have its per-payload outcomes assigned
    verbatim — NO blind retry, which would duplicate landed payloads."""
    from pio_tpu.storage.groupcommit import PartialFlushOutcome

    release = threading.Event()
    in_flush = threading.Event()
    calls = []

    def flush(ps):
        calls.append(list(ps))
        if len(calls) == 1:
            in_flush.set()
            release.wait(10)
            return list(ps)
        # mixed batch: 'x' landed, 'y' failed — report, don't raise raw
        raise PartialFlushOutcome(
            [p if p != "y" else ValueError("io error") for p in ps]
        )

    gc = GroupCommitter(flush)
    results = {}

    def run(p):
        try:
            results[p] = ("ok", gc.submit(p))
        except ValueError as e:
            results[p] = ("err", str(e))

    t0 = threading.Thread(target=run, args=("warm",))
    t0.start()
    in_flush.wait(10)
    ts = [threading.Thread(target=run, args=(p,)) for p in ("x", "y")]
    for t in ts:
        t.start()
    for _ in range(1000):
        with gc._qlock:
            if len(gc._q) == 2:
                break
        threading.Event().wait(0.005)
    release.set()
    t0.join(10)
    for t in ts:
        t.join(10)
    assert results["x"] == ("ok", "x")
    assert results["y"] == ("err", "io error")
    # exactly 2 flushes: warm + the partial batch; NO per-payload retries
    assert len(calls) == 2, calls


def test_short_flush_results_fail_loudly():
    """A flush returning fewer results than payloads is a protocol
    violation: zip would silently mark the tail done with result=None
    (success with nothing written). Every submitter must get an error —
    and NO solo retry, since we can't tell which payloads landed."""
    from pio_tpu.storage.groupcommit import FlushProtocolError

    calls = []

    def flush(ps):
        calls.append(list(ps))
        return list(ps)[:-1]  # drops the last result

    gc = GroupCommitter(flush)
    with pytest.raises(FlushProtocolError):
        gc.submit("a")
    assert len(calls) == 1, calls  # no blind retry


def test_generator_flush_results_accepted():
    """A flush returning a lazy iterable is legal — the length guard
    must materialize it rather than raise TypeError on len() (which the
    generic handler would solo-retry, DUPLICATING the landed batch)."""
    calls = []

    def flush(ps):
        calls.append(list(ps))
        return (p for p in ps)

    gc = GroupCommitter(flush)
    assert gc.submit("a") == "a"
    assert calls == [["a"]]  # exactly one flush, no retry


def test_short_partial_outcomes_fail_loudly():
    from pio_tpu.storage.groupcommit import (
        FlushProtocolError,
        PartialFlushOutcome,
    )

    def flush(ps):
        raise PartialFlushOutcome([])  # fewer outcomes than payloads

    gc = GroupCommitter(flush)
    with pytest.raises(FlushProtocolError):
        gc.submit("a")


@pytest.mark.parametrize("backend", ["sqlite", "eventlog"])
def test_concurrent_single_inserts_land(tmp_home, monkeypatch, backend):
    """16 threads hammering the single-insert path: every event lands,
    ids are unique, and the store reads them all back."""
    from pio_tpu.data.event import Event
    from pio_tpu.storage import Storage

    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "GC")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_GC_TYPE", backend)
    monkeypatch.setenv(
        "PIO_STORAGE_SOURCES_GC_PATH", str(tmp_home / f"gc_{backend}")
    )
    Storage.reset()
    try:
        ids = []
        lock = threading.Lock()

        def worker(t):
            le = Storage.get_levents()
            got = []
            for n in range(25):
                eid = le.insert(
                    Event("rate", "user", f"u{t}", "item", f"i{n}",
                          properties={"rating": float(n % 5) + 1}),
                    7,
                )
                got.append(eid)
            with lock:
                ids.extend(got)

        threads = [
            threading.Thread(target=worker, args=(t,)) for t in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len(ids) == 400 and len(set(ids)) == 400
        events = Storage.get_levents().find(7, limit=None)
        assert len(events) == 400
    finally:
        Storage.reset()
