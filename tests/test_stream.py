"""Streamed training feed tests — executor units + streamed-vs-staged parity.

The executor (pio_tpu/parallel/stream.py) is the ONE streaming
discipline: ALS wire chunks and the two-tower/seqrec batch-span feeds
all ride it. Parity is the load-bearing guarantee — streamed and staged
runs with the same seed must produce **bit-identical** params
(np.array_equal, not allclose), because the spans replay exactly the
staged batch schedule.

Run on the simulated 8-device CPU mesh (tests/conftest.py).
"""

import numpy as np
import pytest

from pio_tpu.parallel.mesh import MeshSpec, build_mesh
from pio_tpu.parallel.partition import DeviceBudgetExceeded
from pio_tpu.parallel.stream import (
    epoch_spans,
    n_stream_chunks,
    record_overlap_ratio,
    span_bounds,
    stream_feed,
)


# ------------------------------------------------------------- chunk sizing
class TestChunkSizing:
    def test_threshold_and_cap(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_TEST_STREAM_MB", "1")
        mb = 2 ** 20
        assert n_stream_chunks(3 * mb, "PIO_TPU_TEST_STREAM_MB") == 3
        assert n_stream_chunks(mb // 2, "PIO_TPU_TEST_STREAM_MB") == 1
        # capped
        assert n_stream_chunks(100 * mb, "PIO_TPU_TEST_STREAM_MB") == 8
        assert n_stream_chunks(
            100 * mb, "PIO_TPU_TEST_STREAM_MB", cap=16
        ) == 16

    def test_knob_off_means_one_chunk(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_TEST_STREAM_MB", "0")
        assert n_stream_chunks(10 ** 9, "PIO_TPU_TEST_STREAM_MB") == 1

    def test_numutil_delegates(self, monkeypatch):
        from pio_tpu.utils.numutil import n_stream_chunks as via_numutil

        monkeypatch.setenv("PIO_TPU_TEST_STREAM_MB", "2")
        for nb in (0, 2 ** 20, 5 * 2 ** 20, 64 * 2 ** 20):
            assert via_numutil(nb, "PIO_TPU_TEST_STREAM_MB") == \
                n_stream_chunks(nb, "PIO_TPU_TEST_STREAM_MB")


class TestSpans:
    def test_span_bounds_cover_epoch(self):
        assert span_bounds(10, 3) == [0, 3, 6, 10]
        assert span_bounds(4, 8) == [0, 1, 2, 3, 4]  # clamped to n_batches
        assert span_bounds(6, 1) == [0, 6]

    def test_epoch_spans_replay_staged_schedule(self):
        # step s consumes batch s % n_batches; spans must cover exactly
        # the staged sequence, wrapping across epoch passes
        bounds = span_bounds(10, 3)
        work = epoch_spans(8, 7, 10, bounds)
        assert work == [(8, 10), (0, 3), (3, 5)]
        batches = [b for b0, b1 in work for b in range(b0, b1)]
        assert batches == [(8 + k) % 10 for k in range(7)]

    def test_epoch_spans_arbitrary_offsets(self):
        for n_batches, n_stream in ((7, 3), (16, 4), (5, 5), (9, 1)):
            bounds = span_bounds(n_batches, n_stream)
            for step0 in (0, 1, n_batches - 1, 2 * n_batches + 3):
                for n in (1, n_batches, 2 * n_batches + 1):
                    work = epoch_spans(step0, n, n_batches, bounds)
                    replay = [
                        b for b0, b1 in work for b in range(b0, b1)
                    ]
                    assert replay == [
                        (step0 + k) % n_batches for k in range(n)
                    ]


# ------------------------------------------------------------ the executor
class TestStreamFeed:
    def _run(self, lookahead=0, stats=None, finalize=None):
        import jax.numpy as jnp

        chunks = [np.arange(4, dtype=np.float32) + 10 * c
                  for c in range(3)]
        return stream_feed(
            list(range(3)),
            encode=lambda c: chunks[c],
            dispatch=lambda carry, dev, i: carry + jnp.sum(dev),
            init_carry=lambda: jnp.float32(0.0),
            finalize=finalize,
            lookahead=lookahead,
            stats=stats,
        )

    def test_modes_agree(self):
        want = float(self._run(lookahead=0))
        assert float(self._run(lookahead=1)) == want
        assert float(self._run(lookahead=2)) == want
        assert float(self._run(stats={})) == want

    def test_stats_keys_and_accumulation(self):
        stats = {}
        self._run(stats=stats)
        for key in ("encode_s", "h2d_s", "device_s", "h2d_bytes"):
            assert key in stats, key
        assert stats["h2d_bytes"] == 3 * 4 * 4
        first = stats["h2d_bytes"]
        self._run(stats=stats)  # phases ACCUMULATE (ALS multi-call runs)
        assert stats["h2d_bytes"] == 2 * first

    def test_h2d_counter_increments(self):
        from pio_tpu.parallel.stream import _H2D_BYTES

        before = _H2D_BYTES.value()
        self._run(lookahead=2)
        assert _H2D_BYTES.value() == before + 3 * 4 * 4

    def test_finalize_retains_device_chunks(self):
        import jax.numpy as jnp

        for kwargs in ({"lookahead": 0}, {"lookahead": 2}, {"stats": {}}):
            carry, devs = self._run(
                finalize=lambda c, d: (c, d), **kwargs
            )
            assert len(devs) == 3
            assert float(jnp.sum(devs[2])) == float(np.sum(
                np.arange(4, dtype=np.float32) + 20
            ))

    def test_put_extra_fires_once_after_chunk_puts(self):
        calls = []

        def run(**kwargs):
            calls.clear()
            stream_feed(
                list(range(3)),
                encode=lambda c: np.zeros(2, np.float32),
                dispatch=lambda carry, dev, i: carry + 1,
                init_carry=lambda: 0,
                put_extra=lambda: calls.append("extra"),
                **kwargs,
            )
            assert calls == ["extra"]

        run(stats={})
        run(lookahead=0)
        run(lookahead=1)  # lookahead window never reaches n mid-loop

    def test_custom_put_receives_index(self):
        seen = []

        def put(host, i):
            seen.append(i)
            return host

        stream_feed(
            list(range(4)),
            encode=lambda c: np.zeros(1, np.float32),
            put=put,
            dispatch=lambda carry, dev, i: carry,
            init_carry=lambda: 0,
            lookahead=2,
        )
        assert seen == [0, 1, 2, 3]

    def test_failpoints_fire_per_phase(self):
        from pio_tpu.faults import registry as faults
        from pio_tpu.faults.registry import FaultInjected

        for point in ("stream.encode", "stream.put", "stream.dispatch"):
            faults.install(f"{point}=error")
            try:
                with pytest.raises(FaultInjected):
                    self._run(lookahead=1)
            finally:
                faults.uninstall()


class TestOverlapRatio:
    def test_ratio_math_and_gauge(self):
        from pio_tpu.parallel.stream import _OVERLAP

        # perfect overlap: wall == max(h2d, device)
        assert record_overlap_ratio(2.0, 3.0, 3.0) == 1.0
        assert _OVERLAP.value() == 1.0
        # no overlap: wall == h2d + device
        assert record_overlap_ratio(2.0, 3.0, 5.0) == 0.0
        # half the smaller phase hidden
        assert record_overlap_ratio(2.0, 3.0, 4.0) == 0.5
        # degenerate phases clamp instead of dividing by zero
        assert record_overlap_ratio(0.0, 3.0, 3.0) == 0.0


# -------------------------------------------------- two-tower streamed feed
def _pairs(n_users=24, n_items=20, n_pairs=1500, groups=4, seed=0):
    rng = np.random.default_rng(seed)
    per = n_items // groups
    u = rng.integers(0, n_users, n_pairs).astype(np.int32)
    i = ((u % groups) * per + rng.integers(0, per, n_pairs)).astype(np.int32)
    return u, i


class TestTwoTowerStreamed:
    def _train(self, mesh, stream, stats=None, steps=40, **over):
        from pio_tpu.models.two_tower import TwoTowerConfig, train_two_tower

        u, i = _pairs()
        cfg = TwoTowerConfig(
            embed_dim=16, hidden=32, out_dim=16, steps=steps,
            batch_size=64, stream=stream, **over,
        )
        return train_two_tower(mesh, u, i, 24, 20, cfg, stats=stats)

    @pytest.mark.parametrize(
        "spec", [None, MeshSpec(data=4, model=2)], ids=["single", "dp4-tp2"]
    )
    def test_streamed_matches_staged_bitexact(self, spec):
        mesh = None if spec is None else build_mesh(spec)
        stats = {}
        staged = self._train(mesh, "off")
        streamed = self._train(mesh, "on", stats=stats)
        np.testing.assert_array_equal(
            staged.user_vectors, streamed.user_vectors
        )
        np.testing.assert_array_equal(
            staged.item_vectors, streamed.item_vectors
        )
        assert stats["n_stream"] >= 2
        assert stats["h2d_bytes"] > 0

    def test_auto_streams_under_tight_budget(self, monkeypatch):
        # budget holds the sharded params but NOT the staged epoch next
        # to them → auto falls back to the streamed feed, same result
        mesh = build_mesh(MeshSpec(data=4, model=2))
        staged = self._train(mesh, "off")
        monkeypatch.setenv("PIO_TPU_DEVICE_BUDGET_BYTES", "15000")
        stats = {}
        auto = self._train(mesh, "auto", stats=stats)
        assert stats["n_stream"] >= 2  # it really streamed
        np.testing.assert_array_equal(
            staged.user_vectors, auto.user_vectors
        )

    def test_single_chip_placement_raises(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_DEVICE_BUDGET_BYTES", "4096")
        with pytest.raises(DeviceBudgetExceeded, match="single-chip"):
            self._train(None, "auto", steps=1)

    def test_stream_validation(self):
        with pytest.raises(ValueError, match="stream"):
            self._train(None, "sideways", steps=1)


# ----------------------------------------------------- seqrec streamed feed
def _histories(n=24, t=12, vocab=40, seed=0):
    rng = np.random.default_rng(seed)
    seqs = rng.integers(1, vocab, size=(n, t), dtype=np.int32)
    lengths = rng.integers(3, t + 1, size=n)
    for r in range(n):
        seqs[r, lengths[r]:] = 0
    return seqs


class TestSeqRecStreamed:
    def _train(self, mesh, stream, stats=None, **over):
        from pio_tpu.models.seqrec import SeqRecConfig, train_seqrec

        kw = dict(
            d_model=8, n_heads=2, n_layers=2, ffn=16, max_len=16,
            steps=6, seed=3, batch_size=8, stream=stream,
        )
        kw.update(over)
        cfg = SeqRecConfig(**kw)
        return train_seqrec(mesh, _histories(), 40, cfg, stats=stats)

    def test_streamed_matches_staged_on_4_axis_mesh(self):
        import jax

        # every parallelism axis live: dp × pp (pipeline_apply) × sp
        # (ring attention) × tp/ep — the ISSUE's full-mesh claim
        mesh = build_mesh(MeshSpec(data=1, pipe=2, seq=2, model=2))
        stats = {}
        staged = self._train(mesh, "off")
        streamed = self._train(mesh, "on", stats=stats)
        for a, b in zip(
            jax.tree_util.tree_leaves(staged.params),
            jax.tree_util.tree_leaves(streamed.params),
        ):
            np.testing.assert_array_equal(a, b)
        assert stats["n_stream"] >= 2
        assert stats["h2d_bytes"] > 0

    def test_minibatch_trains_single_device(self):
        import jax

        staged = self._train(None, "off")
        streamed = self._train(None, "on")
        for a, b in zip(
            jax.tree_util.tree_leaves(staged.params),
            jax.tree_util.tree_leaves(streamed.params),
        ):
            np.testing.assert_array_equal(a, b)

    def test_full_batch_over_budget_raises_with_advice(self, monkeypatch):
        # params fit; params + staged epoch do not; batch_size=0 cannot
        # stream (each step needs the whole dataset) → honest raise
        monkeypatch.setenv("PIO_TPU_DEVICE_BUDGET_BYTES", "49152")
        with pytest.raises(DeviceBudgetExceeded, match="batch_size"):
            from pio_tpu.models.seqrec import SeqRecConfig, train_seqrec

            train_seqrec(
                None, _histories(n=512, t=16), 40,
                SeqRecConfig(d_model=8, n_heads=2, n_layers=2, ffn=16,
                             max_len=16, steps=1),
            )

    def test_stream_on_needs_batch_size(self):
        with pytest.raises(ValueError, match="batch_size"):
            self._train(None, "on", batch_size=0)


# ------------------------------------- giant-vocab sharded persist + reshard
class TestGiantVocabPersist:
    @pytest.fixture(autouse=True)
    def storage(self, tmp_home):
        from pio_tpu.storage import Storage

        Storage.reset()
        yield Storage.get_model_data_models()
        Storage.reset()

    def test_over_budget_table_trains_sharded_and_reshards(
        self, storage, monkeypatch
    ):
        """The ISSUE's e2e shape at test scale: a vocab whose table
        exceeds the single-chip budget trains mesh-sharded, persists as
        shard records, and reassembles on 4 and 1 devices bit-exactly."""
        from pio_tpu.data.bimap import BiMap
        from pio_tpu.models.two_tower import TwoTowerConfig, train_two_tower
        from pio_tpu.templates.twotower import TwoTowerEngineModel
        from pio_tpu.workflow import shard_store

        n_users, n_items = 4096, 64
        monkeypatch.setenv("PIO_TPU_DEVICE_BUDGET_BYTES", "200000")
        u, i = _pairs(n_users, n_items, n_pairs=2000, groups=4, seed=2)
        cfg = TwoTowerConfig(
            embed_dim=16, hidden=32, out_dim=16, steps=10, batch_size=256
        )
        # single-chip placement is over budget (the user-tower table
        # alone is 4096×16×4 B); the mesh shards it under budget
        with pytest.raises(DeviceBudgetExceeded):
            train_two_tower(None, u, i, n_users, n_items, cfg)
        mesh = build_mesh(MeshSpec(data=4, model=2))
        model = train_two_tower(mesh, u, i, n_users, n_items, cfg)

        em = TwoTowerEngineModel(
            model,
            BiMap({f"u{k}": k for k in range(n_users)}),
            BiMap({f"i{k}": k for k in range(n_items)}),
        )
        stripped = shard_store.save_sharded(
            storage, "inst-giant", [em], n_shards=8, mesh_shape=[8]
        )
        assert isinstance(
            stripped[0].model.user_vectors, shard_store.ShardPlaceholder
        )
        for n_devices in (4, 1):
            back = shard_store.restore_sharded(
                storage, "inst-giant", list(stripped), n_devices=n_devices
            )
            np.testing.assert_array_equal(
                back[0].model.user_vectors, model.user_vectors
            )
            np.testing.assert_array_equal(
                back[0].model.item_vectors, model.item_vectors
            )
