"""SelfCleaningDataSource compaction tests (reference core/SelfCleaningDataSource).

Key invariant: a compacted store aggregates to the SAME PropertyMaps as the
original stream — compression must be semantically invisible to serving.
"""

import datetime as dt

import pytest

from pio_tpu.data import (
    Event,
    EventWindow,
    aggregate_properties,
    clean_events,
    parse_duration,
)
from pio_tpu.data.cleaning import SelfCleaningDataSource
from pio_tpu.storage import App, Storage

T0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)


def _t(minutes):
    return T0 + dt.timedelta(minutes=minutes)


class TestParseDuration:
    @pytest.mark.parametrize(
        "text,seconds",
        [
            ("30 days", 30 * 86400),
            ("12h", 12 * 3600),
            ("90 minutes", 5400),
            ("1 week", 604800),
            ("45s", 45),
        ],
    )
    def test_ok(self, text, seconds):
        assert parse_duration(text).total_seconds() == seconds

    def test_bad(self):
        with pytest.raises(ValueError):
            parse_duration("fortnight")


class TestCleanEvents:
    def test_duration_drops_old_plain_events(self):
        events = [
            Event("view", "user", "u1", "item", "i1", event_time=_t(0)),
            Event("view", "user", "u1", "item", "i2", event_time=_t(100)),
        ]
        out = clean_events(
            events,
            EventWindow(duration="30 minutes"),
            now=_t(120),
        )
        assert [e.target_entity_id for e in out] == ["i2"]

    def test_duration_does_not_touch_special_events(self):
        events = [
            Event("$set", "user", "u1", properties={"a": 1},
                  event_time=_t(0)),
            Event("view", "user", "u1", "item", "i1", event_time=_t(0)),
        ]
        out = clean_events(
            events, EventWindow(duration="1 minutes"), now=_t(120)
        )
        assert [e.event for e in out] == ["$set"]

    def test_compress_folds_set_chain(self):
        events = [
            Event("$set", "user", "u1", properties={"a": 1, "b": 1},
                  event_time=_t(0)),
            Event("$set", "user", "u1", properties={"a": 2},
                  event_time=_t(1)),
            Event("$unset", "user", "u1", properties={"b": None},
                  event_time=_t(2)),
        ]
        out = clean_events(events, EventWindow(compress_properties=True))
        assert len(out) == 1
        e = out[0]
        assert e.event == "$set"
        assert e.properties.to_dict() == {"a": 2}
        assert e.event_time == _t(2)  # last_updated watermark preserved

    def test_compress_drops_deleted_entities(self):
        events = [
            Event("$set", "user", "u1", properties={"a": 1},
                  event_time=_t(0)),
            Event("$delete", "user", "u1", event_time=_t(1)),
        ]
        out = clean_events(events, EventWindow(compress_properties=True))
        assert out == []

    def test_compress_preserves_aggregation_semantics(self):
        events = [
            Event("$set", "user", "u1", properties={"a": 1, "b": 2},
                  event_time=_t(0)),
            Event("$unset", "user", "u1", properties={"a": None},
                  event_time=_t(1)),
            Event("$set", "user", "u2", properties={"x": "y"},
                  event_time=_t(2)),
            Event("$delete", "user", "u3", event_time=_t(3)),
        ]
        before = aggregate_properties(events)
        after = aggregate_properties(
            clean_events(events, EventWindow(compress_properties=True))
        )
        assert {k: v.to_dict() for k, v in before.items()} == {
            k: v.to_dict() for k, v in after.items()
        }

    def test_remove_duplicates_list_properties(self):
        # list/dict-valued properties must hash via the canonical JSON key
        e = Event("$set", "item", "i1",
                  properties={"categories": ["a", "b"]}, event_time=_t(0))
        out = clean_events([e, e], EventWindow(remove_duplicates=True))
        assert len(out) == 1

    def test_remove_duplicates(self):
        e = dict(event_time=_t(0))
        events = [
            Event("view", "user", "u1", "item", "i1", **e),
            Event("view", "user", "u1", "item", "i1", **e),
            Event("view", "user", "u1", "item", "i2", **e),
        ]
        out = clean_events(events, EventWindow(remove_duplicates=True))
        assert len(out) == 2

    def test_no_window_flags_is_identity(self):
        events = [
            Event("view", "user", "u1", "item", "i1", event_time=_t(1)),
            Event("$set", "user", "u1", properties={"a": 1},
                  event_time=_t(0)),
        ]
        out = clean_events(events, EventWindow())
        assert [e.event for e in out] == ["$set", "view"]  # time-sorted


class TestSelfCleaningDataSource:
    def test_cleans_persisted_store(self, tmp_home):
        Storage.reset()
        try:
            app_id = Storage.get_meta_data_apps().insert(App(0, "clean-test"))
            le = Storage.get_levents()
            le.insert(Event("$set", "user", "u1", properties={"a": 1},
                            event_time=_t(0)), app_id)
            le.insert(Event("$set", "user", "u1", properties={"a": 2},
                            event_time=_t(1)), app_id)
            le.insert(Event("view", "user", "u1", "item", "i1",
                            event_time=_t(0)), app_id)
            le.insert(Event("view", "user", "u1", "item", "i2",
                            event_time=_t(100)), app_id)

            ds = SelfCleaningDataSource()
            ds.event_window = EventWindow(
                duration="30 minutes", compress_properties=True
            )
            removed = ds.clean_persisted_events(app_id, now=_t(120))
            assert removed == 2  # old view + one folded $set

            left = Storage.get_pevents().find(app_id)
            by_event = sorted(e.event for e in left)
            assert by_event == ["$set", "view"]
            props = Storage.get_pevents().aggregate_properties(
                app_id, entity_type="user"
            )
            assert props["u1"].to_dict() == {"a": 2}
        finally:
            Storage.reset()

    def test_no_window_noop(self, tmp_home):
        Storage.reset()
        try:
            app_id = Storage.get_meta_data_apps().insert(App(0, "clean-test"))
            Storage.get_levents().insert(
                Event("view", "user", "u1", "item", "i1", event_time=_t(0)),
                app_id,
            )
            assert SelfCleaningDataSource().clean_persisted_events(app_id) == 0
            assert len(Storage.get_pevents().find(app_id)) == 1
        finally:
            Storage.reset()
