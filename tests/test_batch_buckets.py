"""Shape-bucket execution cache + cross-worker batch lane (ISSUE 7).

Covers the bucket policy at boundary sizes (B=1, B=bucket, B=bucket+1),
warmed-generation eviction on model hot-swap, the retrace counter
staying flat across steady-state dispatches, and the lane's SPSC slot
protocol driven in-process with ``threading.Event`` doorbells.
"""

import threading
import time

import pytest

import pio_tpu.templates  # noqa: F401
from pio_tpu.obs.metrics import monotonic_s
from pio_tpu.server.batchlane import (
    BatchLaneSegment,
    LaneClient,
    LaneDrainer,
    LaneFallback,
    STATUS_ERROR,
)
from pio_tpu.server.bucketcache import (
    BucketExecutionCache,
    buckets_from_env,
    dispatch_bucketed,
)

# --------------------------------------------------------------- policy


class TestBucketPolicy:
    def test_bucket_for_boundaries(self):
        c = BucketExecutionCache(buckets=(1, 2, 4, 8))
        assert c.bucket_for(1) == 1          # B == smallest bucket
        assert c.bucket_for(2) == 2          # B == bucket
        assert c.bucket_for(3) == 4          # B == bucket + 1 → next up
        assert c.bucket_for(8) == 8          # B == max bucket
        assert c.max_bucket == 8

    def test_pad_exact_bucket_no_copy(self):
        c = BucketExecutionCache(buckets=(1, 2, 4))
        qs = ["a", "b"]
        padded, bucket = c.pad(qs)
        assert bucket == 2 and padded is qs  # no padding allocation

    def test_pad_replicates_last(self):
        c = BucketExecutionCache(buckets=(1, 2, 4))
        padded, bucket = c.pad(["a", "b", "c"])  # bucket+1 → pad to 4
        assert bucket == 4
        assert padded == ["a", "b", "c", "c"]

    def test_pad_single(self):
        c = BucketExecutionCache(buckets=(1, 2, 4))
        padded, bucket = c.pad(["a"])
        assert bucket == 1 and padded == ["a"]

    def test_chunks_oversize(self):
        c = BucketExecutionCache(buckets=(1, 2, 4))
        assert c.chunks(4) == [4]
        assert c.chunks(5) == [4, 1]
        assert c.chunks(11) == [4, 4, 3]

    def test_env_ladder(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_BATCH_BUCKETS", "8,1,4")
        assert buckets_from_env() == (1, 4, 8)

    def test_env_ladder_malformed_falls_back(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_BATCH_BUCKETS", "2,zap")
        assert buckets_from_env() == (1, 2, 4, 8, 16, 32)
        monkeypatch.setenv("PIO_TPU_BATCH_BUCKETS", "0,2")
        assert buckets_from_env() == (1, 2, 4, 8, 16, 32)


class TestWarmedGeneration:
    def test_cold_dispatch_counts_retrace_once(self):
        c = BucketExecutionCache(buckets=(1, 2, 4))
        assert c.note_dispatch(2) is True     # cold → retrace
        assert c.retraces == 1
        assert c.note_dispatch(2) is False    # now warmed
        assert c.retraces == 1

    def test_install_marks_warm(self):
        c = BucketExecutionCache(buckets=(1, 2, 4))
        c.install([1, 2, 4])
        assert c.warmed == {1, 2, 4} and c.generation == 1
        assert c.note_dispatch(4) is False and c.retraces == 0

    def test_hot_swap_evicts(self):
        c = BucketExecutionCache(buckets=(1, 2, 4))
        c.install([1, 2, 4])
        c.install([1, 2])                     # new generation
        assert c.evictions == 3               # old entries evicted
        assert c.generation == 2
        assert c.note_dispatch(4) is True     # 4 is cold again

    def test_retrace_flat_over_steady_state(self):
        c = BucketExecutionCache(buckets=(1, 2, 4))
        c.install([1, 2, 4])
        calls = []

        def run(padded):
            calls.append(len(padded))
            return [q.upper() for q in padded]

        for i in range(100):
            n = (i % 4) + 1                   # B = 1..4 forever
            results, fresh = dispatch_bucketed(c, ["q"] * n, run)
            assert len(results) == n and not fresh
        assert c.retraces == 0                # flat across all 100
        assert set(calls) <= {1, 2, 4}        # only bucket shapes ran

    def test_dispatch_slices_padding(self):
        c = BucketExecutionCache(buckets=(2, 4))
        results, fresh = dispatch_bucketed(
            c, ["a", "b", "c"], lambda qs: [q + "!" for q in qs]
        )
        assert results == ["a!", "b!", "c!"]
        assert fresh is True                  # nothing installed → cold

    def test_dispatch_chunks_oversize(self):
        c = BucketExecutionCache(buckets=(1, 2))
        c.install([1, 2])
        seen = []
        results, fresh = dispatch_bucketed(
            c, list("abcde"), lambda qs: (seen.append(len(qs)), qs)[1]
        )
        assert results == list("abcde") and not fresh
        assert seen == [2, 2, 1]              # max-bucket chunking

    def test_on_dispatch_hook(self):
        c = BucketExecutionCache(buckets=(2, 4))
        c.install([2])
        events = []
        dispatch_bucketed(
            c, ["a", "b", "c"], lambda qs: qs,
            on_dispatch=lambda n, b, fresh: events.append((n, b, fresh)),
        )
        assert events == [(3, 4, True)]


# ------------------------------------------------------------ batch lane


def _lane(tmp_path, n_workers=2, **kw):
    path = str(tmp_path / "lane.shm")
    seg = BatchLaneSegment.create(path, n_workers, **kw)
    doorbell = threading.Event()
    resp = [threading.Event() for _ in range(n_workers)]
    return seg, doorbell, resp


class TestBatchLane:
    def test_open_rejects_garbage(self, tmp_path):
        p = tmp_path / "junk.shm"
        p.write_bytes(b"NOTALANE" + b"\0" * 64)
        with pytest.raises(ValueError):
            BatchLaneSegment.open(str(p))

    def test_roundtrip_aggregates_across_workers(self, tmp_path):
        seg, doorbell, resp = _lane(tmp_path, n_workers=3)
        batches = []

        def dispatch(bodies):
            batches.append(len(bodies))
            return [{"echo": b["user"]} for b in bodies]

        drainer = LaneDrainer(seg, dispatch, doorbell, resp)
        clients = [
            LaneClient(seg, w, doorbell, resp[w], timeout_s=5.0)
            for w in (1, 2)
        ]
        out = {}

        def submit(w):
            out[w] = clients[w - 1].submit({"user": f"u{w}"})

        threads = [
            threading.Thread(target=submit, args=(w,)) for w in (1, 2)
        ]
        for t in threads:
            t.start()
        # both requests posted before one manual drain → ONE cross-worker
        # batch
        deadline = monotonic_s() + 5.0
        while seg.pending_depth() < 2 and monotonic_s() < deadline:
            time.sleep(0.002)
        assert drainer.drain_once() == 2
        for t in threads:
            t.join(timeout=5.0)
        assert out == {1: {"echo": "u1"}, 2: {"echo": "u2"}}
        assert batches == [2]
        assert seg.pending_depth() == 0

    def test_drainer_thread_serves(self, tmp_path):
        seg, doorbell, resp = _lane(tmp_path)
        drainer = LaneDrainer(
            seg, lambda bodies: [{"n": len(bodies)} for _ in bodies],
            doorbell, resp, poll_s=0.01,
        ).start()
        try:
            client = LaneClient(seg, 1, doorbell, resp[1], timeout_s=5.0)
            assert client.submit({"q": 1}) == {"n": 1}
            assert client.submit({"q": 2}) == {"n": 1}
        finally:
            drainer.stop()
        assert drainer.drained == 2

    def test_oversize_body_falls_back(self, tmp_path):
        seg, doorbell, resp = _lane(tmp_path, payload_bytes=64)
        client = LaneClient(seg, 0, doorbell, resp[0], timeout_s=0.2)
        with pytest.raises(LaneFallback) as ei:
            client.submit({"blob": "x" * 200})
        assert ei.value.reason == "oversize"

    def test_full_stripe_falls_back(self, tmp_path):
        seg, doorbell, resp = _lane(tmp_path, slots_per_worker=2)
        client = LaneClient(seg, 0, doorbell, resp[0], timeout_s=0.05)
        # no drainer: both slots end up in-flight (timeout), third is full
        for _ in range(2):
            with pytest.raises(LaneFallback) as ei:
                client.submit({"q": 1})
            assert ei.value.reason == "timeout"
        with pytest.raises(LaneFallback) as ei:
            client.submit({"q": 1})
        assert ei.value.reason == "full"

    def test_timed_out_slot_reclaimed_after_answer(self, tmp_path):
        seg, doorbell, resp = _lane(tmp_path, slots_per_worker=1)
        client = LaneClient(seg, 0, doorbell, resp[0], timeout_s=0.05)
        with pytest.raises(LaneFallback):
            client.submit({"q": "zombie"})
        # late drainer answers the abandoned slot...
        drainer = LaneDrainer(
            seg, lambda bodies: [{"late": True}] * len(bodies),
            doorbell, resp,
        )
        assert drainer.drain_once() == 1
        # ...after which the stripe is usable again
        drainer.start()
        try:
            assert client.submit(
                {"q": "fresh"}, timeout_s=5.0
            ) == {"late": True}
        finally:
            drainer.stop()

    def test_dispatch_error_reports_remote_error(self, tmp_path):
        seg, doorbell, resp = _lane(tmp_path)

        def boom(bodies):
            raise RuntimeError("model died")

        drainer = LaneDrainer(seg, boom, doorbell, resp, poll_s=0.01)
        drainer.start()
        try:
            client = LaneClient(seg, 1, doorbell, resp[1], timeout_s=5.0)
            with pytest.raises(LaneFallback) as ei:
                client.submit({"q": 1})
            assert ei.value.reason == "remote_error"
        finally:
            drainer.stop()

    def test_undecodable_request_errors_only_that_slot(self, tmp_path):
        seg, doorbell, resp = _lane(tmp_path)
        seg.post_request(0, 0, b"\xff\xfenot json")
        drainer = LaneDrainer(
            seg, lambda bodies: [{"ok": True}] * len(bodies), doorbell, resp
        )
        assert drainer.drain_once() == 0      # nothing dispatchable
        status, _ = seg.read_response(0, 0, 1)
        assert status == STATUS_ERROR


# ----------------------------------------------------- service integration
# Mirrors tests/test_servers.py's fixture shape: memory storage + a tiny
# trained ALS instance, then drives the service's bucketed dispatch path
# directly (no HTTP needed for the cache semantics).

import datetime as dt  # noqa: E402

from pio_tpu.controller import ComputeContext  # noqa: E402
from pio_tpu.data import Event  # noqa: E402
from pio_tpu.server.query_server import QueryServerService  # noqa: E402
from pio_tpu.storage import App, Storage  # noqa: E402
from pio_tpu.workflow import (  # noqa: E402
    build_engine,
    run_train,
    variant_from_dict,
)

VARIANT = {
    "id": "rec-buckets",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "bucket-test"}},
    "algorithms": [
        {"name": "als",
         "params": {"rank": 4, "num_iterations": 4, "lambda_": 0.1}}
    ],
}


@pytest.fixture()
def mem_storage(tmp_home, monkeypatch):
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "MEM")
    Storage.reset()
    yield
    Storage.reset()


def _train_instance():
    app_id = Storage.get_meta_data_apps().insert(App(0, "bucket-test"))
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 5, 1, tzinfo=dt.timezone.utc)
    for u in range(6):
        for i in range(5):
            le.insert(
                Event("rate", "user", f"u{u}", "item", f"i{i}",
                      properties={"rating": 4.0}, event_time=t0),
                app_id,
            )
    variant = variant_from_dict(VARIANT)
    engine, ep = build_engine(variant)
    ctx = ComputeContext.local()
    run_train(engine, ep, variant, ctx=ctx)
    return variant, ctx


@pytest.fixture()
def bucket_service(mem_storage, monkeypatch):
    monkeypatch.setenv("PIO_TPU_BATCH_BUCKETS", "1,2,4")
    monkeypatch.setenv("PIO_TPU_BUCKET_WARMUP", "1")
    variant, ctx = _train_instance()
    yield QueryServerService(variant, ctx=ctx)


class TestServiceBuckets:
    def test_deploy_warms_every_bucket(self, bucket_service):
        svc = bucket_service
        assert svc._buckets.warmed == {1, 2, 4}
        assert svc._buckets.generation == 1
        assert svc._buckets.retraces == 0

    def test_steady_state_never_retraces(self, bucket_service):
        svc = bucket_service
        from pio_tpu.templates.recommendation import Query

        compiles_before = svc.devwatch.compile_counts()
        for i in range(100):
            n = (i % 5) + 1                   # includes bucket+1 and >max
            qs = [Query(user=f"u{j % 6}", num=2) for j in range(n)]
            results, fresh = svc._predict_batch_bucketed(qs)
            assert len(results) == n and not fresh
        assert svc._buckets.retraces == 0
        # the ISSUE-17 monitored form of the same invariant: the compile
        # attribution counters must not move across a steady-state window
        assert svc.devwatch.compile_counts() == compiles_before

    def test_batch_matches_solo_results(self, bucket_service):
        svc = bucket_service
        from pio_tpu.templates.recommendation import Query

        qs = [Query(user=f"u{j}", num=3) for j in range(3)]  # pads to 4
        batched = svc._predict_batch(qs)
        for q, got in zip(qs, batched):
            solo = svc._predict_one(q)
            assert [s.item for s in got.item_scores] == \
                [s.item for s in solo.item_scores]

    def test_hot_swap_evicts_and_rewarms(self, bucket_service):
        svc = bucket_service
        gen0 = svc._buckets.generation
        svc._load(None)                       # the /reload path
        assert svc._buckets.generation == gen0 + 1
        assert svc._buckets.evictions >= 3    # old generation evicted
        assert svc._buckets.warmed == {1, 2, 4}  # new one re-warmed

    def test_warmup_skipped_without_batching(self, mem_storage, monkeypatch):
        monkeypatch.delenv("PIO_TPU_BUCKET_WARMUP", raising=False)
        monkeypatch.delenv("PIO_TPU_SERVE_MICROBATCH_US", raising=False)
        variant, ctx = _train_instance()
        svc = QueryServerService(variant, ctx=ctx)
        assert svc._buckets.warmed == frozenset()
