"""Tests for the parallel subsystem: meshes, ring attention, pipelining.

Run on the simulated 8-device CPU mesh (tests/conftest.py) — the analog of
the reference testing Spark code on ``local[*]`` (SURVEY.md §4).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pio_tpu.parallel import (
    MeshSpec,
    build_mesh,
    mesh_axis_size,
    pipeline_apply,
    ring_attention,
    ring_attention_sharded,
    stage_slice,
)


# ---------------------------------------------------------------- mesh spec
def test_mesh_spec_sizes_defaults():
    assert MeshSpec().sizes(8) == {
        "data": 8, "pipe": 1, "seq": 1, "model": 1,
    }


def test_mesh_spec_fixed_axes():
    sizes = MeshSpec(data=-1, seq=2, model=2).sizes(8)
    assert sizes == {"data": 2, "pipe": 1, "seq": 2, "model": 2}


def test_mesh_spec_indivisible_raises():
    with pytest.raises(ValueError):
        MeshSpec(data=-1, model=3).sizes(8)
    with pytest.raises(ValueError):
        MeshSpec(data=4, model=4).sizes(8)


def test_build_mesh_axes():
    mesh = build_mesh(MeshSpec(data=2, seq=2, model=2))
    assert mesh.shape["data"] == 2
    assert mesh.shape["pipe"] == 1
    assert mesh_axis_size(mesh, "seq") == 2
    assert mesh_axis_size(None, "seq") == 1
    assert mesh_axis_size(mesh, "nope") == 1


# ------------------------------------------------------------ ring attention
def _dense_attention(q, k, v, causal):
    """Reference: plain softmax attention in float64-ish numpy."""
    b, t, h, d = q.shape
    scores = np.einsum("bqhd,bkhd->bhqk", q, k).astype(np.float64)
    scores /= np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((t, t), bool))
        scores = np.where(mask[None, None], scores, -np.inf)
    scores -= scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    out = np.einsum("bhqk,bkhd->bqhd", p, v.astype(np.float64))
    return out.astype(np.float32)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_single_device_matches_dense(causal):
    rng = np.random.default_rng(0)
    q, k, v = (
        rng.normal(size=(2, 16, 2, 8)).astype(np.float32) for _ in range(3)
    )
    out = ring_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        axis=None, causal=causal,
    )
    np.testing.assert_allclose(
        np.asarray(out), _dense_attention(q, k, v, causal),
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_sharded_matches_dense(causal):
    mesh = build_mesh(MeshSpec(data=2, seq=4))
    rng = np.random.default_rng(1)
    b, t, h, d = 4, 32, 2, 8  # t=32 → 8 positions per seq shard
    q, k, v = (
        rng.normal(size=(b, t, h, d)).astype(np.float32) for _ in range(3)
    )
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            mesh, q, k, v, causal=causal
        )
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), _dense_attention(q, k, v, causal),
        rtol=1e-4, atol=1e-5,
    )


def test_ring_attention_sharded_grads_flow():
    mesh = build_mesh(MeshSpec(data=2, seq=4))
    rng = np.random.default_rng(2)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 16, 1, 8)), jnp.float32)
        for _ in range(3)
    )

    def loss(q, k, v):
        return ring_attention_sharded(mesh, q, k, v, causal=True).sum()

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


# ----------------------------------------------------- ulysses (all-to-all)
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_single_device_matches_dense(causal):
    from pio_tpu.parallel import ulysses_attention

    rng = np.random.default_rng(3)
    q, k, v = (
        rng.normal(size=(2, 16, 2, 8)).astype(np.float32) for _ in range(3)
    )
    out = ulysses_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        axis=None, causal=causal,
    )
    np.testing.assert_allclose(
        np.asarray(out), _dense_attention(q, k, v, causal),
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_sharded_matches_dense(causal):
    from pio_tpu.parallel import ulysses_attention_sharded

    mesh = build_mesh(MeshSpec(data=2, seq=4))
    rng = np.random.default_rng(4)
    b, t, h, d = 4, 32, 4, 8  # h=4 heads over seq=4 devices
    q, k, v = (
        rng.normal(size=(b, t, h, d)).astype(np.float32) for _ in range(3)
    )
    out = jax.jit(
        lambda q, k, v: ulysses_attention_sharded(
            mesh, q, k, v, causal=causal
        )
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), _dense_attention(q, k, v, causal),
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_ring(causal):
    """Both SP modes are exact attention — identical up to float noise."""
    from pio_tpu.parallel import ulysses_attention_sharded

    mesh = build_mesh(MeshSpec(data=2, seq=4))
    rng = np.random.default_rng(5)
    q, k, v = (
        rng.normal(size=(2, 32, 4, 8)).astype(np.float32) for _ in range(3)
    )
    ring = ring_attention_sharded(mesh, q, k, v, causal=causal)
    uly = ulysses_attention_sharded(mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(uly), rtol=1e-4, atol=1e-5
    )


def test_ulysses_rejects_indivisible_heads():
    from pio_tpu.parallel import ulysses_attention_sharded

    mesh = build_mesh(MeshSpec(data=2, seq=4))
    rng = np.random.default_rng(6)
    q, k, v = (
        rng.normal(size=(2, 32, 3, 8)).astype(np.float32)  # 3 heads, n=4
        for _ in range(3)
    )
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(mesh, q, k, v)


def test_ulysses_sharded_grads_flow():
    from pio_tpu.parallel import ulysses_attention_sharded

    mesh = build_mesh(MeshSpec(data=2, seq=4))
    rng = np.random.default_rng(7)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 16, 4, 8)), jnp.float32)
        for _ in range(3)
    )

    def loss(q, k, v):
        return ulysses_attention_sharded(mesh, q, k, v, causal=True).sum()

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


# ------------------------------------------------------------------ pipeline
def test_pipeline_apply_matches_sequential():
    """4-stage pipeline over the pipe axis ≡ applying the stages in order."""
    from pio_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    n_stages, n_micro, mb, f = 4, 6, 4, 8
    mesh = build_mesh(MeshSpec(data=2, pipe=4))
    rng = np.random.default_rng(3)
    w = rng.normal(size=(n_stages, f, f)).astype(np.float32) * 0.3
    b = rng.normal(size=(n_stages, f)).astype(np.float32) * 0.1
    x = rng.normal(size=(n_micro, mb, f)).astype(np.float32)

    def stage(params, h):
        w, b = params
        return jnp.tanh(h @ w + b)

    def run(w, b, x):
        def inner(w_blk, b_blk, x_loc):
            params = stage_slice((w_blk, b_blk))
            return pipeline_apply(params, x_loc, stage)

        return shard_map(
            inner, mesh=mesh,
            in_specs=(P("pipe"), P("pipe"), P(None, "data")),
            out_specs=P(None, "data"),
            check_vma=False,
        )(w, b, x)

    got = np.asarray(jax.jit(run)(w, b, x))

    want = x
    for s in range(n_stages):
        want = np.tanh(want @ w[s] + b[s])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_pipeline_apply_differentiable():
    from pio_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = build_mesh(MeshSpec(data=2, pipe=4))
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.normal(size=(4, 8, 8)), jnp.float32) * 0.3
    x = jnp.asarray(rng.normal(size=(5, 2, 8)), jnp.float32)

    def loss(w, x):
        def inner(w_blk, x_loc):
            return pipeline_apply(
                stage_slice(w_blk), x_loc, lambda p, h: jnp.tanh(h @ p)
            )

        out = shard_map(
            inner, mesh=mesh,
            in_specs=(P("pipe"), P()),
            out_specs=P(),
            check_vma=False,
        )(w, x)
        return (out ** 2).sum()

    g = jax.jit(jax.grad(loss))(w, x)
    g = np.asarray(g)
    assert np.isfinite(g).all()
    # every stage's weights get gradient
    assert (np.abs(g).reshape(4, -1).sum(axis=1) > 0).all()
