"""Sequence-recommender tests — the full dp/sp/tp/ep/pp training step.

The synthetic task is a deterministic item cycle (1→2→…→V→1): a model that
learns it must attend to the last position through ring attention, the
pipelined trunk, and the vocab-parallel softmax.
"""

import dataclasses

import numpy as np
import pytest

from pio_tpu.models.seqrec import SeqRecConfig, SeqRecModel, train_seqrec
from pio_tpu.parallel.mesh import MeshSpec, build_mesh


def _cycle_sequences(V=12, n=32, T=16, seed=0):
    rng = np.random.default_rng(seed)
    seqs = np.zeros((n, T), np.int32)
    for r in range(n):
        start = rng.integers(1, V + 1)
        L = rng.integers(8, T + 1)
        seqs[r, :L] = [(start + j - 1) % V + 1 for j in range(L)]
    return seqs


CFG = SeqRecConfig(
    d_model=32, n_heads=4, n_layers=2, ffn=64, max_len=16,
    steps=300, learning_rate=3e-3,
)


def _accuracy(model: SeqRecModel, seqs: np.ndarray, V: int) -> float:
    scores = model.next_item_scores(seqs)
    correct = 0
    for r in range(len(seqs)):
        L = int((seqs[r] > 0).sum())
        want = seqs[r, L - 1] % V + 1
        correct += int(np.argmax(scores[r, 1:]) + 1) == want
    return correct / len(seqs)


@pytest.mark.parametrize(
    "spec",
    [
        None,
        MeshSpec(data=2, seq=2, model=2),
        MeshSpec(data=2, pipe=2, seq=2),
        MeshSpec(data=1, pipe=2, seq=2, model=2),
    ],
    ids=["single", "dp-sp-tp", "dp-pp-sp", "pp-sp-tp"],
)
def test_learns_cycle(spec):
    V = 12
    seqs = _cycle_sequences(V)
    mesh = None if spec is None else build_mesh(spec)
    m = train_seqrec(mesh, seqs, V, CFG)
    assert _accuracy(m, seqs[:8], V) >= 0.85


def test_learns_cycle_ulysses_attention():
    """All-to-all SP mode: same training quality as the ring path (4 heads
    over a 2-wide seq axis)."""
    V = 12
    seqs = _cycle_sequences(V)
    mesh = build_mesh(MeshSpec(data=2, seq=2, model=2))
    m = train_seqrec(
        mesh, seqs, V, dataclasses.replace(CFG, attention="ulysses")
    )
    assert _accuracy(m, seqs[:8], V) >= 0.85


def test_unknown_attention_mode_raises():
    V = 12
    seqs = _cycle_sequences(V)
    with pytest.raises(ValueError, match="attention mode"):
        train_seqrec(
            None, seqs, V, dataclasses.replace(CFG, attention="flash")
        )


def test_serving_cache_and_pickle():
    import pickle

    V = 12
    seqs = _cycle_sequences(V)
    m = train_seqrec(None, seqs, V, CFG)
    s1 = m.next_item_scores(seqs[:4])
    assert m._serve_cache is not None
    m2 = pickle.loads(pickle.dumps(m))
    assert m2._serve_cache is None
    np.testing.assert_allclose(
        m2.next_item_scores(seqs[:4]), s1, rtol=1e-5, atol=1e-5
    )


def test_config_validation():
    V = 12
    seqs = _cycle_sequences(V)
    mesh = build_mesh(MeshSpec(data=2, model=4))
    with pytest.raises(ValueError, match="n_heads"):
        train_seqrec(
            mesh, seqs, V,
            SeqRecConfig(d_model=32, n_heads=2, n_layers=2, max_len=16),
        )
    mesh = build_mesh(MeshSpec(data=4, pipe=2))
    with pytest.raises(ValueError, match="n_layers"):
        train_seqrec(
            mesh, seqs, V,
            SeqRecConfig(d_model=32, n_heads=4, n_layers=3, max_len=16),
        )
