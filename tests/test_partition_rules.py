"""Partition-rule registry and mesh-sharded serving (ISSUE 10).

Covers rule matching (first-match-wins, scalar and unmatched policy,
optimizer-state inheritance by substring search), shard→gather identity
over the simulated 8-device mesh, spec projection onto smaller meshes,
sharded model persistence with resharding across device counts and torn
shard detection, the per-device memory budget (a model that only fits
sharded must fail fast unsharded and serve sharded), the query server's
`PIO_TPU_MESH_SERVE` path with host/sharded parity, and the mesh-worker
pool end to end (slow tier).
"""

import datetime as dt
import http.client
import json

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers engine factories)
from pio_tpu.data.bimap import BiMap
from pio_tpu.models.als import ALSFactors
from pio_tpu.parallel.partition import (
    DeviceBudgetExceeded,
    match_partition_rules,
    make_shard_and_gather_fns,
    per_device_nbytes,
    rules_for,
    shard_params,
    spec_for_mesh,
    tree_nbytes,
)
from pio_tpu.templates.recommendation import ALSModel


def _mesh(n=8, names=("data",)):
    import jax
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices()[:n]).reshape(
        (n,) if len(names) == 1 else (-1, len(names))
    )
    return Mesh(devs, names)


def _P(*args):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*args)


# ------------------------------------------------------------ rule matching
class TestRuleMatching:
    def test_first_match_wins(self):
        rules = [("factors", _P("data", None)), ("factors", _P())]
        specs = match_partition_rules(
            rules, {"factors": np.zeros((4, 2), np.float32)}
        )
        assert specs["factors"] == _P("data", None)

    def test_nested_paths_join_with_slash(self):
        rules = rules_for("seqrec")
        tree = {"blocks": {"wq": np.zeros((2, 4, 4), np.float32)}}
        specs = match_partition_rules(rules, tree)
        assert specs["blocks"]["wq"] == _P("pipe", None, "model")

    def test_scalars_always_replicated(self):
        rules = [(".", _P("data"))]  # matches everything
        specs = match_partition_rules(
            rules, {"step": np.float32(3.0), "w": np.zeros(4, np.float32)}
        )
        assert specs["step"] == _P()
        assert specs["w"] == _P("data")

    def test_unmatched_policy(self):
        tree = {"mystery": np.zeros((2, 2), np.float32)}
        specs = match_partition_rules([], tree)  # default: replicate
        assert specs["mystery"] == _P()
        with pytest.raises(ValueError, match="mystery"):
            match_partition_rules([], tree, on_unmatched="error")

    def test_optimizer_state_inherits_by_substring(self):
        # adam-style state nests the param tree under 0/mu — re.search
        # still finds the factor rule inside the longer path
        state = {"0": {"mu": {"item_factors": np.zeros((8, 2), np.float32)}}}
        specs = match_partition_rules(rules_for("als"), state)
        assert specs["0"]["mu"]["item_factors"] == _P("data", None)

    def test_unknown_template_raises(self):
        with pytest.raises(KeyError, match="no partition rules"):
            rules_for("nonesuch")

    def test_template_specs_match_model_params(self):
        # every bundled template's param skeleton resolves with the
        # strict policy — a new parameter without a rule must fail loudly
        from pio_tpu.models.seqrec import SeqRecConfig, param_specs
        from pio_tpu.models.two_tower import _tower_specs

        assert _tower_specs()  # raises on an unmatched leaf
        assert param_specs(SeqRecConfig(d_model=8, n_heads=2, n_layers=1))


# ----------------------------------------------------------- shard / gather
class TestShardGather:
    def test_identity_on_8_device_mesh(self):
        mesh = _mesh(8)
        tree = {
            "user_factors": np.arange(16 * 4, dtype=np.float32).reshape(16, 4),
            "item_factors": np.arange(8 * 4, dtype=np.float32).reshape(8, 4),
        }
        specs = match_partition_rules(rules_for("als"), tree)
        shard_fns, gather_fns = make_shard_and_gather_fns(mesh, specs)
        placed = {k: shard_fns[k](v) for k, v in tree.items()}
        # actually distributed: each device holds rows/8
        assert len(placed["user_factors"].sharding.device_set) == 8
        for k, v in tree.items():
            np.testing.assert_array_equal(gather_fns[k](placed[k]), v)

    def test_spec_projection_drops_absent_axes(self):
        mesh = _mesh(8, ("data",))
        assert spec_for_mesh(mesh, _P("model", None)) == _P(None, None)
        assert spec_for_mesh(mesh, _P("data", "model")) == _P("data", None)
        # tuple-of-axes entries keep only the live axes
        assert spec_for_mesh(mesh, _P(("data", "model"),)) == _P(("data",))

    def test_shard_params_mesh_none_passthrough(self):
        import jax.numpy as jnp

        tree = {"user_factors": np.ones((4, 2), np.float32)}
        sharded, specs = shard_params(None, tree, rules_for("als"))
        assert isinstance(sharded["user_factors"], jnp.ndarray)
        assert specs["user_factors"] == _P("data", None)

    def test_per_device_nbytes_accounting(self):
        mesh = _mesh(8)
        tree = {
            "user_factors": np.zeros((16, 4), np.float32),  # sharded /8
            "bias": np.zeros((16,), np.float32),  # replicated
        }
        specs = match_partition_rules(rules_for("als"), tree)
        got = per_device_nbytes(mesh, tree, specs)
        assert got == (16 * 4 * 4) // 8 + 16 * 4
        assert tree_nbytes(tree) == 16 * 4 * 4 + 16 * 4


# ----------------------------------------------------- sharded persistence
def _als_model(n_users=16, n_items=8, rank=4, seed=0):
    rng = np.random.default_rng(seed)
    return ALSModel(
        ALSFactors(
            user_factors=rng.normal(size=(n_users, rank)).astype(np.float32),
            item_factors=rng.normal(size=(n_items, rank)).astype(np.float32),
        ),
        BiMap({f"u{i}": i for i in range(n_users)}),
        BiMap({f"i{i}": i for i in range(n_items)}),
    )


class TestShardedPersistence:
    @pytest.fixture(autouse=True)
    def storage(self, tmp_home):
        from pio_tpu.storage import Storage

        Storage.reset()
        yield Storage.get_model_data_models()
        Storage.reset()

    def test_reshard_8_to_4_and_1(self, storage):
        from pio_tpu.workflow import shard_store

        model = _als_model()
        stripped = shard_store.save_sharded(
            storage, "inst-rs", [model], n_shards=8, mesh_shape=[8]
        )
        assert isinstance(
            stripped[0].factors.user_factors, shard_store.ShardPlaceholder
        )
        for n_devices in (4, 1):
            back = shard_store.restore_sharded(
                storage, "inst-rs", list(stripped), n_devices=n_devices
            )
            np.testing.assert_array_equal(
                back[0].factors.user_factors, model.factors.user_factors
            )
            np.testing.assert_array_equal(
                back[0].factors.item_factors, model.factors.item_factors
            )

    def test_same_device_count_is_not_a_reshard(self, storage):
        from pio_tpu.workflow import shard_store

        before = shard_store._SHARD_RESHARD.value()
        stripped = shard_store.save_sharded(
            storage, "inst-same", [_als_model(seed=1)],
            n_shards=8, mesh_shape=[8],
        )
        shard_store.restore_sharded(
            storage, "inst-same", list(stripped), n_devices=8
        )
        assert shard_store._SHARD_RESHARD.value() == before
        shard_store.restore_sharded(
            storage, "inst-same", list(stripped), n_devices=2
        )
        assert shard_store._SHARD_RESHARD.value() == before + 1

    def test_torn_shard_detected(self, storage):
        from pio_tpu.storage.records import Model
        from pio_tpu.workflow import shard_store

        stripped = shard_store.save_sharded(
            storage, "inst-torn", [_als_model(seed=2)],
            n_shards=8, mesh_shape=[8],
        )
        shard_id = "inst-torn.shard.0.0.3"
        rec = storage.get(shard_id)
        assert rec is not None
        storage.insert(Model(id=shard_id, models=rec.models[:-1] + b"\x00"))
        with pytest.raises(RuntimeError, match="checksum"):
            shard_store.restore_sharded(
                storage, "inst-torn", list(stripped), n_devices=8
            )

    def test_missing_manifest_is_torn_persist(self, storage):
        from pio_tpu.workflow import shard_store

        stripped = shard_store.save_sharded(
            storage, "inst-a", [_als_model(seed=3)],
            n_shards=8, mesh_shape=[8],
        )
        with pytest.raises(RuntimeError, match="manifest"):
            shard_store.restore_sharded(
                storage, "inst-MISSING", list(stripped), n_devices=8
            )


# ------------------------------------------------------------ device budget
class TestDeviceBudget:
    def test_model_over_one_chip_budget_serves_only_sharded(
        self, monkeypatch
    ):
        from pio_tpu.ops.topn import DeviceTopNScorer

        rng = np.random.default_rng(5)
        rows = rng.normal(size=(64, 8)).astype(np.float32)
        cols = rng.normal(size=(40, 8)).astype(np.float32)
        total = rows.nbytes + cols.nbytes
        # budget holds total/8 (one mesh shard) but not the whole model
        monkeypatch.setenv(
            "PIO_TPU_DEVICE_BUDGET_BYTES", str(-(-total // 8))
        )
        with pytest.raises(DeviceBudgetExceeded):
            DeviceTopNScorer(rows, cols, prefer_device=True)
        sc = DeviceTopNScorer(
            rows, cols, prefer_device=True, mesh=_mesh(8)
        )
        info = sc.sharding_info()
        assert info is not None and info["nDevices"] == 8
        assert info["bytesPerDevice"] <= -(-total // 8)
        # sharded dispatch agrees with the host mirror
        host = rows[:4] @ cols.T
        want = np.argsort(-host, axis=1)[:, :5]
        got_idx, got_val = sc.top_n_batch(np.arange(4, dtype=np.int32), 5)
        np.testing.assert_array_equal(got_idx, want)
        np.testing.assert_allclose(
            got_val, np.take_along_axis(host, want, axis=1), atol=1e-5
        )

    def test_shard_params_budget(self, monkeypatch):
        tree = {"user_factors": np.zeros((64, 8), np.float32)}
        nbytes = tree["user_factors"].nbytes
        monkeypatch.setenv("PIO_TPU_DEVICE_BUDGET_BYTES", str(nbytes // 8))
        sharded, _ = shard_params(_mesh(8), tree, rules_for("als"))
        assert len(sharded["user_factors"].sharding.device_set) == 8
        monkeypatch.setenv(
            "PIO_TPU_DEVICE_BUDGET_BYTES", str(nbytes // 16)
        )
        with pytest.raises(DeviceBudgetExceeded):
            shard_params(_mesh(8), tree, rules_for("als"))


# ----------------------------------------------- query server mesh serving
VARIANT = {
    "id": "shard-e2e",
    "engineFactory": "templates.recommendation",
    "datasource": {"params": {"app_name": "shard-test"}},
    "algorithms": [
        {
            "name": "als",
            "params": {
                "rank": 4, "num_iterations": 5, "lambda_": 0.05, "seed": 1,
            },
        }
    ],
}


def _seed_and_train(ctx=None):
    from pio_tpu.controller import ComputeContext
    from pio_tpu.data import Event
    from pio_tpu.storage import App, Storage
    from pio_tpu.workflow import build_engine, run_train, variant_from_dict

    app_id = Storage.get_meta_data_apps().insert(App(0, "shard-test"))
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    for u in range(10):
        for i in range(6):
            in_block = (u < 5) == (i < 3)
            le.insert(
                Event(
                    "rate", "user", f"u{u}", "item", f"i{i}",
                    properties={"rating": 5.0 if in_block else 1.0},
                    event_time=t0 + dt.timedelta(minutes=u * 60 + i),
                ),
                app_id,
            )
    variant = variant_from_dict(VARIANT)
    engine, ep = build_engine(variant)
    ctx = ctx or ComputeContext.create(seed=0)
    run_train(engine, ep, variant, ctx=ctx)
    return variant, ctx


def _query(svc, body):
    from pio_tpu.server.http import Request

    code, resp = svc.query(
        Request("POST", "/queries.json", {}, body,
                raw_body=json.dumps(body).encode())
    )
    assert code == 200, (code, resp)
    raw = resp.body if hasattr(resp, "body") else resp
    return json.loads(raw) if isinstance(raw, (str, bytes)) else raw


class TestMeshServing:
    @pytest.fixture(autouse=True)
    def storage(self, tmp_home):
        from pio_tpu.storage import Storage

        Storage.reset()
        yield
        Storage.reset()

    def test_sharded_serving_parity_and_stats(self, monkeypatch):
        from pio_tpu.server.http import Request
        from pio_tpu.server.query_server import QueryServerService

        variant, ctx = _seed_and_train()
        monkeypatch.setenv("PIO_TPU_MESH_SERVE", "0")
        ref = _query(
            QueryServerService(variant, ctx=ctx), {"user": "u1", "num": 3}
        )
        monkeypatch.setenv("PIO_TPU_MESH_SERVE", "1")
        svc = QueryServerService(variant, ctx=ctx)
        got = _query(svc, {"user": "u1", "num": 3})
        assert ([s["item"] for s in got["itemScores"]]
                == [s["item"] for s in ref["itemScores"]])
        for a, b in zip(ref["itemScores"], got["itemScores"]):
            assert abs(a["score"] - b["score"]) <= 1e-3
        _, stats = svc.get_stats(Request("GET", "/stats.json", {}, None))
        sh = stats["sharding"]
        assert sh["enabled"] and sh["meshDevices"] == 8
        assert sh["models"][0]["model"] == "ALSModel"
        assert sh["models"][0]["nDevices"] == 8
        eng = variant.engine_id
        assert svc._shard_bytes_placed_total.value(eng) > 0

    def test_sharded_persist_deploy_round_trip(self, monkeypatch):
        # train with sharded persistence ON: the blob holds placeholders,
        # deploy reassembles from verified shards and still answers
        from pio_tpu.server.query_server import QueryServerService

        monkeypatch.setenv("PIO_TPU_SHARDED_PERSIST", "1")
        variant, ctx = _seed_and_train()
        monkeypatch.setenv("PIO_TPU_MESH_SERVE", "1")
        svc = QueryServerService(variant, ctx=ctx)
        got = _query(svc, {"user": "u1", "num": 3})
        assert {s["item"] for s in got["itemScores"]} <= {"i0", "i1", "i2"}

    def test_gate_defaults_off(self, monkeypatch):
        from pio_tpu.server.http import Request
        from pio_tpu.server.query_server import QueryServerService

        monkeypatch.delenv("PIO_TPU_MESH_SERVE", raising=False)
        variant, ctx = _seed_and_train()
        svc = QueryServerService(variant, ctx=ctx)
        _, stats = svc.get_stats(Request("GET", "/stats.json", {}, None))
        assert stats["sharding"] == {"enabled": False}


# ------------------------------------------------------- mesh-worker pool
@pytest.mark.slow
class TestMeshWorkerPool:
    def test_pool_parity_and_owner_sharding(self, tmp_home):
        from pio_tpu.controller import ComputeContext
        from pio_tpu.server.worker_pool import ServingPool
        from pio_tpu.storage import Storage

        Storage.reset()
        try:
            variant, _ = _seed_and_train(ctx=ComputeContext.local())
            pool = ServingPool(
                variant, host="127.0.0.1", port=0, n_workers=2,
                mesh_worker=True,
            )
            pool.start()
            try:
                pool.wait_ready(timeout=180)

                def post(body):
                    c = http.client.HTTPConnection(
                        "127.0.0.1", pool.port, timeout=30
                    )
                    try:
                        c.request(
                            "POST", "/queries.json",
                            body=json.dumps(body).encode(),
                            headers={"Content-Type": "application/json"},
                        )
                        r = c.getresponse()
                        return r.status, json.loads(r.read())
                    finally:
                        c.close()

                def stats():
                    c = http.client.HTTPConnection(
                        "127.0.0.1", pool.port, timeout=30
                    )
                    try:
                        c.request("GET", "/stats.json")
                        return json.loads(c.getresponse().read())
                    finally:
                        c.close()

                st, ref = post({"user": "u1", "num": 3})
                assert st == 200 and len(ref["itemScores"]) == 3
                shard_owner = None
                for _ in range(40):
                    st, got = post({"user": "u1", "num": 3})
                    assert st == 200
                    assert ([s["item"] for s in got["itemScores"]]
                            == [s["item"] for s in ref["itemScores"]])
                    s = stats()
                    sh = s.get("sharding") or {}
                    if sh.get("enabled"):
                        shard_owner = (s["worker"], sh)
                # the kernel rotates fresh connections across both
                # workers; only the mesh owner (worker 0) reports sharding
                assert shard_owner is not None
                assert shard_owner[0] == 0
                assert shard_owner[1]["models"][0]["model"] == "ALSModel"
            finally:
                pool.stop()
        finally:
            Storage.reset()
