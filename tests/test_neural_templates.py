"""End-to-end lifecycle tests for the two-tower and sequence templates.

Same quickstart shape as the reference's integration scenarios
(tests/pio_tests/scenarios/quickstart_test.py — UNVERIFIED; SURVEY.md §4):
import events → train → load models → query.
"""

import datetime as dt

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers engine factories)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.storage import App, Storage
from pio_tpu.templates.common import PredictedResult
from pio_tpu.workflow import (
    build_engine,
    load_models_for_instance,
    run_train,
    variant_from_dict,
)


@pytest.fixture(autouse=True)
def _home(tmp_home):
    return tmp_home


GROUPS = 4
N_USERS, N_ITEMS = 16, 16


def _seed_interactions(app_id):
    """User u views/buys items from group u % GROUPS, in time order."""
    le = Storage.get_levents()
    rng = np.random.default_rng(0)
    per = N_ITEMS // GROUPS
    t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    for u in range(N_USERS):
        group = u % GROUPS
        for k in range(12):
            item = group * per + rng.integers(0, per)
            le.insert(
                Event(
                    event="view" if k % 3 else "buy",
                    entity_type="user",
                    entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id=f"i{item}",
                    event_time=t0 + dt.timedelta(minutes=int(k)),
                ),
                app_id,
            )


def _train_and_serve(variant_dict, query):
    variant = variant_from_dict(variant_dict)
    engine, ep = build_engine(variant)
    ctx = ComputeContext.create(seed=0)
    instance_id = run_train(engine, ep, variant, ctx=ctx)
    models = load_models_for_instance(instance_id, engine, ep, ctx)
    serving = engine.make_serving(ep)
    pairs = engine.algorithms_with_models(ep, models)
    return serving.serve(query, [a.predict(m, query) for a, m in pairs])


class TestTwoTowerTemplate:
    def test_full_lifecycle(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "tt-test"))
        _seed_interactions(app_id)
        from pio_tpu.templates.twotower import Query

        result = _train_and_serve(
            {
                "id": "tt",
                "engineFactory": "templates.twotower",
                "datasource": {
                    "params": {"app_name": "tt-test", "rate_event": "view"}
                },
                "algorithms": [
                    {
                        "name": "twotower",
                        "params": {
                            "embed_dim": 16,
                            "hidden": 32,
                            "out_dim": 16,
                            "steps": 200,
                            "batch_size": 64,
                            "model_parallel": 2,
                        },
                    }
                ],
            },
            Query(user="u1", num=3),
        )
        assert isinstance(result, PredictedResult)
        assert len(result.item_scores) == 3
        per = N_ITEMS // GROUPS
        group_of = lambda item: int(item[1:]) // per  # noqa: E731
        hits = sum(
            group_of(s.item) == 1 % GROUPS for s in result.item_scores
        )
        assert hits >= 2  # top-3 dominated by the user's group

    def test_unknown_user_empty(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "tt-test"))
        _seed_interactions(app_id)
        from pio_tpu.templates.twotower import Query

        result = _train_and_serve(
            {
                "id": "tt",
                "engineFactory": "templates.twotower",
                "datasource": {
                    "params": {"app_name": "tt-test", "rate_event": "view"}
                },
                "algorithms": [
                    {
                        "name": "twotower",
                        "params": {"embed_dim": 8, "hidden": 16,
                                   "out_dim": 8, "steps": 5},
                    }
                ],
            },
            Query(user="nobody", num=3),
        )
        assert result.item_scores == ()


class TestSequenceTemplate:
    def _variant(self, **algo_params):
        params = {
            "d_model": 32,
            "n_heads": 4,
            "n_layers": 2,
            "ffn": 64,
            "max_len": 16,
            "steps": 250,
            "learning_rate": 3e-3,
        }
        params.update(algo_params)
        return {
            "id": "sr",
            "engineFactory": "templates.sequence",
            "datasource": {"params": {"app_name": "sr-test"}},
            "algorithms": [{"name": "seqrec", "params": params}],
        }

    def _seed_cycles(self, app_id, V=8):
        """Every user walks the item cycle i0→i1→…→i{V-1}→i0…"""
        le = Storage.get_levents()
        t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        rng = np.random.default_rng(1)
        for u in range(12):
            start = rng.integers(0, V)
            for k in range(10):
                le.insert(
                    Event(
                        event="view",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{(start + k) % V}",
                        event_time=t0 + dt.timedelta(minutes=int(k)),
                    ),
                    app_id,
                )

    def test_full_lifecycle_user_query(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "sr-test"))
        self._seed_cycles(app_id)
        from pio_tpu.templates.sequence import Query

        # user u0's history ends at some item ik → next should be i(k+1)%V
        result = _train_and_serve(
            self._variant(seq_parallel=2, pipe_parallel=2),
            Query(user="u0", num=1),
        )
        assert len(result.item_scores) == 1

    def test_history_query_predicts_cycle(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "sr-test"))
        self._seed_cycles(app_id)
        from pio_tpu.templates.sequence import Query

        result = _train_and_serve(
            self._variant(),
            Query(history=("i0", "i1", "i2", "i3"), num=1),
        )
        assert result.item_scores[0].item == "i4"

    def test_empty_history_empty_result(self):
        app_id = Storage.get_meta_data_apps().insert(App(0, "sr-test"))
        self._seed_cycles(app_id)
        from pio_tpu.templates.sequence import Query

        result = _train_and_serve(
            self._variant(steps=5),
            Query(user="ghost", num=3),
        )
        assert result.item_scores == ()


class TestTwoTowerBatchPredict:
    def test_batch_matches_loop(self):
        from pio_tpu.templates.twotower import Query, twotower_engine

        app_id = Storage.get_meta_data_apps().insert(App(0, "tt-test"))
        _seed_interactions(app_id)
        variant = variant_from_dict({
            "id": "ttb", "engineFactory": "templates.twotower",
            "datasource": {"params": {"app_name": "tt-test",
                                      "rate_event": "view"}},
            "algorithms": [{"name": "twotower", "params": {
                "embed_dim": 16, "hidden": 32, "out_dim": 16,
                "steps": 100, "batch_size": 64}}],
        })
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        iid = run_train(engine, ep, variant, ctx=ctx)
        models = load_models_for_instance(iid, engine, ep, ctx)
        algo, model = engine.algorithms_with_models(ep, models)[0]
        queries = [
            (i, Query(user=f"u{i % 6}", num=4)) for i in range(12)
        ] + [(99, Query(user="stranger", num=4))]
        loop = {i: algo.predict(model, q) for i, q in queries}
        bat = dict(algo.batch_predict(model, queries))
        assert set(loop) == set(bat)
        for i in loop:
            assert [s.item for s in loop[i].item_scores] == [
                s.item for s in bat[i].item_scores
            ], i


class TestSequenceBatchPredict:
    def test_batch_matches_loop(self):
        from pio_tpu.templates.sequence import Query

        app_id = Storage.get_meta_data_apps().insert(App(0, "seq-test"))
        le = Storage.get_levents()
        t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        for u in range(8):
            for k in range(10):
                le.insert(
                    Event(event="view", entity_type="user",
                          entity_id=f"u{u}", target_entity_type="item",
                          target_entity_id=f"i{(u + k) % 8}",
                          event_time=t0 + dt.timedelta(minutes=k)),
                    app_id,
                )
        variant = variant_from_dict({
            "id": "sqb", "engineFactory": "templates.sequence",
            "datasource": {"params": {"app_name": "seq-test",
                                      "event_names": ["view"]}},
            "algorithms": [{"name": "seqrec", "params": {
                "d_model": 32, "n_heads": 4, "n_layers": 2, "ffn": 64,
                "max_len": 16, "steps": 120, "learning_rate": 3e-3}}],
        })
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        iid = run_train(engine, ep, variant, ctx=ctx)
        models = load_models_for_instance(iid, engine, ep, ctx)
        algo, model = engine.algorithms_with_models(ep, models)[0]
        queries = (
            [(i, Query(user=f"u{i % 6}", num=3)) for i in range(10)]
            + [(90, Query(history=("i1", "i2"), num=3))]
            + [(91, Query(user="stranger", num=3))]
        )
        loop = {i: algo.predict(model, q) for i, q in queries}
        bat = dict(algo.batch_predict(model, queries))
        assert set(loop) == set(bat)
        for i in loop:
            assert [s.item for s in loop[i].item_scores] == [
                s.item for s in bat[i].item_scores
            ], i
