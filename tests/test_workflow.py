"""Workflow tests: run_train bookkeeping, model persistence, engine.json,
run_evaluation (reference CoreWorkflow/FakeWorkflow test analogs)."""

import json

import pytest

from pio_tpu.controller import ComputeContext
from pio_tpu.storage import RunStatus, Storage
from pio_tpu.workflow import (
    EngineJsonError,
    WorkflowParams,
    build_engine,
    load_models_for_instance,
    load_variant,
    run_evaluation,
    run_train,
    variant_from_dict,
)
from tests.fixtures import FixtureModel, fixture_engine
from tests.test_controller import NegAbsErr, variant


@pytest.fixture(autouse=True)
def isolated_storage(tmp_home):
    Storage.reset()
    yield
    Storage.reset()


CTX = ComputeContext.local()


class TestEngineJson:
    def test_load_variant_file(self, tmp_path):
        p = tmp_path / "engine.json"
        p.write_text(json.dumps(variant(algos=[{"name": "algo"}])))
        v = load_variant(str(p))
        assert v.engine_factory == "fixture-engine"
        assert v.engine_id == "test"
        engine, ep = build_engine(v)
        assert ep.algorithm_params_list[0][0] == "algo"

    def test_missing_file(self):
        with pytest.raises(EngineJsonError, match="not found"):
            load_variant("/nope/engine.json")

    def test_bad_json(self, tmp_path):
        p = tmp_path / "engine.json"
        p.write_text("{nope")
        with pytest.raises(EngineJsonError, match="invalid JSON"):
            load_variant(str(p))

    def test_missing_factory(self):
        with pytest.raises(EngineJsonError, match="engineFactory"):
            variant_from_dict({"id": "x"})


class TestRunTrain:
    def _variant(self, **kw):
        return variant_from_dict(variant(**kw))

    def test_completed_run_persists_models(self):
        v = self._variant(algos=[{"name": "algo", "params": {"id": 1, "mult": 4}}])
        engine, ep = build_engine(v)
        iid = run_train(engine, ep, v, ctx=CTX)

        inst = Storage.get_meta_data_engine_instances().get(iid)
        assert inst.status == RunStatus.COMPLETED
        assert inst.engine_factory == "fixture-engine"
        assert json.loads(inst.algorithms_params)[0]["params"]["mult"] == 4
        assert "train_seconds" in inst.env

        models = load_models_for_instance(iid, engine, ep, CTX)
        assert models == [FixtureModel(algo_id=1, mult=4, prep_id=8, ds_id=7)]

        latest = Storage.get_meta_data_engine_instances().get_latest_completed(
            v.engine_id, v.engine_version, v.path or v.engine_id
        )
        assert latest.id == iid

    def test_phase_timings_recorded(self):
        v = self._variant(algos=[{"name": "algo", "params": {"id": 1}}])
        engine, ep = build_engine(v)
        iid = run_train(engine, ep, v, ctx=CTX)
        env = Storage.get_meta_data_engine_instances().get(iid).env
        assert "phase_read" in env and "phase_prepare" in env
        assert "phase_train:0_algo" in env
        assert float(env["phase_read"]) >= 0.0

    def test_profile_dir_captures_trace(self, tmp_path):
        import os

        v = self._variant(algos=[{"name": "algo", "params": {"id": 1}}])
        engine, ep = build_engine(v)
        prof = str(tmp_path / "trace")
        run_train(
            engine, ep, v, WorkflowParams(profile_dir=prof), ctx=CTX
        )
        files = [
            os.path.join(r, f)
            for r, _, fs in os.walk(prof)
            for f in fs
        ]
        assert files, "profiler produced no trace files"

    def test_failed_run_marked(self):
        v = self._variant(ds={"id": 1, "fail_sanity": True}, algos=[{"name": "algo"}])
        engine, ep = build_engine(v)
        with pytest.raises(ValueError):
            run_train(engine, ep, v, ctx=CTX)
        insts = Storage.get_meta_data_engine_instances().get_all()
        assert len(insts) == 1
        assert insts[0].status == RunStatus.FAILED
        assert "sanity check failed" in insts[0].env["error"]

    def test_stop_after_read_aborts(self):
        v = self._variant(algos=[{"name": "algo"}])
        engine, ep = build_engine(v)
        iid = run_train(
            engine, ep, v, WorkflowParams(stop_after_read=True), ctx=CTX
        )
        assert (
            Storage.get_meta_data_engine_instances().get(iid).status
            == RunStatus.ABORTED
        )
        assert Storage.get_model_data_models().get(iid) is None

    def test_load_models_missing_instance(self):
        v = self._variant(algos=[{"name": "algo"}])
        engine, ep = build_engine(v)
        with pytest.raises(RuntimeError, match="no models stored"):
            load_models_for_instance("ghost", engine, ep, CTX)


class TestRunEvaluation:
    def test_records_result(self):
        from pio_tpu.controller import EngineParamsGenerator, Evaluation

        engine = fixture_engine()
        candidates = [
            engine.params_from_variant(
                variant(ds={"id": 1, "eval_folds": 1},
                        algos=[{"name": "algo", "params": {"mult": m}}])
            )
            for m in (1, 2)
        ]
        result = run_evaluation(
            Evaluation(engine, NegAbsErr()),
            EngineParamsGenerator(candidates),
            ctx=CTX,
        )
        assert result.best_score == 0.0
        done = Storage.get_meta_data_evaluation_instances().get_completed()
        assert len(done) == 1
        assert "NegAbsErr" in done[0].evaluator_results
        parsed = json.loads(done[0].evaluator_results_json)
        assert parsed["bestIndex"] == 1
