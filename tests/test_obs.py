"""Unit tests for pio_tpu/obs — the metrics registry, text exposition
(round-tripped through the promparse parser the way a real scraper
would), stage tracing, and cross-worker shared-memory aggregation."""

import os
import tempfile
import threading

import pytest

from pio_tpu.obs import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    RequestWindow,
    Tracer,
    escape_help,
    escape_label_value,
    monotonic_s,
)
from pio_tpu.obs import promparse
from pio_tpu.obs.promparse import parse_prometheus_text
from pio_tpu.obs.shm import PoolMetricsSegment


def render_parse(reg, pool=True):
    return parse_prometheus_text("\n".join(reg.render(pool=pool)))


class TestRegistry:
    def test_counter_inc_and_render(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "things", ("kind",))
        c.inc(kind="a")
        c.inc(2, kind="a")
        c.inc(kind="b")
        pm = render_parse(reg)
        assert pm.value("t_total", kind="a") == 3
        assert pm.value("t_total", kind="b") == 1
        assert pm.types["t_total"] == "counter"
        assert pm.helps["t_total"] == "things"

    def test_counter_rejects_negative(self):
        c = MetricsRegistry().counter("n_total", "n")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set(self):
        reg = MetricsRegistry()
        g = reg.gauge("g", "a gauge")
        g.set(4.5)
        g.inc(0.5)
        assert render_parse(reg).value("g") == 5.0

    def test_registration_idempotent(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", "x", ("l",))
        b = reg.counter("x_total", "x", ("l",))
        assert a is b

    def test_registration_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.counter("x_total", "x", ("l",))
        with pytest.raises(ValueError):
            reg.counter("x_total", "x", ("other",))
        with pytest.raises(ValueError):
            reg.gauge("x_total", "x", ("l",))

    def test_help_and_label_escaping_round_trip(self):
        reg = MetricsRegistry()
        nasty = 'sla\\sh "quote"\nnewline'
        c = reg.counter("esc_total", 'help with \\ and\nnewline', ("path",))
        c.inc(path=nasty)
        text = "\n".join(reg.render())
        # escaped on the wire: no raw newline inside any sample line
        assert '\\n' in text
        pm = parse_prometheus_text(text)
        assert pm.value("esc_total", path=nasty) == 1
        assert pm.helps["esc_total"] == 'help with \\ and\nnewline'

    def test_escape_helpers(self):
        assert escape_help("a\\b\nc") == "a\\\\b\\nc"
        assert escape_label_value('a"b\nc\\d') == 'a\\"b\\nc\\\\d'

    def test_collector_lines_appended_and_errors_swallowed(self):
        reg = MetricsRegistry()
        reg.counter("a_total", "a").inc()
        reg.add_collector(lambda: ["extra_metric 42"])
        reg.add_collector(lambda: 1 / 0)  # must not kill /metrics
        pm = render_parse(reg)
        assert pm.value("a_total") == 1
        assert pm.value("extra_metric") == 42


class TestHistogram:
    def test_bucket_monotonicity_and_count(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "lat", buckets=(0.001, 0.01, 0.1))
        for v in (0.0005, 0.005, 0.05, 0.5, 0.0009):
            h.observe(v)
        pm = render_parse(reg)
        buckets = pm.histogram_buckets("lat_seconds")
        assert [le for le, _ in buckets] == [0.001, 0.01, 0.1, float("inf")]
        cums = [c for _, c in buckets]
        assert cums == sorted(cums)  # cumulative => monotone
        assert cums[-1] == 5
        assert cums[0] == 2  # 0.0005 and 0.0009
        assert pm.value("lat_seconds_count") == 5
        assert pm.value("lat_seconds_sum") == pytest.approx(0.5564)

    def test_observation_on_edge_goes_to_that_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("e_seconds", "e", buckets=(1.0, 2.0))
        h.observe(1.0)  # le="1" is inclusive per Prometheus semantics
        pm = render_parse(reg)
        assert dict(
            (le, c) for le, c in pm.histogram_buckets("e_seconds")
        )[1.0] == 1

    def test_quantile_interpolation(self):
        reg = MetricsRegistry()
        h = reg.histogram("q_seconds", "q", buckets=(0.1, 0.2, 0.4))
        cell = h.labels()
        for _ in range(100):
            cell.observe(0.15)  # all in the (0.1, 0.2] bucket
        q50 = cell.quantile(0.5)
        assert 0.1 <= q50 <= 0.2
        assert cell.quantile(0.99) <= 0.2

    def test_quantile_empty_is_none(self):
        h = MetricsRegistry().histogram("z_seconds", "z")
        assert h.labels().quantile(0.5) is None

    def test_default_buckets_sorted(self):
        assert list(DEFAULT_LATENCY_BUCKETS) == sorted(DEFAULT_LATENCY_BUCKETS)


class TestRequestWindow:
    def test_cumulative_and_percentiles(self):
        w = RequestWindow()
        for ms in (1.0, 2.0, 3.0, 4.0, 100.0):
            w.record(ms)
        w.record(5.0, error=True)
        d = w.to_dict()
        assert d["requestCount"] == 6
        assert d["errorCount"] == 1
        assert d["avgMs"] == pytest.approx(115.0 / 6, abs=0.01)
        assert d["p50Ms"] <= d["p95Ms"] <= d["p99Ms"]

    def test_window_view(self):
        w = RequestWindow()
        w.record(7.0)
        d = w.window(60.0)
        assert d["windowSeconds"] == 60.0
        assert d["requestCount"] == 1
        assert d["p50Ms"] == 7.0
        # nothing recorded in a zero-width recent window
        assert w.window(0.0)["requestCount"] == 0

    def test_ring_bounded(self):
        w = RequestWindow(cap=8)
        for i in range(100):
            w.record(float(i))
        assert w.count == 100
        assert len(w._ring) == 8


class TestTracer:
    def test_spans_feed_histogram_and_ring(self):
        reg = MetricsRegistry()
        tracer = Tracer("demo", registry=reg, stages=("a", "b"))
        with tracer.trace("req", user="u1") as tr:
            with tr.span("a"):
                pass
            tr.add_span("b", 0.25)
        pm = render_parse(reg)
        assert pm.value("pio_tpu_demo_stage_seconds_count", stage="a") == 1
        assert pm.value("pio_tpu_demo_stage_seconds_sum", stage="b") == 0.25
        recent = tracer.recent()
        assert len(recent) == 1
        t = recent[0]
        assert t["kind"] == "req" and t["meta"] == {"user": "u1"}
        assert [s["stage"] for s in t["spans"]] == ["a", "b"]
        assert t["spans"][1]["durMs"] == 250.0
        assert not t["error"]

    def test_exception_marks_error_and_still_records(self):
        tracer = Tracer("err")
        with pytest.raises(RuntimeError):
            with tracer.trace("boom"):
                raise RuntimeError("x")
        assert tracer.recent()[0]["error"] is True

    def test_ring_bounded_and_slowest_first(self):
        tracer = Tracer("ring", ring=4)
        for i in range(10):
            with tracer.trace(f"k{i}") as tr:
                tr.add_span("s", 0.0)
        assert len(tracer.recent(n=100)) == 4
        slow = tracer.recent(n=4, slowest=True)
        totals = [t["totalMs"] for t in slow]
        assert totals == sorted(totals, reverse=True)

    def test_stage_cells_precreated_for_pool_layout(self):
        reg = MetricsRegistry()
        Tracer("pre", registry=reg, stages=("x", "y"))
        pm = render_parse(reg)
        # declared stages expose zero-count cells before any traffic
        assert pm.value("pio_tpu_pre_stage_seconds_count", stage="x") == 0
        assert pm.value("pio_tpu_pre_stage_seconds_count", stage="y") == 0


@pytest.fixture()
def seg_path(tmp_path):
    return str(tmp_path / "metrics.shm")


def _make_worker_registry():
    """The same registration sequence in every 'worker' — layout parity
    is what makes registration-order slot assignment correct."""
    reg = MetricsRegistry()
    c = reg.counter("w_total", "w", ("k",))
    c.labels("x")
    h = reg.histogram("w_seconds", "w lat", buckets=(0.1, 1.0))
    h.labels()
    return reg, c, h


class TestPoolSegment:
    def test_create_open_read_write(self, seg_path):
        seg = PoolMetricsSegment.create(seg_path, n_workers=3,
                                        slots_per_worker=8)
        seg.set(2, 7, 1.5)
        assert seg.read(2, 7) == 1.5
        assert seg.sum_slot(7) == 1.5
        reopened = PoolMetricsSegment.open(seg_path)
        assert reopened.n_workers == 3
        assert reopened.slots_per_worker == 8
        assert reopened.read(2, 7) == 1.5
        reopened.close()
        seg.unlink()
        assert not os.path.exists(seg_path)

    def test_open_rejects_garbage(self, tmp_path):
        p = tmp_path / "junk"
        p.write_bytes(b"not a segment at all................")
        with pytest.raises(ValueError):
            PoolMetricsSegment.open(str(p))

    def test_bounds_checked(self, seg_path):
        seg = PoolMetricsSegment.create(seg_path, 1, slots_per_worker=4)
        with pytest.raises(IndexError):
            seg.set(1, 0, 1.0)
        with pytest.raises(IndexError):
            seg.read(0, 4)
        seg.unlink()

    def test_cross_worker_sum(self, seg_path):
        """The acceptance-criteria mechanism, in-process: two registries
        bound as worker 0 and 1 of one segment — a scrape of EITHER
        reports the pool-wide totals."""
        PoolMetricsSegment.create(seg_path, n_workers=2)
        r0, c0, h0 = _make_worker_registry()
        r1, c1, h1 = _make_worker_registry()
        r0.bind_pool_segment(PoolMetricsSegment.open(seg_path), 0)
        r1.bind_pool_segment(PoolMetricsSegment.open(seg_path), 1)
        for _ in range(3):
            c0.inc(k="x")
        for _ in range(2):
            c1.inc(k="x")
        h0.observe(0.05)
        h1.observe(0.5)
        for reg in (r0, r1):  # both workers expose the same pool totals
            pm = render_parse(reg)
            assert pm.value("w_total", k="x") == 5
            assert pm.value("w_seconds_count") == 2
            buckets = dict(pm.histogram_buckets("w_seconds"))
            assert buckets[0.1] == 1 and buckets[1.0] == 2
        # local (pool=False) view stays per-worker
        assert render_parse(r0, pool=False).value("w_total", k="x") == 3

    def test_respawned_worker_adopts_stripe(self, seg_path):
        """A crashed worker's replacement rebinds the same stripe and
        must ADOPT its value — pool totals survive worker respawn."""
        PoolMetricsSegment.create(seg_path, n_workers=2)
        r0, c0, _ = _make_worker_registry()
        r0.bind_pool_segment(PoolMetricsSegment.open(seg_path), 0)
        c0.inc(4, k="x")
        # "respawn": fresh registry, same worker index
        r0b, c0b, _ = _make_worker_registry()
        r0b.bind_pool_segment(PoolMetricsSegment.open(seg_path), 0)
        assert c0b.value("x") == 4
        c0b.inc(k="x")
        assert c0b.value("x") == 5

    def test_gauges_never_bound(self, seg_path):
        PoolMetricsSegment.create(seg_path, n_workers=2)
        reg = MetricsRegistry()
        g = reg.gauge("up", "uptime")
        g.set(10)
        reg.bind_pool_segment(PoolMetricsSegment.open(seg_path), 0)
        reg2 = MetricsRegistry()
        g2 = reg2.gauge("up", "uptime")
        g2.set(99)
        reg2.bind_pool_segment(PoolMetricsSegment.open(seg_path), 1)
        # each worker's gauge stays local — no cross-stripe summing
        assert render_parse(reg).value("up") == 10
        assert render_parse(reg2).value("up") == 99

    def test_segment_too_small_raises(self, seg_path):
        PoolMetricsSegment.create(seg_path, 1, slots_per_worker=2)
        reg, _, _ = _make_worker_registry()  # needs 1 + (2+1+2) slots
        with pytest.raises(ValueError, match="too small"):
            reg.bind_pool_segment(PoolMetricsSegment.open(seg_path), 0)

    def test_concurrent_observe_under_binding(self, seg_path):
        """Counter increments from several threads while bound: the
        stripe must end up exactly at the true total (per-cell lock)."""
        PoolMetricsSegment.create(seg_path, n_workers=1)
        reg, c, _ = _make_worker_registry()
        seg = PoolMetricsSegment.open(seg_path)
        reg.bind_pool_segment(seg, 0)

        def spin():
            for _ in range(500):
                c.inc(k="x")

        threads = [threading.Thread(target=spin) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value("x") == 2000
        assert seg.read(0, 0) == 2000


class TestPromParse:
    def test_summary_quantile_lines(self):
        pm = parse_prometheus_text(
            '# TYPE lat_ms summary\n'
            'lat_ms{quantile="0.5"} 1.25\n'
            'lat_ms{quantile="0.95"} 9\n'
            'lat_ms_sum 100\nlat_ms_count 42\n'
        )
        assert pm.value("lat_ms", quantile="0.5") == 1.25
        assert pm.value("lat_ms_count") == 42

    def test_histogram_quantile_estimate(self):
        pm = parse_prometheus_text(
            'h_bucket{le="0.1"} 0\n'
            'h_bucket{le="0.2"} 100\n'
            'h_bucket{le="+Inf"} 100\n'
            'h_count 100\nh_sum 15\n'
        )
        q = pm.histogram_quantile("h", 0.5)
        assert 0.1 <= q <= 0.2

    def test_inf_value_parsing(self):
        pm = parse_prometheus_text("x +Inf\ny -Inf\n")
        assert pm.value("x") == float("inf")
        assert pm.value("y") == float("-inf")

    @pytest.mark.parametrize("raw", [
        'back\\slash', 'dou"ble', 'new\nline', '\\', '"', '\n',
        'all\\three"at\nonce', 'trailing\\',
    ])
    def test_escaped_label_values_round_trip(self, raw):
        # what the registry renders, the parser must read back verbatim
        reg = MetricsRegistry()
        reg.counter("esc_total", "", ("val",)).inc(3, val=raw)
        pm = render_parse(reg)
        assert pm.value("esc_total", val=raw) == 3

    def test_escaped_label_value_literal_line(self):
        # against a hand-written line too, not just our own renderer
        pm = parse_prometheus_text(
            'e_total{a="x\\\\y",b="q\\"r",c="s\\nt"} 7\n'
        )
        assert pm.value("e_total", a="x\\y", b='q"r', c="s\nt") == 7

    def test_help_text_unescapes(self):
        pm = parse_prometheus_text(
            "# HELP weird line one\\nline two with \\\\ slash\n"
        )
        assert pm.helps["weird"] == "line one\nline two with \\ slash"

    def test_inf_only_bucket_histogram(self):
        # a scraped histogram may carry ONLY the mandatory +Inf bucket;
        # the quantile estimate must clamp, not divide by a missing edge
        pm = parse_prometheus_text(
            'h1_bucket{le="+Inf"} 5\nh1_sum 10\nh1_count 5\n'
        )
        assert pm.histogram_buckets("h1") == [(float("inf"), 5.0)]
        assert pm.histogram_quantile("h1", 0.99) == 0.0

    def test_empty_histogram_has_no_quantile(self):
        pm = parse_prometheus_text('h2_bucket{le="+Inf"} 0\nh2_count 0\n')
        assert pm.histogram_quantile("h2", 0.5) is None


class TestMonotonicClock:
    def test_is_monotonic_and_subsecond(self):
        a = monotonic_s()
        b = monotonic_s()
        assert b >= a
        assert isinstance(a, float)


class TestProfileHook:
    def test_inert_without_directory(self, monkeypatch):
        from pio_tpu.obs.profile import DeviceProfileHook

        monkeypatch.delenv("PIO_TPU_PROFILE", raising=False)
        hook = DeviceProfileHook.from_env()
        assert not hook.enabled
        with hook.capture():
            pass  # must be a no-op, not start a trace

    def test_from_env_reads_directory_and_n(self, monkeypatch):
        from pio_tpu.obs.profile import DeviceProfileHook

        monkeypatch.setenv("PIO_TPU_PROFILE", "/tmp/prof")
        monkeypatch.setenv("PIO_TPU_PROFILE_EXECUTIONS", "3")
        hook = DeviceProfileHook.from_env()
        assert hook.enabled
        assert hook.directory == "/tmp/prof"
        assert hook.first_n == 3


def _assert_parsed_equal(a, b):
    assert a.samples == b.samples
    assert a.types == b.types
    assert a.helps == b.helps
    assert a.exemplars == b.exemplars


class TestPromMerge:
    """promparse.merge / with_labels / render — the federation algebra
    (ISSUE 11 satellite: counters sum, gauges last-write-wins,
    histograms add bucket-wise, type conflicts are loud)."""

    A = (
        "# HELP q_total served\n"
        "# TYPE q_total counter\n"
        'q_total{code="200"} 3\n'
        "# TYPE temp gauge\n"
        "temp 20\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="+Inf"} 2\n'
        "lat_seconds_sum 0.6\n"
        "lat_seconds_count 2\n"
    )
    B = (
        "# TYPE q_total counter\n"
        'q_total{code="200"} 4\n'
        'q_total{code="500"} 1\n'
        "# TYPE temp gauge\n"
        "temp 25\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 5\n'
        'lat_seconds_bucket{le="+Inf"} 7\n'
        "lat_seconds_sum 2.0\n"
        "lat_seconds_count 7\n"
    )

    def test_counter_sum_gauge_lww_histogram_bucketwise(self):
        m = promparse.merge(parse_prometheus_text(self.A),
                            parse_prometheus_text(self.B))
        assert m.value("q_total", code="200") == 7       # summed
        assert m.value("q_total", code="500") == 1       # union
        assert m.value("temp") == 25                     # later wins
        buckets = dict(m.histogram_buckets("lat_seconds"))
        assert buckets[0.1] == 6 and buckets[float("inf")] == 9
        assert m.value("lat_seconds_sum") == pytest.approx(2.6)
        assert m.value("lat_seconds_count") == 9

    def test_merge_is_identity_for_one_scrape(self):
        pm = parse_prometheus_text(self.A)
        _assert_parsed_equal(promparse.merge(pm), pm)

    def test_conflicting_types_raise(self):
        a = parse_prometheus_text("# TYPE x counter\nx 1\n")
        b = parse_prometheus_text("# TYPE x gauge\nx 2\n")
        with pytest.raises(ValueError, match="conflicting TYPE"):
            promparse.merge(a, b)

    def test_untyped_total_suffix_sums_untyped_other_lww(self):
        a = parse_prometheus_text("mystery_total 2\nmystery_level 9\n")
        b = parse_prometheus_text("mystery_total 3\nmystery_level 4\n")
        m = promparse.merge(a, b)
        assert m.value("mystery_total") == 5   # counter naming discipline
        assert m.value("mystery_level") == 4   # point sample: last wins

    def test_inf_only_bucket_histogram_merges(self):
        text = (
            "# TYPE all_seconds histogram\n"
            'all_seconds_bucket{le="+Inf"} 3\n'
            "all_seconds_sum 1.5\n"
            "all_seconds_count 3\n"
        )
        m = promparse.merge(parse_prometheus_text(text),
                            parse_prometheus_text(text))
        assert m.histogram_buckets("all_seconds") == [(float("inf"), 6)]
        rt = parse_prometheus_text("\n".join(promparse.render(m)))
        _assert_parsed_equal(rt, m)

    def test_with_labels_injects_and_overrides(self):
        pm = parse_prometheus_text(
            "# TYPE q_total counter\n"
            'q_total{code="200",pio_tpu_member="stale"} 3\n'
        )
        out = promparse.with_labels(pm, pio_tpu_member="h:1")
        assert out.value("q_total", code="200", pio_tpu_member="h:1") == 3
        assert len(out.samples) == 1  # the stale member label was replaced

    def test_member_labeled_sums_equal_per_member_scrapes(self):
        """The acceptance identity: sum over the injected member label
        of the federated scrape == sum of the raw per-member scrapes."""
        pa, pb = parse_prometheus_text(self.A), parse_prometheus_text(self.B)
        fed = promparse.merge(
            promparse.with_labels(pa, pio_tpu_member="a:1"),
            promparse.with_labels(pb, pio_tpu_member="b:2"),
        )
        fed_sum = sum(fed.family("q_total").values())
        raw_sum = (sum(pa.family("q_total").values())
                   + sum(pb.family("q_total").values()))
        assert fed_sum == raw_sum == 8

    def test_render_round_trips_escapes_and_exemplars(self):
        text = (
            "# HELP odd_total has \\\\ and \\n in help\n"
            "# TYPE odd_total counter\n"
            'odd_total{path="a\\\\b",msg="say \\"hi\\"\\nbye"} 2\n'
            "# TYPE rt_seconds histogram\n"
            'rt_seconds_bucket{le="0.5"} 1 # {trace_id="q-7"} 0.0042\n'
            'rt_seconds_bucket{le="+Inf"} 1\n'
            "rt_seconds_sum 0.0042\n"
            "rt_seconds_count 1\n"
        )
        pm = parse_prometheus_text(text)
        assert pm.exemplar("rt_seconds_bucket", le="0.5") == (
            {"trace_id": "q-7"}, 0.0042
        )
        rt = parse_prometheus_text("\n".join(promparse.render(pm)))
        _assert_parsed_equal(rt, pm)

    def test_registry_render_round_trips_through_promparse_render(self):
        """Property-style: a real registry's exposition survives
        parse -> render -> parse unchanged."""
        reg = MetricsRegistry()
        c = reg.counter("p_q_total", 'weird "help" \\ here', ("code",))
        c.inc(3, code='2"00')
        g = reg.gauge("p_depth", "queue depth")
        g.set(-4.25)
        h = reg.histogram("p_lat_seconds", "lat", buckets=(0.01, 0.1))
        for v in (0.005, 0.05, 5.0):
            h.observe(v)
        pm = render_parse(reg)
        once = parse_prometheus_text("\n".join(promparse.render(pm)))
        _assert_parsed_equal(once, pm)
        twice = parse_prometheus_text("\n".join(promparse.render(once)))
        _assert_parsed_equal(twice, once)

    def test_labeled_histogram_quantile_merges_cells(self):
        """Histogram.quantile() pools every label cell — what bench
        reads now pio_tpu_repl_ack_seconds is per-partition/follower."""
        reg = MetricsRegistry()
        h = reg.histogram("m_seconds", "x", ("part",),
                          buckets=(0.1, 1.0, 10.0))
        for _ in range(99):
            h.observe(0.05, part="0")
        h.observe(5.0, part="1")
        q = h.quantile(0.5)
        assert q is not None and q <= 0.1
        assert h.quantile(0.999) > 1.0
        empty = reg.histogram("n_seconds", "y", ("part",))
        assert empty.quantile(0.95) is None


class TestPoolSegmentGenerations:
    """Stripe generation words (ISSUE 11 satellite): spawn bumps,
    retirement freezes negative, totals never vanish from sums."""

    def test_lifecycle_bump_adopt_retire(self, seg_path):
        seg = PoolMetricsSegment.create(seg_path, n_workers=3,
                                        slots_per_worker=4)
        assert seg.generations() == [0, 0, 0]   # never owned
        assert seg.bump_generation(0) == 1      # first spawn
        assert seg.bump_generation(0) == 2      # respawn adopts
        assert seg.generation(0) == 2
        assert seg.retire_stripe(0) == -2       # frozen, history kept
        assert seg.generation(0) == -2
        # bump after retire = budget-respawn never happens, but the
        # algebra stays sane: abs+1
        assert seg.bump_generation(0) == 3
        seg.unlink()

    def test_generations_persist_across_reopen_and_data_intact(
            self, seg_path):
        seg = PoolMetricsSegment.create(seg_path, n_workers=2,
                                        slots_per_worker=4)
        seg.set(0, 1, 7.5)
        seg.set(1, 1, 2.5)
        seg.bump_generation(0)
        seg.bump_generation(1)
        seg.retire_stripe(1)
        reopened = PoolMetricsSegment.open(seg_path)
        assert reopened.generations() == [1, -1]
        # retired stripe still contributes to the pool-wide sum
        assert reopened.sum_slot(1) == 10.0
        assert reopened.read(0, 1) == 7.5
        reopened.close()
        seg.unlink()

    def test_set_generation_bounds_checked(self, seg_path):
        seg = PoolMetricsSegment.create(seg_path, n_workers=1,
                                        slots_per_worker=2)
        with pytest.raises(IndexError):
            seg.generation(1)
        with pytest.raises(IndexError):
            seg.bump_generation(-1)
        seg.unlink()
