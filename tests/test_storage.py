"""Storage conformance suite: one spec, many backends (SURVEY.md §4).

Mirrors the reference's LEventsSpec / PEventsSpec pattern parameterized over
backends, plus meta-store CRUD, model store, EventFrame, and registry tests.
"""

import dataclasses
import datetime as dt

import numpy as np
import pytest

from pio_tpu.data import DataMap, Event
from pio_tpu.storage import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
    RunStatus,
    Storage,
)
from pio_tpu.storage.localfs import LocalFSModels
from pio_tpu.storage.memory import (
    MemAccessKeys,
    MemApps,
    MemChannels,
    MemEngineInstances,
    MemEvaluationInstances,
    MemLEvents,
    MemModels,
    MemPEvents,
)
from pio_tpu.storage.parquet import ParquetPEvents
from pio_tpu.storage.sqlite import (
    SQLiteAccessKeys,
    SQLiteApps,
    SQLiteChannels,
    SQLiteClient,
    SQLiteEngineInstances,
    SQLiteEvaluationInstances,
    SQLiteEvents,
    SQLiteModels,
    SQLitePEvents,
)


def T(h, m=0):
    return dt.datetime(2026, 2, 1, h, m, tzinfo=dt.timezone.utc)


def ev(name, t, eid="u1", etype="user", target=None, props=None):
    return Event(
        name,
        etype,
        eid,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=props or {},
        event_time=t,
    )


@pytest.fixture()
def sqlite_client(tmp_path):
    return SQLiteClient(str(tmp_path / "test.db"))


# ------------------------------------------------------------------ LEvents
def _eventlog(tmp_path):
    from pio_tpu.native import NativeUnavailable

    try:
        from pio_tpu.storage.eventlog import EventLogEvents

        return EventLogEvents(str(tmp_path / "eventlog"))
    except NativeUnavailable as e:  # no toolchain in this environment
        pytest.skip(f"native eventlog unavailable: {e}")


@pytest.fixture(params=["memory", "sqlite", "eventlog", "searchable",
                        "partlog"])
def levents(request, tmp_path):
    if request.param == "memory":
        return MemLEvents()
    if request.param == "eventlog":
        return _eventlog(tmp_path)
    if request.param == "partlog":
        from pio_tpu.storage.partlog import PartitionedEventLog

        return PartitionedEventLog(str(tmp_path / "partlog"), partitions=3)
    if request.param == "searchable":
        from pio_tpu.storage.searchable import (
            SearchableClient, SearchableEvents,
        )

        return SearchableEvents(SearchableClient(str(tmp_path / "se.db")))
    return SQLiteEvents(SQLiteClient(str(tmp_path / "le.db")))


class TestLEventsConformance:
    def test_insert_batch(self, levents):
        """Bulk insert (the /batch/events.json storage path): ids in
        order, every event readable, channel + explicit ids honored."""
        events = [
            ev("rate", T(i), target=f"i{i}", props={"rating": float(i)})
            for i in range(1, 7)
        ]
        events[2] = dataclasses.replace(events[2], event_id="pinned-id")
        ids = levents.insert_batch(events, app_id=1)
        assert len(ids) == 6 and ids[2] == "pinned-id"
        for i, eid in enumerate(ids):
            got = levents.get(eid, 1)
            assert got is not None and got.target_entity_id == f"i{i + 1}"
        # other apps/channels don't see them
        assert levents.get(ids[0], 2) is None
        assert levents.insert_batch([], 1) == []

    def test_insert_get_delete(self, levents):
        e = ev("rate", T(1), target="i1", props={"rating": 4.0})
        eid = levents.insert(e, app_id=1)
        got = levents.get(eid, 1)
        assert got is not None
        assert got.event == "rate"
        assert got.target_entity_id == "i1"
        assert got.properties.get_double("rating") == 4.0
        assert got.event_id == eid
        assert levents.delete(eid, 1)
        assert levents.get(eid, 1) is None
        assert not levents.delete(eid, 1)

    def test_find_filters(self, levents):
        levents.insert(ev("rate", T(1), "u1", target="i1"), 1)
        levents.insert(ev("buy", T(2), "u1", target="i2"), 1)
        levents.insert(ev("rate", T(3), "u2", target="i1"), 1)
        levents.insert(ev("rate", T(4), "u9"), 2)  # other app

        assert len(levents.find(1)) == 3
        assert [e.event for e in levents.find(1, event_names=["buy"])] == ["buy"]
        # [] = "match no names" (only None means any) — same on every backend
        assert levents.find(1, event_names=[]) == []
        # explicit "" filters match nothing (no stored field is empty)
        assert levents.find(1, entity_id="") == []
        assert levents.find(1, target_entity_id="") == []
        assert levents.get("", 1) is None
        assert len(levents.find(1, entity_id="u1")) == 2
        assert len(levents.find(1, target_entity_type="item", target_entity_id="i1")) == 2
        assert len(levents.find(1, start_time=T(2))) == 2
        assert len(levents.find(1, until_time=T(2))) == 1
        assert len(levents.find(1, start_time=T(2), until_time=T(3))) == 1
        assert len(levents.find(2)) == 1

    def test_find_order_and_limit(self, levents):
        for h in (3, 1, 2):
            levents.insert(ev("rate", T(h), f"u{h}"), 1)
        times = [e.event_time for e in levents.find(1)]
        assert times == sorted(times)
        rev = levents.find(1, reversed_order=True, limit=2)
        assert [e.event_time for e in rev] == [T(3), T(2)]

    def test_channels_isolated(self, levents):
        levents.init_channel(1, 5)
        levents.insert(ev("rate", T(1)), 1, channel_id=5)
        levents.insert(ev("rate", T(2)), 1)
        assert len(levents.find(1)) == 1
        assert len(levents.find(1, channel_id=5)) == 1
        levents.remove(1, channel_id=5)
        assert len(levents.find(1, channel_id=5)) == 0
        assert len(levents.find(1)) == 1

    def test_aggregate_properties(self, levents):
        levents.insert(ev("$set", T(1), "u1", props={"a": 1, "plan": "free"}), 1)
        levents.insert(ev("$set", T(2), "u1", props={"plan": "pro"}), 1)
        levents.insert(ev("$unset", T(3), "u1", props={"a": None}), 1)
        levents.insert(ev("$set", T(1), "u2", props={"b": 2}), 1)
        levents.insert(ev("$delete", T(2), "u2"), 1)
        levents.insert(ev("rate", T(4), "u1", target="i1"), 1)

        agg = levents.aggregate_properties(1, "user")
        assert set(agg) == {"u1"}
        assert agg["u1"].to_dict() == {"plan": "pro"}

        req = levents.aggregate_properties(1, "user", required=["missing"])
        assert req == {}


# ------------------------------------------------------------------ PEvents
@pytest.fixture(params=["memory", "sqlite", "parquet", "eventlog",
                        "searchable", "partlog"])
def pevents(request, tmp_path):
    if request.param == "memory":
        return MemPEvents(MemLEvents())
    if request.param == "sqlite":
        return SQLitePEvents(SQLiteEvents(SQLiteClient(str(tmp_path / "pe.db"))))
    if request.param == "eventlog":
        from pio_tpu.storage.base import PEventsAdapter

        return PEventsAdapter(_eventlog(tmp_path))
    if request.param == "partlog":
        from pio_tpu.storage.base import PEventsAdapter
        from pio_tpu.storage.partlog import PartitionedEventLog

        return PEventsAdapter(
            PartitionedEventLog(str(tmp_path / "partlog"), partitions=3)
        )
    if request.param == "searchable":
        from pio_tpu.storage.searchable import (
            SearchableClient, SearchableEvents,
        )

        return SQLitePEvents(
            SearchableEvents(SearchableClient(str(tmp_path / "spe.db")))
        )
    return ParquetPEvents(str(tmp_path / "events"))


class TestPEventsConformance:
    def test_write_find(self, pevents):
        evs = [
            ev("rate", T(i), f"u{i % 3}", target=f"i{i}", props={"rating": float(i)})
            for i in range(1, 7)
        ]
        pevents.write(evs, app_id=1)
        out = pevents.find(1)
        assert len(out) == 6
        assert [e.event_time for e in out] == [T(i) for i in range(1, 7)]
        assert len(pevents.find(1, entity_id="u1")) == 2
        assert len(pevents.find(1, start_time=T(3), until_time=T(5))) == 2
        assert pevents.find(2) == []

    def test_write_appends(self, pevents):
        pevents.write([ev("a", T(1))], 1)
        pevents.write([ev("b", T(2))], 1)
        assert len(pevents.find(1)) == 2

    def test_bulk_delete(self, pevents):
        e1, e2 = ev("a", T(1)).with_event_id("id1"), ev("b", T(2)).with_event_id("id2")
        pevents.write([e1, e2], 1)
        pevents.delete(["id1"], 1)
        out = pevents.find(1)
        assert [e.event_id for e in out] == ["id2"]

    def test_find_frame(self, pevents):
        pevents.write(
            [ev("rate", T(i), f"u{i}", target="i1", props={"rating": i / 2}) for i in (1, 2)],
            1,
        )
        frame = pevents.find_frame(1)
        assert len(frame) == 2
        np.testing.assert_allclose(
            frame.property_column("rating"), np.array([0.5, 1.0], dtype=np.float32)
        )
        idx, codes = frame.codes("entity_id")
        assert idx.to_dict() == {"u1": 0, "u2": 1}
        assert codes.tolist() == [0, 1]

    def test_aggregate_properties(self, pevents):
        pevents.write(
            [
                ev("$set", T(1), "u1", props={"x": 1}),
                ev("$unset", T(2), "u1", props={"x": None}),
                ev("$set", T(3), "u1", props={"y": 2}),
            ],
            1,
        )
        agg = pevents.aggregate_properties(1, "user")
        assert agg["u1"].to_dict() == {"y": 2}


def test_parquet_compact(tmp_path):
    pe = ParquetPEvents(str(tmp_path / "ev"))
    pe.write([ev("a", T(1))], 1)
    pe.write([ev("b", T(2))], 1)
    pe.compact(1)
    import os

    d = pe._dir(1, None)
    assert len(os.listdir(d)) == 1
    assert len(pe.find(1)) == 2


# ------------------------------------------------------------------ meta
@pytest.fixture(params=["memory", "sqlite", "searchable"])
def meta(request, sqlite_client, tmp_path):
    if request.param == "searchable":
        from pio_tpu.storage.searchable import (
            SearchableApps,
            SearchableClient,
            SearchableEngineInstances,
            SearchableEvaluationInstances,
        )

        c = SearchableClient(str(tmp_path / "smeta.db"))
        return dict(
            apps=SearchableApps(c),
            keys=SQLiteAccessKeys(c),
            channels=SQLiteChannels(c),
            engine_instances=SearchableEngineInstances(c),
            evaluation_instances=SearchableEvaluationInstances(c),
        )
    if request.param == "memory":
        return dict(
            apps=MemApps(),
            keys=MemAccessKeys(),
            channels=MemChannels(),
            engine_instances=MemEngineInstances(),
            evaluation_instances=MemEvaluationInstances(),
        )
    return dict(
        apps=SQLiteApps(sqlite_client),
        keys=SQLiteAccessKeys(sqlite_client),
        channels=SQLiteChannels(sqlite_client),
        engine_instances=SQLiteEngineInstances(sqlite_client),
        evaluation_instances=SQLiteEvaluationInstances(sqlite_client),
    )


class TestMetaConformance:
    def test_apps_crud(self, meta):
        apps = meta["apps"]
        aid = apps.insert(App(0, "myapp", "desc"))
        assert aid
        assert apps.get(aid).name == "myapp"
        assert apps.get_by_name("myapp").id == aid
        assert apps.insert(App(0, "myapp")) is None  # duplicate name
        aid2 = apps.insert(App(0, "other"))
        assert aid2 != aid
        assert [a.name for a in apps.get_all()] == ["myapp", "other"]
        assert apps.update(App(aid, "renamed", None))
        assert apps.get(aid).name == "renamed"
        assert apps.delete(aid2)
        assert apps.get(aid2) is None

    def test_access_keys(self, meta):
        keys = meta["keys"]
        k = keys.insert(AccessKey("", 7, ("rate", "buy")))
        assert k and len(k) > 20
        got = keys.get(k)
        assert got.app_id == 7 and got.events == ("rate", "buy")
        k2 = keys.insert(AccessKey("fixed-key", 7))
        assert k2 == "fixed-key"
        assert keys.insert(AccessKey("fixed-key", 8)) is None  # dup
        assert {x.key for x in keys.get_by_app_id(7)} == {k, "fixed-key"}
        assert keys.update(AccessKey("fixed-key", 7, ("x",)))
        assert keys.get("fixed-key").events == ("x",)
        assert keys.delete(k)
        assert keys.get(k) is None

    def test_channels(self, meta):
        channels = meta["channels"]
        cid = channels.insert(Channel(0, "mobile", 7))
        assert cid
        assert channels.get(cid).name == "mobile"
        assert channels.insert(Channel(0, "bad name!", 7)) is None
        assert channels.insert(Channel(0, "x" * 17, 7)) is None
        cid2 = channels.insert(Channel(0, "web", 7))
        assert {c.name for c in channels.get_by_app_id(7)} == {"mobile", "web"}
        assert channels.delete(cid2)
        assert channels.get(cid2) is None

    def test_engine_instances(self, meta):
        ei = meta["engine_instances"]
        base_kwargs = dict(
            start_time=T(1),
            end_time=T(1),
            engine_id="rec",
            engine_version="1",
            engine_variant="engine.json",
            engine_factory="RecommendationEngine",
        )
        iid = ei.insert(EngineInstance(id="", status=RunStatus.RUNNING, **base_kwargs))
        assert iid
        got = ei.get(iid)
        assert got.status == "RUNNING"
        assert ei.get_latest_completed("rec", "1", "engine.json") is None
        ei.update(got.with_status(RunStatus.COMPLETED))
        later = EngineInstance(
            id="", status=RunStatus.COMPLETED,
            **{**base_kwargs, "start_time": T(2), "end_time": T(2)},
        )
        iid2 = ei.insert(later)
        latest = ei.get_latest_completed("rec", "1", "engine.json")
        assert latest.id == iid2
        assert len(ei.get_completed("rec", "1", "engine.json")) == 2
        assert ei.delete(iid2)
        assert ei.get(iid2) is None
        assert not ei.update(EngineInstance(id="nope", status="X", **base_kwargs))

    def test_evaluation_instances(self, meta):
        evi = meta["evaluation_instances"]
        iid = evi.insert(
            EvaluationInstance(
                id="", status=RunStatus.RUNNING, start_time=T(1), end_time=T(1),
                evaluation_class="MyEval",
            )
        )
        got = evi.get(iid)
        assert got.evaluation_class == "MyEval"
        evi.update(got.with_status(RunStatus.COMPLETED))
        assert [i.id for i in evi.get_completed()] == [iid]
        assert evi.delete(iid)


# ------------------------------------------------------------------ models
@pytest.fixture(params=["memory", "sqlite", "localfs", "blob"])
def models(request, sqlite_client, tmp_path):
    if request.param == "memory":
        return MemModels()
    if request.param == "sqlite":
        return SQLiteModels(sqlite_client)
    if request.param == "blob":
        from pio_tpu.storage.blobstore import BlobModels, open_blob_backend

        return BlobModels(
            open_blob_backend("file://" + str(tmp_path / "blobs"))
        )
    return LocalFSModels(str(tmp_path / "models"))


class TestModelsConformance:
    def test_roundtrip(self, models):
        blob = b"\x00\x01binary\xff" * 100
        models.insert(Model("inst1", blob))
        assert models.get("inst1").models == blob
        models.insert(Model("inst1", b"v2"))  # overwrite
        assert models.get("inst1").models == b"v2"
        assert models.get("missing") is None
        assert models.delete("inst1")
        assert not models.delete("inst1")


# ------------------------------------------------------------------ frame
class TestEventFrame:
    def test_to_device_arrays_unsharded(self):
        from pio_tpu.storage.frame import EventFrame

        frame = EventFrame.from_events(
            [ev("rate", T(i), f"u{i}", target="i1", props={"rating": float(i)}) for i in (1, 2, 3)]
        )
        _, codes = frame.codes("entity_id")
        arrays = frame.to_device_arrays(
            {"user": codes, "rating": frame.property_column("rating")}
        )
        assert arrays["user"].shape == (3,)
        assert float(arrays["mask"].sum()) == 3.0

    def test_to_device_arrays_sharded_pads(self):
        import jax
        from jax.sharding import Mesh

        from pio_tpu.storage.frame import EventFrame

        frame = EventFrame.from_events(
            [ev("rate", T(i), f"u{i}") for i in range(1, 6)]  # 5 rows on 8 devices
        )
        mesh = Mesh(np.array(jax.devices()), ("data",))
        _, codes = frame.codes("entity_id")
        arrays = frame.to_device_arrays({"user": codes}, mesh=mesh)
        assert arrays["user"].shape == (8,)  # padded to mesh multiple
        assert float(arrays["mask"].sum()) == 5.0
        assert arrays["user"].sharding.spec == jax.sharding.PartitionSpec("data")

    def test_codes_with_existing_index(self):
        from pio_tpu.data.bimap import BiMap
        from pio_tpu.storage.frame import EventFrame

        frame = EventFrame.from_events([ev("r", T(1), "u1"), ev("r", T(2), "uX")])
        idx = BiMap.string_int(["u1", "u2"])
        _, codes = frame.codes("entity_id", index=idx)
        assert codes.tolist() == [0, -1]  # unseen id masked as -1


# ------------------------------------------------------------------ registry
class TestRegistry:
    def test_defaults_sqlite(self, tmp_home, monkeypatch):
        for var in list(__import__("os").environ):
            if var.startswith("PIO_STORAGE_"):
                monkeypatch.delenv(var)
        Storage.reset()
        apps = Storage.get_meta_data_apps()
        aid = apps.insert(App(0, "regtest"))
        assert Storage.get_meta_data_apps().get(aid).name == "regtest"
        assert (tmp_home / "pio.db").exists()
        checks = Storage.verify_all_data_objects()
        assert all(checks.values()), checks
        Storage.reset()

    def test_env_wiring_parquet_events(self, tmp_home, monkeypatch):
        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "PQ")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_PQ_TYPE", "parquet")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_PQ_PATH", str(tmp_home / "ev"))
        Storage.reset()
        pe = Storage.get_pevents()
        pe.write([ev("rate", T(1))], 1)
        assert len(pe.find(1)) == 1
        assert (tmp_home / "ev").exists()
        Storage.reset()

    def test_env_wiring_memory(self, tmp_home, monkeypatch):
        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "MEM")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
        Storage.reset()
        le = Storage.get_levents()
        le.insert(ev("rate", T(1)), 1)
        # PEvents over the same memory store sees the event
        assert len(Storage.get_pevents().find(1)) == 1
        Storage.reset()

    def test_bad_source(self, tmp_home, monkeypatch):
        from pio_tpu.storage import StorageConfigError

        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "NOPE")
        Storage.reset()
        with pytest.raises(StorageConfigError):
            Storage.get_meta_data_apps()
        Storage.reset()


# ------------------------------------------------------- schema migrations
class TestSchemaMigrations:
    def test_fresh_db_stamped_current(self, tmp_path):
        from pio_tpu.storage import sqlite as sq

        c = sq.SQLiteClient(str(tmp_path / "v.db"))
        assert sq.SQLiteClient.schema_version(c.conn()) == sq.SCHEMA_VERSION

    def test_migration_ladder_applies_and_stamps(self, tmp_path, monkeypatch):
        from pio_tpu.storage import sqlite as sq

        path = str(tmp_path / "m.db")
        sq.SQLiteClient(path)  # create at v1
        monkeypatch.setattr(sq, "SCHEMA_VERSION", 2)
        monkeypatch.setattr(
            sq, "MIGRATIONS",
            {1: ["ALTER TABLE apps ADD COLUMN note TEXT"]},
        )
        c = sq.SQLiteClient(path)
        assert sq.SQLiteClient.schema_version(c.conn()) == 2
        c.conn().execute("SELECT note FROM apps")  # column exists
        sq.SQLiteClient(path)  # idempotent reopen at current version

    def test_failed_migration_rolls_back_whole_step(
        self, tmp_path, monkeypatch
    ):
        import sqlite3

        from pio_tpu.storage import sqlite as sq

        path = str(tmp_path / "f.db")
        sq.SQLiteClient(path)
        monkeypatch.setattr(sq, "SCHEMA_VERSION", 2)
        monkeypatch.setattr(
            sq, "MIGRATIONS",
            {1: ["ALTER TABLE apps ADD COLUMN note TEXT",
                 "THIS IS NOT SQL"]},
        )
        with pytest.raises(sqlite3.OperationalError):
            sq.SQLiteClient(path)
        conn = sqlite3.connect(path)
        # stamped version unchanged AND the step's first statement undone
        assert conn.execute("PRAGMA user_version").fetchone()[0] == 1
        with pytest.raises(sqlite3.OperationalError):
            conn.execute("SELECT note FROM apps")
        conn.close()

    def test_newer_schema_refused(self, tmp_path):
        import sqlite3

        from pio_tpu.storage import sqlite as sq
        from pio_tpu.storage.base import StorageError

        path = str(tmp_path / "n.db")
        sq.SQLiteClient(path)
        conn = sqlite3.connect(path)
        conn.execute("PRAGMA user_version = 99")
        conn.commit()
        conn.close()
        with pytest.raises(StorageError, match="newer"):
            sq.SQLiteClient(path)

    def test_pre_versioning_db_goes_through_ladder(
        self, tmp_path, monkeypatch
    ):
        import sqlite3

        from pio_tpu.storage import sqlite as sq

        path = str(tmp_path / "pre.db")
        sq.SQLiteClient(path)
        conn = sqlite3.connect(path)
        conn.execute("PRAGMA user_version = 0")  # simulate pre-versioning
        conn.commit()
        conn.close()
        monkeypatch.setattr(sq, "SCHEMA_VERSION", 2)
        monkeypatch.setattr(
            sq, "MIGRATIONS",
            {1: ["ALTER TABLE apps ADD COLUMN note TEXT"]},
        )
        c = sq.SQLiteClient(path)
        # the migration MUST have run (not fast-forward stamped past it)
        assert sq.SQLiteClient.schema_version(c.conn()) == 2
        c.conn().execute("SELECT note FROM apps")

    def test_missing_migration_step_is_clear_error(
        self, tmp_path, monkeypatch
    ):
        from pio_tpu.storage import sqlite as sq
        from pio_tpu.storage.base import StorageError

        path = str(tmp_path / "gap.db")
        sq.SQLiteClient(path)
        monkeypatch.setattr(sq, "SCHEMA_VERSION", 3)
        monkeypatch.setattr(sq, "MIGRATIONS", {2: ["SELECT 1"]})
        with pytest.raises(StorageError, match="no migration registered"):
            sq.SQLiteClient(path)
