"""Text-classification template tests: TF-IDF, sparse MLP, e2e lifecycle.

Mirrors the upstream text-classification quickstart scenario: $set documents
(text + label) → TF-IDF bags → train → query label (BASELINE.json config #4).
"""

import datetime as dt

import numpy as np
import pytest

import pio_tpu.templates  # noqa: F401  (registers engine factories)
from pio_tpu.controller import ComputeContext
from pio_tpu.data import Event
from pio_tpu.models.mlp import MLPConfig, train_mlp
from pio_tpu.models.tfidf import TfIdfVectorizer, tokenize
from pio_tpu.ops.embedding import pack_bags
from pio_tpu.storage import App, Storage
from pio_tpu.templates.textclassification import PredictedResult, Query
from pio_tpu.workflow import (
    build_engine,
    load_models_for_instance,
    run_train,
    variant_from_dict,
)


# --------------------------------------------------------------- featurizer
class TestTfIdf:
    def test_tokenize(self):
        assert tokenize("Hello, TPU-world! it's 42") == [
            "hello", "tpu", "world", "it's", "42",
        ]

    def test_fit_reserves_pad_row(self):
        vec = TfIdfVectorizer.fit(["a b", "b c"])
        assert 0 not in vec.vocab.values()
        assert vec.n_features == len(vec.vocab) + 1

    def test_rare_tokens_weigh_more(self):
        docs = ["common rare", "common", "common other"]
        vec = TfIdfVectorizer.fit(docs)
        ids, w = vec.transform_doc("common rare")
        weights = dict(zip(ids, w))
        assert weights[vec.vocab["rare"]] > weights[vec.vocab["common"]]

    def test_transform_l2_normalized(self):
        vec = TfIdfVectorizer.fit(["x y z", "x q"])
        _, w = vec.transform_doc("x y z q")
        assert np.linalg.norm(w) == pytest.approx(1.0, abs=1e-5)

    def test_unknown_tokens_dropped(self):
        vec = TfIdfVectorizer.fit(["alpha beta"])
        ids, w = vec.transform_doc("gamma delta")
        assert ids == [] and w == []

    def test_max_features_caps_vocab(self):
        docs = [f"tok{i} shared" for i in range(20)]
        vec = TfIdfVectorizer.fit(docs, max_features=5)
        assert len(vec.vocab) == 5
        assert "shared" in vec.vocab  # highest df survives the cap


# --------------------------------------------------------------- MLP model
class TestSparseMLP:
    def test_learns_separable_bags(self):
        # docs about class 0 use tokens {1,2}, class 1 uses {3,4}
        rng = np.random.default_rng(0)
        n = 64
        y = (np.arange(n) % 2).astype(np.int32)
        bags = [
            ([1, 2], [1.0, 1.0]) if c == 0 else ([3, 4], [1.0, 1.0])
            for c in y
        ]
        ids, w = pack_bags([b[0] for b in bags], [b[1] for b in bags])
        ctx = ComputeContext.create(seed=0)
        model = train_mlp(
            ctx, ids, w, y, n_features=5, n_classes=2,
            config=MLPConfig(hidden=16, iterations=150, learning_rate=0.05),
        )
        q_ids, q_w = pack_bags([[1, 2], [3, 4]], [[1.0, 1.0], [1.0, 1.0]])
        pred = model.predict(q_ids, q_w)
        assert pred[0] == 0 and pred[1] == 1
        proba = model.predict_proba(q_ids, q_w)
        assert proba.shape == (2, 2)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)

    def test_single_device_path(self):
        ids, w = pack_bags([[1], [2]], [[1.0], [1.0]])
        y = np.array([0, 1], np.int32)
        model = train_mlp(
            ComputeContext.local(), ids, w, y, n_features=3, n_classes=2,
            config=MLPConfig(hidden=8, iterations=50),
        )
        assert model.w_in.shape == (3, 8)


# --------------------------------------------------------------- end-to-end
@pytest.fixture(autouse=True)
def isolated_storage(tmp_home):
    Storage.reset()
    yield
    Storage.reset()


DOCS = {
    "sports": [
        "the team won the final match with a late goal",
        "striker scores twice as the league season opens",
        "coach praises the defence after a clean sheet win",
        "fans cheer the home team at the stadium tonight",
        "the match ended in a draw after extra time",
        "player transfers dominate the football league news",
    ],
    "tech": [
        "the new chip doubles matrix multiply throughput",
        "compiler updates speed up the neural network training",
        "a software release adds faster tensor kernels",
        "the datacenter deploys accelerators for machine learning",
        "researchers benchmark the model on new hardware",
        "the framework compiles programs for the accelerator",
    ],
}


def _seed_docs(app_id: int):
    le = Storage.get_levents()
    t0 = dt.datetime(2026, 4, 1, tzinfo=dt.timezone.utc)
    n = 0
    for label, docs in DOCS.items():
        for text in docs:
            le.insert(
                Event(
                    "$set", "content", f"doc{n}",
                    properties={"text": text, "label": label},
                    event_time=t0 + dt.timedelta(minutes=n),
                ),
                app_id,
            )
            n += 1


def _variant(algo):
    return variant_from_dict({
        "id": "text-e2e",
        "engineFactory": "templates.textclassification",
        "datasource": {"params": {"app_name": "text-test"}},
        "algorithms": [algo],
    })


class TestTextClassificationEndToEnd:
    @pytest.mark.parametrize(
        "algo",
        [
            {"name": "mlp", "params": {
                "hidden": 32, "iterations": 200, "learning_rate": 0.05}},
            {"name": "nb", "params": {"lambda_": 0.5}},
        ],
        ids=["mlp", "nb"],
    )
    def test_full_lifecycle(self, algo):
        app_id = Storage.get_meta_data_apps().insert(App(0, "text-test"))
        _seed_docs(app_id)

        variant = _variant(algo)
        engine, ep = build_engine(variant)
        ctx = ComputeContext.create(seed=0)
        instance_id = run_train(engine, ep, variant, ctx=ctx)
        models = load_models_for_instance(instance_id, engine, ep, ctx)
        serving = engine.make_serving(ep)
        pairs = engine.algorithms_with_models(ep, models)

        def serve(q):
            return serving.serve(q, [a.predict(m, q) for a, m in pairs])

        cases = [
            (Query(text="the team plays a match in the league"), "sports"),
            (Query(text="the compiler speeds up tensor kernels"), "tech"),
        ]
        for query, want in cases:
            result = serve(query)
            assert isinstance(result, PredictedResult)
            assert result.label == want
            assert 0.0 <= result.confidence <= 1.0

    def test_empty_app_raises_sanity(self):
        Storage.get_meta_data_apps().insert(App(0, "text-test"))
        v = _variant({"name": "nb", "params": {}})
        engine, ep = build_engine(v)
        with pytest.raises(ValueError, match="empty"):
            run_train(engine, ep, v, ctx=ComputeContext.create(seed=0))


class TestSparseNBTraining:
    """train_multinomial_nb_bags ≡ the dense estimator, without the [n, V]."""

    def test_matches_dense(self):
        from pio_tpu.models.naive_bayes import (
            train_multinomial_nb,
            train_multinomial_nb_bags,
        )

        rng = np.random.default_rng(0)
        n, L, V, C = 32, 6, 50, 3
        ids = rng.integers(1, V, size=(n, L)).astype(np.int32)
        w = rng.uniform(0.1, 1.0, size=(n, L)).astype(np.float32)
        # emulate pad slots
        w[:, -2:] = 0.0
        ids[:, -2:] = 0
        y = rng.integers(0, C, size=n).astype(np.int32)

        X = np.zeros((n, V), np.float32)
        rows = np.repeat(np.arange(n), L)
        np.add.at(X, (rows, ids.reshape(-1)), w.reshape(-1))

        dense = train_multinomial_nb(X, y, n_classes=C)
        sparse = train_multinomial_nb_bags(
            ids, w, y, n_features=V, n_classes=C
        )
        np.testing.assert_allclose(
            sparse.log_prior, dense.log_prior, rtol=1e-5
        )
        np.testing.assert_allclose(
            sparse.log_theta, dense.log_theta, rtol=1e-5, atol=1e-6
        )


class TestBagTruncation:
    def test_keeps_highest_weight_tokens(self):
        from pio_tpu.templates.textclassification import _truncate_bag

        ids = np.array([1, 2, 3, 4, 5], np.int32)
        w = np.array([0.1, 0.9, 0.2, 0.8, 0.3], np.float32)
        tids, tw = _truncate_bag(ids, w, 2)
        assert list(tids) == [2, 4]
        assert list(tw) == pytest.approx([0.9, 0.8])

    def test_noop_when_within_width(self):
        from pio_tpu.templates.textclassification import _truncate_bag

        ids = np.array([1, 2], np.int32)
        w = np.array([0.5, 0.5], np.float32)
        tids, tw = _truncate_bag(ids, w, 8)
        assert list(tids) == [1, 2]


class TestMLPServingCache:
    def test_pickle_roundtrip_drops_cache(self):
        import pickle

        from pio_tpu.models.mlp import MLPModel

        m = MLPModel(
            w_in=np.ones((10, 4), np.float32),
            b_in=np.zeros(4, np.float32),
            w_out=np.ones((4, 2), np.float32),
            b_out=np.zeros(2, np.float32),
            n_classes=2,
        )
        ids = np.array([[1, 2, 0, 0]], np.int32)
        w = np.array([[0.5, 0.5, 0.0, 0.0]], np.float32)
        before = m.logits(ids, w)
        assert m._serve_cache is not None
        m2 = pickle.loads(pickle.dumps(m))
        assert m2._serve_cache is None
        np.testing.assert_allclose(m2.logits(ids, w), before, rtol=1e-6)

    def test_repeated_predict_reuses_cache(self):
        from pio_tpu.models.mlp import MLPModel

        m = MLPModel(
            w_in=np.ones((10, 4), np.float32),
            b_in=np.zeros(4, np.float32),
            w_out=np.ones((4, 2), np.float32),
            b_out=np.zeros(2, np.float32),
            n_classes=2,
        )
        ids = np.array([[1, 2, 0, 0]], np.int32)
        w = np.array([[0.5, 0.5, 0.0, 0.0]], np.float32)
        m.logits(ids, w)
        fn1 = m._serve_cache[0]
        m.logits(ids, w)
        assert m._serve_cache[0] is fn1


class TestShippedEvaluation:
    def test_textclassification_evaluation_sweep(self):
        from pio_tpu.controller import ComputeContext
        from pio_tpu.templates.textclassification import (
            textclassification_evaluation,
        )
        from pio_tpu.workflow import run_evaluation

        app_id = Storage.get_meta_data_apps().insert(App(0, "txt-eval"))
        # k-fold needs more than the 9 base docs: repeat each doc with a
        # neutral suffix so every fold's training set covers both labels
        le = Storage.get_levents()
        t0 = dt.datetime(2026, 4, 2, tzinfo=dt.timezone.utc)
        n = 0
        for label, docs in DOCS.items():
            for text in docs:
                for rep in range(3):
                    le.insert(
                        Event(
                            "$set", "content", f"rep{n}",
                            properties={"text": text + f" copy {rep}",
                                        "label": label},
                            event_time=t0 + dt.timedelta(minutes=n),
                        ),
                        app_id,
                    )
                    n += 1
        ev = textclassification_evaluation(
            app_name="txt-eval", eval_k=3, hiddens=(32,)
        )
        result = run_evaluation(
            ev, ev.engine_params_generator, ctx=ComputeContext.create()
        )
        assert result.best_score > 0.6, result.best_score
        insts = Storage.get_meta_data_evaluation_instances().get_all()
        assert insts[0].status == "COMPLETED"


class TestBatchPredict:
    @pytest.mark.parametrize("algo", ["mlp", "nb"])
    def test_batch_matches_loop(self, algo):
        app_id = Storage.get_meta_data_apps().insert(App(0, "text-test"))
        _seed_docs(app_id)
        variant = _variant({"name": algo, "params": {}})
        engine, ep = build_engine(variant)
        from pio_tpu.controller import ComputeContext

        ctx = ComputeContext.create(seed=0)
        iid = run_train(engine, ep, variant, ctx=ctx)
        models = load_models_for_instance(iid, engine, ep, ctx)
        a, model = engine.algorithms_with_models(ep, models)[0]
        from pio_tpu.templates.textclassification import Query

        queries = [
            (i, Query(text=t))
            for i, t in enumerate(
                DOCS["sports"][:2] + DOCS["tech"][:2]
                + ["completely unrelated words entirely"]
            )
        ]
        loop = {i: a.predict(model, q) for i, q in queries}
        bat = dict(a.batch_predict(model, queries))
        for i in loop:
            assert loop[i].label == bat[i].label, i
            assert loop[i].confidence == pytest.approx(
                bat[i].confidence, abs=1e-5
            )
