"""Fleet telemetry plane tests (ISSUE 11): target parsing, federation
failure modes with an injected fetch, the up -> stale -> down walk,
fleetd + follower-sidecar routes over real HTTP, the embedded dashboard
panel, and a 3-member end-to-end federation (query server + replicated
partlog event server + follower) asserted against ground truth."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from pio_tpu.obs import promparse
from pio_tpu.obs.fleet import (
    DEFAULT_INTERVAL_S,
    FleetAggregator,
    TARGETS_ENV,
    parse_targets,
)
from pio_tpu.obs.metrics import MetricsRegistry, monotonic_s
from pio_tpu.obs.promparse import parse_prometheus_text
from pio_tpu.server.fleetd import (
    FleetService,
    FollowerStatusService,
    create_fleet_server,
    create_follower_status_server,
)


def http(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            raw = resp.read()
            if "json" in resp.headers.get("Content-Type", ""):
                return resp.status, json.loads(raw or b"null")
            return resp.status, raw.decode()
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


class TestParseTargets:
    def test_bare_and_schemed_and_dedupe(self):
        got = parse_targets(
            "h1:9001, http://h2:9002/, h1:9001,, https://h3:443"
        )
        assert got == [
            ("h1:9001", "http://h1:9001"),
            ("h2:9002", "http://h2:9002"),
            ("h3:443", "https://h3:443"),
        ]

    def test_empty_specs(self):
        assert parse_targets(None) == []
        assert parse_targets("") == []
        assert parse_targets(" , ,") == []


class _FakeFleet:
    """Dict-of-endpoints fake backing the injected fetch: tests flip
    members dead/alive or swap bodies between scrape passes."""

    def __init__(self, members):
        #: member name -> {path: str-body} | None (None = unreachable)
        self.members = dict(members)

    def fetch(self, url, timeout):
        name = url.split("://", 1)[1].split("/", 1)[0]
        path = "/" + url.split("://", 1)[1].split("/", 1)[1]
        endpoints = self.members.get(name)
        if endpoints is None:
            raise OSError(f"connection refused: {name}")
        if path not in endpoints:
            raise urllib.error.HTTPError(url, 404, "nope", {}, None)
        body = endpoints[path]
        return body.encode() if isinstance(body, str) else body


def _metrics(n):
    return ("# TYPE pio_tpu_q_total counter\n"
            f"pio_tpu_q_total {n}\n")


def _agg(fake, targets="a:1,b:2", **kw):
    kw.setdefault("interval_s", 0.05)
    return FleetAggregator(
        parse_targets(targets), registry=MetricsRegistry(),
        fetch=fake.fetch, **kw,
    )


class TestFailureModes:
    def test_member_down_at_first_scrape(self):
        """Satellite: a member that never answered is down (not stale —
        there is no snapshot to grow stale) and contributes nothing."""
        fake = _FakeFleet({"a:1": {"/metrics": _metrics(5)}, "b:2": None})
        agg = _agg(fake)
        assert agg.scrape_once() == 1
        by = {e["member"]: e for e in agg.fleet_payload()["members"]}
        assert by["a:1"]["status"] == "up"
        assert by["b:2"]["status"] == "down"
        assert "connection refused" in by["b:2"]["lastError"]
        pm = parse_prometheus_text("\n".join(agg.obs.render()))
        assert pm.value("pio_tpu_fleet_member_up", member="a:1") == 1
        assert pm.value("pio_tpu_fleet_member_up", member="b:2") == 0
        assert pm.value("pio_tpu_fleet_scrape_errors_total",
                        member="b:2", reason="unreachable") == 1
        assert pm.value("pio_tpu_q_total", pio_tpu_member="a:1") == 5
        assert pm.value("pio_tpu_q_total", pio_tpu_member="b:2") is None

    def test_member_dies_mid_interval_snapshot_retained(self):
        """Satellite: death between scrapes keeps the last-seen counters
        in the federated sums (no silent disappearance) while the
        liveness gauge drops to 0."""
        fake = _FakeFleet({"a:1": {"/metrics": _metrics(5)},
                           "b:2": {"/metrics": _metrics(7)}})
        agg = _agg(fake, stale_after_s=0.0, down_after_s=0.01)
        assert agg.scrape_once() == 2
        fake.members["b:2"] = None  # SIGKILL between intervals
        time.sleep(0.02)
        assert agg.scrape_once() == 1
        by = {e["member"]: e for e in agg.fleet_payload()["members"]}
        assert by["b:2"]["status"] == "down"
        pm = parse_prometheus_text("\n".join(agg.obs.render()))
        assert pm.value("pio_tpu_fleet_member_up", member="b:2") == 0
        # retained snapshot still federated — sums keep adding up
        assert pm.value("pio_tpu_q_total", pio_tpu_member="b:2") == 7
        assert pm.value("pio_tpu_q_total", pio_tpu_member="a:1") == 5

    def test_malformed_exposition_counted_others_unaffected(self):
        fake = _FakeFleet({
            "a:1": {"/metrics": "{} this is not exposition at all"},
            "b:2": {"/metrics": _metrics(7)},
        })
        agg = _agg(fake)
        assert agg.scrape_once() == 1
        by = {e["member"]: e for e in agg.fleet_payload()["members"]}
        assert by["a:1"]["status"] == "down"
        assert by["a:1"]["scrapeErrors"] == 1
        assert by["b:2"]["status"] == "up"
        pm = parse_prometheus_text("\n".join(agg.obs.render()))
        assert pm.value("pio_tpu_fleet_scrape_errors_total",
                        member="a:1", reason="parse") == 1
        assert pm.value("pio_tpu_q_total", pio_tpu_member="b:2") == 7

    def test_http_error_reason_bucketed(self):
        fake = _FakeFleet({"a:1": {"/other": "x"}})  # 404 on /metrics
        agg = _agg(fake, targets="a:1")
        agg.scrape_once()
        pm = parse_prometheus_text("\n".join(agg.obs.render()))
        assert pm.value("pio_tpu_fleet_scrape_errors_total",
                        member="a:1", reason="http") == 1

    def test_up_stale_down_walk(self):
        """The staleness state machine against a frozen last_ok."""
        fake = _FakeFleet({"a:1": {"/metrics": _metrics(1)}})
        agg = _agg(fake, targets="a:1",
                   stale_after_s=0.04, down_after_s=0.1)
        agg.scrape_once()
        m = agg.members()[0]
        assert m.status(agg.stale_after_s, agg.down_after_s) == "up"
        time.sleep(0.05)
        assert m.status(agg.stale_after_s, agg.down_after_s) == "stale"
        time.sleep(0.07)
        assert m.status(agg.stale_after_s, agg.down_after_s) == "down"
        # a fresh scrape resurrects it
        agg.scrape_once()
        assert m.status(agg.stale_after_s, agg.down_after_s) == "up"

    def test_member_never_scraped_is_unknown(self):
        agg = _agg(_FakeFleet({}), targets="a:1")
        assert agg.fleet_payload()["members"][0]["status"] == "unknown"

    def test_background_loop_scrapes_and_stops(self):
        fake = _FakeFleet({"a:1": {"/metrics": _metrics(1)}})
        agg = _agg(fake, targets="a:1", interval_s=0.02)
        agg.start()
        deadline = monotonic_s() + 5
        while agg.passes < 2 and monotonic_s() < deadline:
            time.sleep(0.01)
        agg.stop()
        assert agg.passes >= 2
        settled = agg.passes
        time.sleep(0.06)
        assert agg.passes == settled  # loop actually stopped

    def test_interval_env_fallback(self, monkeypatch):
        monkeypatch.setenv("PIO_TPU_FLEET_INTERVAL_S", "11.5")
        agg = FleetAggregator(parse_targets("a:1"),
                              registry=MetricsRegistry())
        assert agg.interval_s == 11.5
        assert agg.stale_after_s == pytest.approx(2.5 * 11.5)
        monkeypatch.delenv("PIO_TPU_FLEET_INTERVAL_S")
        agg2 = FleetAggregator(parse_targets("a:1"),
                               registry=MetricsRegistry())
        assert agg2.interval_s == DEFAULT_INTERVAL_S


class TestRollups:
    def _scraped(self, endpoints, targets="a:1"):
        fake = _FakeFleet({t.split("://")[-1]: endpoints
                           for t in targets.split(",")})
        agg = _agg(fake, targets=targets)
        agg.scrape_once()
        return agg

    def test_slo_worst_burn_across_members(self):
        def slo(burn, firing):
            return json.dumps({"slos": [{
                "name": "latency_p99", "objective": 0.999,
                "burnRates": {"5m": burn, "1h": burn / 2},
                "alerts": [{"severity": "page", "firing": firing}],
                "errorBudgetRemaining": 0.5,
            }]})
        fake = _FakeFleet({
            "a:1": {"/metrics": _metrics(1), "/slo.json": slo(0.4, False)},
            "b:2": {"/metrics": _metrics(1), "/slo.json": slo(6.0, True)},
        })
        agg = _agg(fake)
        agg.scrape_once()
        worst = agg.fleet_payload()["slo"]["worstBurn"]["latency_p99"]
        assert worst["member"] == "b:2"
        assert worst["burn"] == 6.0 and worst["window"] == "5m"
        assert worst["firing"] == ["page"]

    def test_partlog_rollup_lag_and_min_acked(self):
        storage = json.dumps({
            "backend": "partlog", "role": "leader", "partitions": 2,
            "durability": "commit",
            "partition_detail": [
                {"partition": 0, "committed_bytes": 100},
                {"partition": 1, "committed_bytes": 50},
            ],
            "replication": {
                "min_acks": 1, "replicas": ["f0", "f1"],
                "followers": [
                    {"follower": "f0", "connected": True,
                     "acked": {"0": 90, "1": 50}},
                    {"follower": "f1", "connected": False,
                     "acked": {"0": 40}},
                ],
            },
        })
        agg = self._scraped({"/metrics": _metrics(1),
                             "/storage.json": storage})
        lead = agg.fleet_payload()["partlog"]["leaders"][0]
        assert lead["durability"] == "commit"
        p0 = lead["partitionDetail"][0]
        lag = {f["follower"]: f["lagBytes"] for f in p0["followers"]}
        assert lag == {"f0": 10, "f1": 60}
        assert p0["minAckedBytes"] == 40
        p1 = lead["partitionDetail"][1]
        assert p1["minAckedBytes"] == 50
        # f1 never acked partition 1 — explicit unknown, not 0
        f1 = [f for f in p1["followers"] if f["follower"] == "f1"][0]
        assert f1["ackedBytes"] is None and f1["lagBytes"] is None

    def test_placement_modes(self):
        def stats(shard, res):
            return json.dumps({
                "residency": {"enabled": res, "paramBytes": 64,
                              "scorers": [{"name": "als", "paramBytes": 64,
                                           "sharded": shard,
                                           "retired": False}]},
                "sharding": {"enabled": shard, "axis": "model"},
            })
        fake = _FakeFleet({
            "a:1": {"/metrics": _metrics(1),
                    "/stats.json": stats(True, True)},
            "b:2": {"/metrics": _metrics(1),
                    "/stats.json": stats(False, False)},
        })
        agg = _agg(fake)
        agg.scrape_once()
        pay = agg.fleet_payload()
        modes = {p["member"]: p["mode"] for p in pay["placement"]}
        assert modes == {"a:1": "mesh", "b:2": "host"}
        by = {e["member"]: e for e in pay["members"]}
        assert by["a:1"]["role"] == "query"  # residency block => query


class TestFleetd:
    def test_empty_targets_rejected(self):
        with pytest.raises(ValueError, match="at least one target"):
            FleetService([])

    def test_routes_and_readiness_gate(self):
        fake = _FakeFleet({"a:1": {"/metrics": _metrics(3)}})
        service = FleetService(parse_targets("a:1"), interval_s=0.05,
                               fetch=fake.fetch)
        # not ready until one full scrape pass — the router must not
        # steer by an empty snapshot
        assert service.readyz(None)[0] == 503
        service.agg.scrape_once()
        assert service.readyz(None)[0] == 200
        assert service.healthz(None)[0] == 200
        st, idx = service.index(None)
        assert st == 200 and idx["members"] == ["a:1"]
        st, pay = service.fleet_json(None)
        assert st == 200 and pay["fleet"]["up"] == 1

    def test_create_fleet_server_over_http(self):
        fake = _FakeFleet({"a:1": {"/metrics": _metrics(3)}})
        server = create_fleet_server("a:1", host="127.0.0.1", port=0)
        server.service.agg._fetch = fake.fetch
        server.service.agg.interval_s = 0.05
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            assert http("GET", url + "/readyz")[0] == 503
            server.service.agg.scrape_once()
            assert http("GET", url + "/readyz")[0] == 200
            st, pay = http("GET", url + "/fleet.json")
            assert st == 200 and pay["fleet"]["members"] == 1
            st, text = http("GET", url + "/metrics")
            assert st == 200
            pm = parse_prometheus_text(text)
            assert pm.value("pio_tpu_q_total", pio_tpu_member="a:1") == 3
            assert pm.value("pio_tpu_fleet_member_up", member="a:1") == 1
            # the aggregator's own families are not double-federated
            assert pm.value("pio_tpu_fleet_member_up", member="a:1",
                            pio_tpu_member="a:1") is None
        finally:
            server.stop()


class TestFollowerSidecar:
    def test_status_surface_over_http(self, tmp_path):
        from pio_tpu.storage.partlog import framing
        from pio_tpu.storage.partlog.replication import FollowerServer

        follower = FollowerServer(str(tmp_path / "mirror"))
        try:
            # simulate the leader handshake: MANIFEST + mirrored bytes
            with open(os.path.join(follower.root, "MANIFEST.json"),
                      "w") as f:
                json.dump({"version": 1, "partitions": 2}, f)
            with open(os.path.join(follower.root, "p000.repl"),
                      "wb") as f:
                f.write(framing.frame(b"hello"))
            sidecar = create_follower_status_server(
                follower, host="127.0.0.1", port=0
            ).start()
            try:
                url = f"http://127.0.0.1:{sidecar.port}"
                st, topo = http("GET", url + "/storage.json")
                assert st == 200
                assert topo["role"] == "follower"
                assert topo["backend"] == "partlog"
                assert topo["partitions"] == 2
                assert topo["replicationPort"] == follower.port
                want = len(framing.frame(b"hello"))
                assert topo["positions"] == {"0": want, "1": 0}
                st, text = http("GET", url + "/metrics")
                assert st == 200
                pm = parse_prometheus_text(text)
                assert pm.value("pio_tpu_repl_follower_position_bytes",
                                partition="0") == want
                assert http("GET", url + "/readyz")[0] == 200
            finally:
                sidecar.stop()
        finally:
            follower.stop()

    def test_no_manifest_means_zero_partitions(self, tmp_path):
        from pio_tpu.storage.partlog.replication import FollowerServer

        follower = FollowerServer(str(tmp_path / "mirror"))
        try:
            service = FollowerStatusService(follower)
            st, topo = service.storage_json(None)
            assert st == 200 and topo["partitions"] == 0
            assert topo["positions"] == {}
        finally:
            follower.stop()


def _train_metrics(steps):
    return ("# TYPE pio_tpu_train_steps_total counter\n"
            f'pio_tpu_train_steps_total{{algo="als"}} {steps}\n')


def _train_json(step=10, total=40):
    return json.dumps({
        "runId": "r1", "engineId": "e1", "phase": "train.0_als",
        "algo": "als", "step": step, "totalSteps": total,
        "epoch": 0.5, "progress": step / total, "etaSeconds": 3.0,
        "loss": 0.5, "examples": 320,
    })


class TestTrainerMember:
    """ISSUE 16 satellite: a `pio train` status sidecar federates as a
    role=trainer member beside the serving fleet."""

    def test_role_and_training_row(self):
        fake = _FakeFleet({
            "t:1": {"/metrics": _train_metrics(10),
                    "/train.json": _train_json()},
            "q:2": {"/metrics": _metrics(5)},
        })
        agg = _agg(fake, targets="t:1,q:2")
        assert agg.scrape_once() == 2
        by = {e["member"]: e for e in agg.fleet_payload()["members"]}
        assert by["t:1"]["role"] == "trainer"
        assert by["t:1"]["status"] == "up"
        tr = by["t:1"]["training"]
        assert tr["runId"] == "r1"
        assert tr["step"] == 10 and tr["totalSteps"] == 40
        assert tr["loss"] == 0.5 and tr["progress"] == 0.25
        assert by["q:2"]["training"] is None
        assert by["q:2"]["role"] != "trainer"

    def test_counters_federate_beside_serving(self):
        """The trainer's step counter joins the federated exposition
        with its member label; serving sums stay untouched."""
        fake = _FakeFleet({
            "t:1": {"/metrics": _train_metrics(12),
                    "/train.json": _train_json(step=12)},
            "a:1": {"/metrics": _metrics(5)},
            "b:2": {"/metrics": _metrics(7)},
        })
        agg = _agg(fake, targets="t:1,a:1,b:2")
        assert agg.scrape_once() == 3
        pm = parse_prometheus_text("\n".join(agg.obs.render()))
        assert pm.value("pio_tpu_train_steps_total", algo="als",
                        pio_tpu_member="t:1") == 12
        total = sum(pm.family("pio_tpu_q_total").values())
        assert total == 12  # 5 + 7, trainer contributes none

    def test_down_walk_when_run_exits(self):
        """The sidecar dies with its run: up while training, down after
        the exit (the last /train.json snapshot — and the trainer role —
        are retained for the post-mortem view)."""
        fake = _FakeFleet({
            "t:1": {"/metrics": _train_metrics(40),
                    "/train.json": _train_json(step=40)},
        })
        agg = _agg(fake, targets="t:1",
                   stale_after_s=0.2, down_after_s=0.4)
        assert agg.scrape_once() == 1
        entry = agg.fleet_payload()["members"][0]
        assert (entry["status"], entry["role"]) == ("up", "trainer")
        fake.members["t:1"] = None  # run over, sidecar gone
        time.sleep(0.5)
        assert agg.scrape_once() == 0
        entry = agg.fleet_payload()["members"][0]
        assert entry["status"] == "down"
        assert entry["role"] == "trainer"
        assert entry["training"]["step"] == 40


class TestTrainStatusSidecar:
    def test_sidecar_surface_over_http(self):
        from pio_tpu.obs import trainwatch
        from pio_tpu.server.fleetd import create_train_status_server

        server = create_train_status_server().start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            # no run in flight: 503 on the progress surface and readiness
            assert http("GET", base + "/train.json")[0] == 503
            assert http("GET", base + "/readyz")[0] == 503
            assert http("GET", base + "/healthz")[0] == 200
            rec = trainwatch.StepRecorder("run-x", "eng-x")
            with trainwatch.recording(rec):
                trainwatch.begin_algo("als", total_steps=4)
                trainwatch.record_steps(
                    2, losses=[0.5, 0.4], examples=24, dur_s=0.01
                )
                st, body = http("GET", base + "/train.json")
                assert st == 200
                assert body["runId"] == "run-x"
                assert body["step"] == 2 and body["totalSteps"] == 4
                assert body["lossWindow"] == [0.5, 0.4]
                assert http("GET", base + "/readyz")[0] == 200
                st, text = http("GET", base + "/metrics")
                assert st == 200
                assert "pio_tpu_train_steps_total" in text
                st, logs = http("GET", base + "/logs.json?n=5")
                assert st == 200 and "logs" in logs
            # run done, recorder deactivated: back to 503
            assert http("GET", base + "/train.json")[0] == 503
        finally:
            server.stop()

    def test_fleet_scrapes_live_sidecar(self):
        """Real HTTP end to end: a FleetAggregator (default fetch) sees
        the sidecar as an up trainer while a recorder is active, and
        walks it down once the sidecar process is gone."""
        from pio_tpu.obs import trainwatch
        from pio_tpu.server.fleetd import create_train_status_server

        server = create_train_status_server().start()
        target = f"127.0.0.1:{server.port}"
        agg = FleetAggregator(
            parse_targets(target), registry=MetricsRegistry(),
            interval_s=0.05, stale_after_s=0.2, down_after_s=0.4,
        )
        rec = trainwatch.StepRecorder("run-live", "eng-live")
        try:
            with trainwatch.recording(rec):
                trainwatch.begin_algo("als", total_steps=8)
                trainwatch.record_steps(3, losses=[1.0], examples=30)
                assert agg.scrape_once() == 1
                entry = agg.fleet_payload()["members"][0]
                assert entry["status"] == "up"
                assert entry["role"] == "trainer"
                assert entry["training"]["runId"] == "run-live"
                assert entry["training"]["step"] == 3
        finally:
            server.stop()
        time.sleep(0.5)
        assert agg.scrape_once() == 0
        entry = agg.fleet_payload()["members"][0]
        assert entry["status"] == "down"
        assert entry["role"] == "trainer"  # snapshot retained


class TestDashboardPanel:
    def test_unconfigured_dashboard_serves_pointer(self, monkeypatch):
        from pio_tpu.server.dashboard import DashboardService

        monkeypatch.delenv(TARGETS_ENV, raising=False)
        svc = DashboardService()
        assert svc.fleet is None
        st, body = svc.fleet_json(None)
        assert st == 404 and "no fleet configured" in body["message"]
        st, page = svc.fleet_html(None)
        assert st == 200 and "no fleet configured" in page.body

    def test_embedded_aggregator_from_env(self, monkeypatch):
        from pio_tpu.server.dashboard import DashboardService

        monkeypatch.setenv(TARGETS_ENV, "a:1,b:2")
        fake = _FakeFleet({"a:1": {"/metrics": _metrics(5)},
                           "b:2": {"/metrics": _metrics(7)}})
        svc = DashboardService()
        assert svc.fleet is not None
        svc.fleet._fetch = fake.fetch
        svc.fleet.scrape_once()
        st, pay = svc.fleet_json(None)
        assert st == 200 and pay["fleet"]["up"] == 2
        st, page = svc.fleet_html(None)
        assert st == 200 and "2 up" in page.body
        # the dashboard's own /metrics carries the federation
        pm = parse_prometheus_text("\n".join(svc.obs.render()))
        assert pm.value("pio_tpu_q_total", pio_tpu_member="a:1") == 5
        assert pm.value("pio_tpu_q_total", pio_tpu_member="b:2") == 7


@pytest.fixture()
def partlog_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_TPU_HOME", str(tmp_path))
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MEM_TYPE", "memory")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE", "MEM")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "PL")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_PL_TYPE", "partlog")
    monkeypatch.setenv(
        "PIO_STORAGE_SOURCES_PL_PATH", str(tmp_path / "partlog")
    )
    monkeypatch.setenv("PIO_TPU_PARTLOG_PARTITIONS", "2")
    from pio_tpu.storage import Storage

    Storage.reset()
    yield monkeypatch
    Storage.reset()


class TestThreeMemberE2E:
    """Satellite: a real fleet — query server + event server with a
    replicated 2-partition partlog + follower sidecar — federated over
    real HTTP, /fleet.json asserted against per-member ground truth."""

    def test_federation_matches_ground_truth(self, partlog_env, tmp_path):
        import pio_tpu.templates  # noqa: F401 — registers engines
        from tests.test_servers import _train
        from pio_tpu.server import create_event_server, create_query_server
        from pio_tpu.storage import AccessKey, App, Storage
        from pio_tpu.storage.partlog.replication import FollowerServer

        mp = partlog_env
        follower = FollowerServer(str(tmp_path / "mirror"))
        mp.setenv("PIO_TPU_PARTLOG_REPLICAS",
                  f"127.0.0.1:{follower.port}")
        Storage.reset()
        app_id = Storage.get_meta_data_apps().insert(App(0, "srv-test"))
        key = Storage.get_meta_data_access_keys().insert(
            AccessKey("", app_id)
        )
        servers = []
        try:
            event = create_event_server(host="127.0.0.1", port=0).start()
            servers.append(event)
            eurl = f"http://127.0.0.1:{event.port}"
            for i in range(8):
                st, _ = http(
                    "POST", f"{eurl}/events.json?accessKey={key}",
                    {"event": "rate", "entityType": "user",
                     "entityId": f"u{i}", "targetEntityType": "item",
                     "targetEntityId": f"i{i}",
                     "properties": {"rating": 4.0},
                     "eventTime": "2026-03-01T10:00:00Z"},
                )
                assert st == 201
            variant, ctx, _ = _train(app_id)
            query, _svc = create_query_server(
                variant, host="127.0.0.1", port=0, ctx=ctx,
                slos=["p99=50ms:99.9"],
            )
            query.start()
            servers.append(query)
            qurl = f"http://127.0.0.1:{query.port}"
            assert http("POST", qurl + "/queries.json",
                        {"user": "u1", "num": 2})[0] == 200
            sidecar = create_follower_status_server(
                follower, host="127.0.0.1", port=0
            ).start()
            servers.append(sidecar)
            surl = f"http://127.0.0.1:{sidecar.port}"

            # ground truth: wait until replication fully acked
            deadline = monotonic_s() + 20
            while monotonic_s() < deadline:
                topo = http("GET", eurl + "/storage.json")[1]
                repl = topo["replication"]
                committed = {
                    str(p["partition"]): p["committed_bytes"]
                    for p in topo["partition_detail"]
                }
                if repl and repl["followers"] and all(
                    repl["min_acked"].get(k) == v
                    for k, v in committed.items()
                ):
                    break
                time.sleep(0.1)
            else:
                pytest.fail(f"replication never caught up: {topo}")

            members = ",".join(
                u.split("://")[1] for u in (qurl, eurl, surl)
            )
            agg = FleetAggregator(
                parse_targets(members), registry=MetricsRegistry(),
                interval_s=0.2,
            )
            agg.scrape_once()
            pay = agg.fleet_payload()
            assert pay["fleet"]["members"] == 3
            assert pay["fleet"]["up"] == 3
            roles = {e["member"]: e["role"] for e in pay["members"]}
            assert roles[qurl.split("://")[1]] == "query"
            assert roles[eurl.split("://")[1]] == "leader"
            assert roles[surl.split("://")[1]] == "follower"

            # replication lag in /fleet.json == ground truth (acked ==
            # committed, so lag 0 and min-acked == committed bytes)
            lead = pay["partlog"]["leaders"][0]
            assert len(lead["partitionDetail"]) == 2
            for p in lead["partitionDetail"]:
                k = str(p["partition"])
                assert p["committedBytes"] == committed[k]
                assert p["minAckedBytes"] == committed[k]
                assert p["followers"][0]["lagBytes"] == 0
                assert p["followers"][0]["connected"] is True
            assert sum(committed.values()) > 0  # events actually landed

            # burn rollup names the query server's SLO
            slo_truth = http("GET", qurl + "/slo.json")[1]["slos"][0]
            worst = pay["slo"]["worstBurn"][slo_truth["name"]]
            assert worst["member"] == qurl.split("://")[1]
            assert worst["objective"] == slo_truth["objective"]

            # federated counter sums equal the per-member scrapes
            fed = parse_prometheus_text(
                "\n".join(agg.obs.render())
            )
            for url in (qurl, eurl, surl):
                name = url.split("://")[1]
                raw = parse_prometheus_text(
                    http("GET", url + "/metrics")[1]
                )
                for (mname, ls), v in raw.samples.items():
                    if promparse._merge_mode(mname, raw.types) != "sum":
                        continue
                    fed_key = (mname, frozenset(
                        set(ls) | {("pio_tpu_member", name)}
                    ))
                    # scrapes raced by live traffic can only grow
                    assert fed.samples.get(fed_key, -1.0) <= v, (
                        mname, ls
                    )
                q = raw.value("pio_tpu_http_requests_total",
                              code="200", path="/metrics")
                if q is not None:
                    assert fed.value(
                        "pio_tpu_http_requests_total", code="200",
                        path="/metrics", pio_tpu_member=name,
                    ) is not None

            # kill the follower sidecar: down within two intervals,
            # retained snapshot still federated
            sidecar.stop()
            servers.remove(sidecar)
            agg.stale_after_s = 0.0
            agg.down_after_s = 0.2
            time.sleep(0.3)
            agg.scrape_once()
            by = {e["member"]: e
                  for e in agg.fleet_payload()["members"]}
            assert by[surl.split("://")[1]]["status"] == "down"
            fed2 = parse_prometheus_text("\n".join(agg.obs.render()))
            assert fed2.value(
                "pio_tpu_repl_follower_position_bytes",
                partition="0", pio_tpu_member=surl.split("://")[1],
            ) is not None
        finally:
            for s in servers:
                s.stop()
            follower.stop()
