"""Two-tower retrieval model tests — dp/tp/ep sharded training.

Run on the simulated 8-device CPU mesh (tests/conftest.py).
"""

import numpy as np
import pytest

from pio_tpu.models.two_tower import (
    TwoTowerConfig,
    TwoTowerModel,
    train_two_tower,
)
from pio_tpu.parallel.mesh import MeshSpec, build_mesh


def _clustered_pairs(n_users=24, n_items=20, n_pairs=1500, groups=4, seed=0):
    """User u interacts only with items in group u % groups."""
    rng = np.random.default_rng(seed)
    per = n_items // groups
    u = rng.integers(0, n_users, n_pairs).astype(np.int32)
    i = ((u % groups) * per + rng.integers(0, per, n_pairs)).astype(np.int32)
    return u, i


CFG = TwoTowerConfig(
    embed_dim=16, hidden=32, out_dim=16, steps=150, batch_size=64
)


@pytest.mark.parametrize(
    "spec",
    [None, MeshSpec(data=8), MeshSpec(data=2, model=4)],
    ids=["single", "dp8", "dp2-tp4"],
)
def test_learns_clustered_preferences(spec):
    n_users, n_items, groups = 24, 20, 4
    u, i = _clustered_pairs(n_users, n_items)
    mesh = None if spec is None else build_mesh(spec)
    m = train_two_tower(mesh, u, i, n_users, n_items, CFG)
    assert m.user_vectors.shape == (n_users, CFG.out_dim)
    assert m.item_vectors.shape == (n_items, CFG.out_dim)
    # unit rows
    np.testing.assert_allclose(
        np.linalg.norm(m.item_vectors, axis=1), 1.0, atol=1e-3
    )
    scores = m.scores(m.user_vectors)
    per = n_items // groups
    hits = sum(
        int(t) // per == uu % groups
        for uu in range(n_users)
        for t in np.argsort(-scores[uu])[:3]
    )
    assert hits / (3 * n_users) > 0.9


def test_sharded_matches_single_device_quality():
    """Same data, same config: sharded training reaches similar loss.

    Exact equality is not expected (batch partition order differs), but
    retrieval structure must agree: per-user top-1 group.
    """
    n_users, n_items, groups = 16, 16, 4
    u, i = _clustered_pairs(n_users, n_items, n_pairs=1000)
    m1 = train_two_tower(None, u, i, n_users, n_items, CFG)
    m2 = train_two_tower(
        build_mesh(MeshSpec(data=4, model=2)), u, i, n_users, n_items, CFG
    )
    per = n_items // groups
    for m in (m1, m2):
        s = m.scores(m.user_vectors)
        top1 = np.argmax(s, axis=1)
        agree = np.mean(top1 // per == np.arange(n_users) % groups)
        assert agree > 0.85


def test_handles_vocab_not_divisible_by_mesh():
    # 23 users / 19 items on a model=4 axis → tables padded internally
    u, i = _clustered_pairs(23, 19, n_pairs=500, groups=1)
    m = train_two_tower(
        build_mesh(MeshSpec(data=2, model=4)), u, i, 23, 19, CFG
    )
    assert m.user_vectors.shape == (23, CFG.out_dim)
    assert m.item_vectors.shape == (19, CFG.out_dim)
    assert np.isfinite(m.user_vectors).all()
