"""ALS model tests: reconstruction quality, mesh-vs-local parity, implicit
mode, edge cases. Runs on the simulated 8-device CPU mesh (conftest)."""

import numpy as np
import pytest

from pio_tpu.models.als import ALSConfig, top_n, train_als
from pio_tpu.parallel.context import ComputeContext


@pytest.fixture(scope="module")
def synthetic():
    rng = np.random.default_rng(0)
    U, I, K = 60, 40, 4
    P = rng.normal(size=(U, K))
    Q = rng.normal(size=(I, K))
    R = P @ Q.T
    mask = rng.random((U, I)) < 0.6
    u_idx, i_idx = np.nonzero(mask)
    return dict(U=U, I=I, R=R, mask=mask, u=u_idx, i=i_idx, r=R[u_idx, i_idx])


CFG = ALSConfig(rank=8, iterations=12, reg=0.01, blocks_per_chunk=64)


class TestALS:
    def test_reconstructs_observed_local(self, synthetic):
        s = synthetic
        f = train_als(ComputeContext.local(), s["u"], s["i"], s["r"], s["U"], s["I"], CFG)
        pred = f.user_factors @ f.item_factors.T
        rmse = np.sqrt(np.mean((pred[s["u"], s["i"]] - s["r"]) ** 2))
        assert rmse < 0.05
        assert f.user_factors.shape == (s["U"], 8)
        assert f.item_factors.shape == (s["I"], 8)

    def test_mesh_matches_local(self, synthetic):
        s = synthetic
        f_local = train_als(
            ComputeContext.local(), s["u"], s["i"], s["r"], s["U"], s["I"], CFG
        )
        f_mesh = train_als(
            ComputeContext.create(), s["u"], s["i"], s["r"], s["U"], s["I"], CFG
        )
        pl = f_local.user_factors @ f_local.item_factors.T
        pm = f_mesh.user_factors @ f_mesh.item_factors.T
        # same predictions up to reduction-order float noise
        assert np.abs(pl - pm).max() < 0.05

    def test_mesh_compact_wire_matches_blocked(self, synthetic,
                                               monkeypatch):
        """The compact mesh wire (sharded h2d → ICI all-gather → device
        dual-layout construction) must train BYTE-IDENTICAL factors to
        the host-packed blocked-f32 shipment — the two paths feed the
        same shard_map trainer and device_pack is bit-identical to the
        host packers. Grid ratings make the u4 rating decode exact."""
        s = synthetic
        rng = np.random.default_rng(5)
        r_grid = (rng.integers(1, 11, len(s["u"])) * 0.5).astype(np.float32)

        monkeypatch.setenv("PIO_TPU_ALS_MESH_WIRE", "blocked")
        st_b = {}
        f_blocked = train_als(
            ComputeContext.create(), s["u"], s["i"], r_grid,
            s["U"], s["I"], CFG, stats=st_b,
        )
        assert st_b["encoding"] == "blocked-f32"

        monkeypatch.setenv("PIO_TPU_ALS_MESH_WIRE", "compact")
        st_c = {}
        f_compact = train_als(
            ComputeContext.create(), s["u"], s["i"], r_grid,
            s["U"], s["I"], CFG, stats=st_c,
        )
        assert st_c["encoding"].startswith("u4"), st_c
        assert np.array_equal(
            f_blocked.user_factors, f_compact.user_factors
        )
        assert np.array_equal(
            f_blocked.item_factors, f_compact.item_factors
        )
        # the whole point: the compact wire crosses the host link with a
        # small fraction of the blocked-f32 bytes
        assert st_c["wire_bytes"] < st_b["wire_bytes"] / 3, (st_c, st_b)

    def test_mesh_compact_wire_chunked_stream(self, synthetic,
                                              monkeypatch):
        """PIO_TPU_ALS_STREAM_MB applies to the mesh path too: the
        encoded wire ships as multiple sharded spans (pipelined puts)
        and the trainer splices them back — factors stay byte-identical
        to blocked-f32 and the stats record the per-chunk timings."""
        s = synthetic
        rng = np.random.default_rng(7)
        r_grid = (rng.integers(1, 11, len(s["u"])) * 0.5).astype(np.float32)

        monkeypatch.setenv("PIO_TPU_ALS_MESH_WIRE", "blocked")
        f_blocked = train_als(
            ComputeContext.create(), s["u"], s["i"], r_grid,
            s["U"], s["I"], CFG,
        )
        monkeypatch.setenv("PIO_TPU_ALS_MESH_WIRE", "compact")
        monkeypatch.setenv("PIO_TPU_ALS_STREAM_MB", "0.001")  # force chunks
        st = {}
        f_chunked = train_als(
            ComputeContext.create(), s["u"], s["i"], r_grid,
            s["U"], s["I"], CFG, stats=st,
        )
        assert st["n_stream"] > 1, st
        assert len(st["h2d_chunk_s"]) == st["n_stream"], st
        assert np.array_equal(
            f_blocked.user_factors, f_chunked.user_factors
        )
        assert np.array_equal(
            f_blocked.item_factors, f_chunked.item_factors
        )

    def test_mesh_compact_planes_wire_with_high_plane(self, monkeypatch):
        """Items ≥ 2^16 force the planes wire with a NON-EMPTY high
        plane — that array rides the sharded put + slice path too and
        must stay byte-identical to blocked."""
        rng = np.random.default_rng(11)
        n = 3000
        u = rng.integers(0, 40, n).astype(np.int32)
        i = rng.integers(0, 70_000, n).astype(np.int32)
        r = (rng.integers(1, 11, n) * 0.5).astype(np.float32)
        cfg = ALSConfig(rank=4, iterations=4, reg=0.05,
                        blocks_per_chunk=16)
        monkeypatch.setenv("PIO_TPU_ALS_MESH_WIRE", "blocked")
        f_b = train_als(ComputeContext.create(), u, i, r, 40, 70_000, cfg)
        monkeypatch.setenv("PIO_TPU_ALS_MESH_WIRE", "compact")
        st = {}
        f_c = train_als(ComputeContext.create(), u, i, r, 40, 70_000,
                        cfg, stats=st)
        assert st["encoding"].endswith("planes"), st
        assert np.array_equal(f_b.user_factors, f_c.user_factors)
        assert np.array_equal(f_b.item_factors, f_c.item_factors)

    def test_mesh_compact_delta_overflow(self, monkeypatch):
        """Within-user item gaps > 4095 exercise the sparse overflow
        list on the mesh wire; factors must match blocked exactly."""
        rng = np.random.default_rng(12)
        n_users, n_items = 24, 60_000
        us, its = [], []
        for uu in range(n_users):
            # a handful of items spread across the full range → most
            # consecutive gaps exceed 4095
            for ii in range(0, n_items, 7013):
                us.append(uu)
                its.append((ii + uu * 311) % n_items)
        u = np.array(us, np.int32)
        i = np.array(its, np.int32)
        r = (rng.integers(1, 11, len(u)) * 0.5).astype(np.float32)
        cfg = ALSConfig(rank=4, iterations=3, reg=0.05,
                        blocks_per_chunk=16)
        monkeypatch.setenv("PIO_TPU_ALS_ITEM_WIRE", "delta12")
        monkeypatch.setenv("PIO_TPU_ALS_MESH_WIRE", "blocked")
        f_b = train_als(ComputeContext.create(), u, i, r,
                        n_users, n_items, cfg)
        monkeypatch.setenv("PIO_TPU_ALS_MESH_WIRE", "compact")
        st = {}
        f_c = train_als(ComputeContext.create(), u, i, r,
                        n_users, n_items, cfg, stats=st)
        assert st["encoding"].endswith("delta12"), st
        assert np.array_equal(f_b.user_factors, f_c.user_factors)
        assert np.array_equal(f_b.item_factors, f_c.item_factors)

    def test_implicit_separates_observed(self, synthetic):
        s = synthetic
        f = train_als(
            ComputeContext.create(),
            s["u"], s["i"], np.abs(s["r"]), s["U"], s["I"],
            ALSConfig(rank=8, iterations=8, reg=0.1, implicit=True, alpha=10,
                      blocks_per_chunk=64),
        )
        pred = f.user_factors @ f.item_factors.T
        hu, hi = np.nonzero(~s["mask"])
        assert pred[s["u"], s["i"]].mean() > pred[hu, hi].mean() + 0.1

    def test_cg_solver_matches_cholesky(self, synthetic):
        """The >32k-entity perf path (CG) must agree with the exact solver
        on the observed entries (well-conditioned config: rank ≤ data
        rank, real regularization)."""
        s = synthetic
        cfg = dict(rank=4, iterations=10, reg=0.1, blocks_per_chunk=64)
        preds = {}
        for solver in ("cholesky", "cg"):
            f = train_als(
                ComputeContext.local(), s["u"], s["i"], s["r"],
                s["U"], s["I"], ALSConfig(solver=solver, **cfg),
            )
            preds[solver] = (f.user_factors @ f.item_factors.T)[
                s["u"], s["i"]
            ]
        err = np.abs(preds["cg"] - preds["cholesky"]).max()
        assert err < 0.05, err

    def test_unknown_solver_raises(self, synthetic):
        s = synthetic
        with pytest.raises(Exception, match="unknown ALS solver"):
            train_als(
                ComputeContext.local(), s["u"], s["i"], s["r"],
                s["U"], s["I"], ALSConfig(solver="choleski"),
            )

    def test_empty_ratings_raises(self):
        with pytest.raises(ValueError, match="at least one rating"):
            train_als(
                ComputeContext.local(),
                np.array([], np.int32), np.array([], np.int32),
                np.array([], np.float32), 5, 5,
            )

    def test_native_packer_matches_numpy(self):
        """C++ packer (pio_tpu/native/als_pack.cpp) must be bit-identical
        to the numpy reference layout."""
        from pio_tpu.models.als import (
            _f32p, _i32p, _i64p, _native_packer, _pack_blocks, _round_up,
        )

        native = _native_packer()
        if native is None:
            pytest.skip("no native toolchain")
        rng = np.random.default_rng(11)
        E, N, W = 50_000, 700, 16
        ent = rng.integers(0, N, E).astype(np.int32)
        other = rng.integers(0, 9999, E).astype(np.int32)
        rat = rng.random(E).astype(np.float32)
        ref = _pack_blocks(ent, other, rat, N, W, 64)
        S = ref[0].shape[0]
        counts = np.zeros(N, np.int64)
        nb = int(native.als_pack_count(_i32p(ent), E, N, W, _i64p(counts)))
        assert S == max(64, _round_up(nb, 64))
        be = np.empty(S, np.int32)
        bo = np.empty(S * W, np.int32)
        br = np.empty(S * W, np.float32)
        native.als_pack_fill(
            _i32p(ent), _i32p(other), _f32p(rat), E, N, W,
            _i64p(counts), S, _i32p(be), _i32p(bo), _f32p(br),
        )
        assert (be == ref[0]).all()
        assert (bo.reshape(S, W) == ref[1]).all()
        assert (br.reshape(S, W) == ref[2]).all()

    def test_native_sort_by_entity_matches_numpy(self):
        """C++ counting sort (the counts wire-format producer) must match
        numpy's stable argsort exactly."""
        from pio_tpu.models.als import (
            _f32p, _i32p, _i64p, _native_packer,
        )

        native = _native_packer()
        if native is None:
            pytest.skip("no native toolchain")
        rng = np.random.default_rng(5)
        E, N = 40_000, 321
        ent = rng.integers(0, N, E).astype(np.int32)
        other = rng.integers(0, 7777, E).astype(np.int32)
        rat = rng.random(E).astype(np.float32)
        counts = np.zeros(N, np.int64)
        native.als_pack_count(_i32p(ent), E, N, 16, _i64p(counts))
        o_sorted = np.empty(E, np.int32)
        r_sorted = np.empty(E, np.float32)
        native.als_sort_by_entity(
            _i32p(ent), _i32p(other), _f32p(rat), E, N, _i64p(counts),
            _i32p(o_sorted), _f32p(r_sorted),
        )
        order = np.argsort(ent, kind="stable")
        assert (o_sorted == other[order]).all()
        assert (r_sorted == rat[order]).all()

    def test_native_and_numpy_paths_agree_bitwise(self, synthetic,
                                                  monkeypatch):
        """Single-device training must not depend on which host packer
        produced the wire format (same stable edge order → same floats)."""
        s = synthetic
        f1 = train_als(
            ComputeContext.local(), s["u"], s["i"], s["r"], s["U"], s["I"],
            CFG,
        )
        monkeypatch.setenv("PIO_TPU_NO_NATIVE", "1")
        f2 = train_als(
            ComputeContext.local(), s["u"], s["i"], s["r"], s["U"], s["I"],
            CFG,
        )
        assert (f1.user_factors == f2.user_factors).all()
        assert (f1.item_factors == f2.item_factors).all()

    def test_non_grid_ratings_train(self):
        """Ratings off the uint8/fp16 grids ride the f32 wire fallback."""
        rng = np.random.default_rng(3)
        E = 400
        u = rng.integers(0, 30, E).astype(np.int32)
        i = rng.integers(0, 20, E).astype(np.int32)
        r = (rng.random(E) * 3.7 + 0.123).astype(np.float32)  # not fp16-exact
        f = train_als(ComputeContext.local(), u, i, r, 30, 20,
                      ALSConfig(rank=4, iterations=3, reg=0.05))
        assert np.isfinite(f.user_factors).all()
        pred = (f.user_factors[u] * f.item_factors[i]).sum(1)
        assert np.sqrt(np.mean((pred - r) ** 2)) < 1.0

    def test_device_pack_matches_host_packers(self):
        """The on-device packer must be bit-identical to the host layout
        (the trainer's correctness rides on ascending block_ent for
        indices_are_sorted segment sums and -1 padding sentinels)."""
        import jax
        import jax.numpy as jnp

        from pio_tpu.models.als import (
            _pack_blocks, _round_up, device_pack,
        )

        rng = np.random.default_rng(21)
        for E, N, W in [(5000, 80, 16), (1, 4, 8), (64, 4, 8), (97, 200, 8)]:
            ent = rng.integers(0, N, E).astype(np.int32)
            oth = rng.integers(0, 999, E).astype(np.int32)
            rat = rng.random(E).astype(np.float32)
            ref = _pack_blocks(ent, oth, rat, N, W, 8)
            S = ref[0].shape[0]
            got = jax.jit(
                device_pack, static_argnums=(3, 4, 5)
            )(jnp.asarray(ent), jnp.asarray(oth), jnp.asarray(rat), N, W, S)
            assert (np.asarray(got[0]) == ref[0]).all(), (E, N, W)
            assert (np.asarray(got[1]) == ref[1]).all(), (E, N, W)
            assert (np.asarray(got[2]) == ref[2]).all(), (E, N, W)

    def test_wide_id_space_plane_encoding(self):
        """Entity ids in [2^16, 2^24) ship as uint16+uint8 planes; a
        mis-widened id would train the wrong rows."""
        rng = np.random.default_rng(5)
        hi_users = [65_536, 70_000, 99_999]  # beyond the uint16 range
        u = np.array(hi_users * 40, np.int32)
        i = rng.integers(0, 8, len(u)).astype(np.int32)
        R = rng.normal(size=(3, 8)).astype(np.float32)
        r = np.array(
            [R[hi_users.index(uu), ii] for uu, ii in zip(u, i)], np.float32
        )
        f = train_als(
            ComputeContext.local(), u, i, r, 100_000, 8,
            ALSConfig(rank=4, iterations=10, reg=0.05),
        )
        pred = (f.user_factors[u] * f.item_factors[i]).sum(1)
        rmse = float(np.sqrt(np.mean((pred - r) ** 2)))
        assert rmse < 0.1, rmse
        # untouched rows stay at their tiny init scale
        assert np.abs(f.user_factors[500]).max() < 0.05

    def test_numpy_fallback_trains(self, synthetic, monkeypatch):
        monkeypatch.setenv("PIO_TPU_NO_NATIVE", "1")
        s = synthetic
        f = train_als(
            ComputeContext.local(), s["u"], s["i"], s["r"], s["U"], s["I"],
            CFG,
        )
        pred = f.user_factors @ f.item_factors.T
        rmse = np.sqrt(np.mean((pred[s["u"], s["i"]] - s["r"]) ** 2))
        assert rmse < 0.05

    def test_single_rating(self):
        f = train_als(
            ComputeContext.create(),
            np.array([0], np.int32), np.array([0], np.int32),
            np.array([5.0], np.float32), 1, 1,
            ALSConfig(rank=2, iterations=3, reg=0.01),
        )
        pred = float(f.user_factors[0] @ f.item_factors[0])
        assert abs(pred - 5.0) < 0.5

    def test_streamed_matches_monolithic(self, synthetic, monkeypatch):
        """The double-buffered chunked shipment must train the same model
        as the single-dispatch path (it differs only in iteration-1
        accumulation grouping — float reduction order)."""
        s = synthetic
        f_mono = train_als(
            ComputeContext.local(), s["u"], s["i"], s["r"], s["U"], s["I"],
            CFG,
        )
        # ~KB-scale threshold forces the max 8 stream chunks on this data
        monkeypatch.setenv("PIO_TPU_ALS_STREAM_MB", "0.0005")
        stats = {}
        f_str = train_als(
            ComputeContext.local(), s["u"], s["i"], s["r"], s["U"], s["I"],
            CFG, stats=stats,
        )
        assert stats["n_stream"] > 1, stats
        pm = f_mono.user_factors @ f_mono.item_factors.T
        ps = f_str.user_factors @ f_str.item_factors.T
        assert np.abs(pm - ps).max() < 0.05

    def test_stream_disable_env(self, synthetic, monkeypatch):
        """PIO_TPU_ALS_STREAM_MB <= 0 means 'streaming off' — the
        intuitive disable value must not degenerate into a 1-byte
        threshold that forces the max chunked path."""
        s = synthetic
        monkeypatch.setenv("PIO_TPU_ALS_STREAM_MB", "0")
        stats = {}
        train_als(
            ComputeContext.local(), s["u"], s["i"], s["r"], s["U"], s["I"],
            CFG, stats=stats,
        )
        assert stats["n_stream"] == 1, stats

    def test_streamed_u4_ratings(self, synthetic, monkeypatch):
        """Half-star-grid ratings ride the nibble-packed u4 wire; the
        decode is exact, so streamed-vs-monolithic differences reduce to
        reduction-order float noise."""
        s = synthetic
        rng = np.random.default_rng(9)
        r_grid = (rng.integers(1, 11, len(s["u"])) * 0.5).astype(np.float32)
        stats = {}
        f_mono = train_als(
            ComputeContext.local(), s["u"], s["i"], r_grid, s["U"], s["I"],
            CFG, stats=stats,
        )
        assert stats["encoding"].startswith("u4"), stats
        monkeypatch.setenv("PIO_TPU_ALS_STREAM_MB", "0.0005")
        stats2 = {}
        f_str = train_als(
            ComputeContext.local(), s["u"], s["i"], r_grid, s["U"], s["I"],
            CFG, stats=stats2,
        )
        assert stats2["n_stream"] > 1
        assert stats2["encoding"].startswith("u4")
        # the two paths saw identical decoded floats (u4 is exact), so
        # they may differ only by reduction-order noise
        pm = f_mono.user_factors @ f_mono.item_factors.T
        ps = f_str.user_factors @ f_str.item_factors.T
        assert np.abs(pm - ps).max() < 0.05

    def test_delta_item_wire_roundtrip(self):
        """The 12-bit delta item wire must reproduce ids EXACTLY (numpy
        reference of the device decode, overflow gaps included)."""
        from pio_tpu.models.als import _encode_items_delta

        rng = np.random.default_rng(3)
        # segmented ids with deliberate >4095 gaps and duplicate items
        counts = np.array([0, 5, 0, 3, 1, 7, 0], np.int64)
        ids = []
        for c in counts:
            row = np.sort(rng.integers(0, 60000, c))
            ids.extend(row.tolist())
        ids = np.array(ids, np.int32)
        d_lo, d_hi, ovf_idx, ovf_val, nbytes = _encode_items_delta(
            ids, counts
        )
        assert nbytes == d_lo.nbytes + d_hi.nbytes + ovf_idx.nbytes \
            + ovf_val.nbytes
        # numpy mirror of _make_math.decode_items("delta12")
        E = len(ids)
        hi = np.stack([d_hi & 0xF, d_hi >> 4], 1).reshape(-1)[:E]
        delta = d_lo.astype(np.uint32) | (hi.astype(np.uint32) << 8)
        delta[ovf_idx] += ovf_val.astype(np.uint32) << 12
        G = np.cumsum(delta, dtype=np.uint32)
        cnt = counts[counts > 0]
        starts = np.zeros(len(cnt), np.int64)
        np.cumsum(cnt[:-1], out=starts[1:])
        prev = np.zeros(E, np.uint32)
        es = np.repeat(np.where(starts > 0, G[starts - 1], 0), cnt)
        got = (G - es).astype(np.int32)
        assert (got == ids).all()

    def test_item_wire_formats_agree_bitwise(self, synthetic, monkeypatch):
        """delta12 decode is integer-exact, so forcing planes vs delta12
        must give BITWISE identical factors (same sorted edge order →
        same floats through the same math)."""
        s = synthetic
        outs = {}
        for wire in ("planes", "delta12"):
            monkeypatch.setenv("PIO_TPU_ALS_ITEM_WIRE", wire)
            outs[wire] = train_als(
                ComputeContext.local(), s["u"], s["i"], s["r"],
                s["U"], s["I"], CFG,
            )
        assert (outs["planes"].user_factors
                == outs["delta12"].user_factors).all()
        assert (outs["planes"].item_factors
                == outs["delta12"].item_factors).all()

    def test_item_wire_formats_agree_streamed(self, synthetic,
                                              monkeypatch):
        """Same bitwise equality through the chunked stream path (the
        delta wire restarts gap chains at chunk boundaries)."""
        s = synthetic
        monkeypatch.setenv("PIO_TPU_ALS_STREAM_MB", "0.0005")
        outs = {}
        for wire in ("planes", "delta12"):
            monkeypatch.setenv("PIO_TPU_ALS_ITEM_WIRE", wire)
            st = {}
            outs[wire] = train_als(
                ComputeContext.local(), s["u"], s["i"], s["r"],
                s["U"], s["I"], CFG, stats=st,
            )
            assert st["n_stream"] > 1
        assert (outs["planes"].user_factors
                == outs["delta12"].user_factors).all()
        assert (outs["planes"].item_factors
                == outs["delta12"].item_factors).all()

    def test_streamed_delta_overflow_and_chunk_carry(self, monkeypatch):
        """Sparse adjacencies over a wide item space: deltas overflow the
        12-bit field (sparse overflow list) AND chunk boundaries split
        users mid-adjacency (the first in-chunk edge ships its ABSOLUTE
        id, itself often an overflow). Streamed delta12 must still match
        planes bitwise."""
        from pio_tpu.models.als import _delta_wire_size

        rng = np.random.default_rng(17)
        U, I, E = 25, 50_000, 1_200
        u = np.sort(rng.integers(0, U, E)).astype(np.int32)
        i = rng.integers(0, I, E).astype(np.int32)  # mean gap ~2k, tail >4095
        r = (rng.integers(1, 11, E) * 0.5).astype(np.float32)
        # sanity: this workload really produces overflow entries
        order = np.lexsort((i, u))
        counts = np.bincount(u, minlength=U).astype(np.int64)
        _, n_ovf = _delta_wire_size(
            np.ascontiguousarray(i[order]), counts
        )
        assert n_ovf > 0, "fixture must exercise the overflow list"

        cfg = ALSConfig(rank=4, iterations=5, reg=0.1, blocks_per_chunk=16)
        monkeypatch.setenv("PIO_TPU_ALS_STREAM_MB", "0.0002")  # many chunks
        outs = {}
        for wire in ("planes", "delta12"):
            monkeypatch.setenv("PIO_TPU_ALS_ITEM_WIRE", wire)
            st = {}
            outs[wire] = train_als(
                ComputeContext.local(), u, i, r, U, I, cfg, stats=st
            )
            assert st["n_stream"] > 1, st
        assert (outs["planes"].user_factors
                == outs["delta12"].user_factors).all()
        assert (outs["planes"].item_factors
                == outs["delta12"].item_factors).all()

    def test_native_delta_encoder_matches_numpy(self, monkeypatch):
        """The C++ delta encoder must be bit-identical to the numpy
        reference (wire format parity, overflow entries included)."""
        from pio_tpu.models.als import (
            _delta_wire_size, _encode_items_delta, _native_packer,
        )

        if _native_packer() is None:
            pytest.skip("no native toolchain")
        rng = np.random.default_rng(12)
        counts = rng.integers(0, 40, 300).astype(np.int64)
        ids = np.concatenate([
            np.sort(rng.integers(0, 60000, c)) for c in counts
        ]).astype(np.int32)
        got_native = _encode_items_delta(ids, counts)
        nb_native, novf_native = _delta_wire_size(ids, counts)
        monkeypatch.setenv("PIO_TPU_NO_NATIVE", "1")
        got_numpy = _encode_items_delta(ids, counts)
        nb_numpy, novf_numpy = _delta_wire_size(ids, counts)
        assert nb_native == nb_numpy == got_native[4]
        assert novf_native == novf_numpy == len(got_native[2])
        for a, b in zip(got_native[:4], got_numpy[:4]):
            assert a.dtype == b.dtype and (a == b).all()

    def test_native_within_entity_sort_matches_lexsort(self):
        """The native (user, item) two-pass sort must equal numpy's
        lexsort order exactly (stability on duplicate pairs included)."""
        from pio_tpu.models.als import (
            _f32p, _i32p, _i64p, _native_packer,
        )

        native = _native_packer()
        if native is None:
            pytest.skip("no native toolchain")
        rng = np.random.default_rng(8)
        E, U, I = 30_000, 200, 500
        u = rng.integers(0, U, E).astype(np.int32)
        i = rng.integers(0, I, E).astype(np.int32)  # many duplicates
        r = rng.random(E).astype(np.float32)
        counts = np.zeros(U, np.int64)
        native.als_pack_count(_i32p(u), E, U, 16, _i64p(counts))
        i_s = np.empty(E, np.int32)
        r_s = np.empty(E, np.float32)
        native.als_sort_by_entity(
            _i32p(u), _i32p(i), _f32p(r), E, U, _i64p(counts),
            _i32p(i_s), _f32p(r_s),
        )
        native.als_sort_within_entity(
            _i32p(i_s), _f32p(r_s), U, _i64p(counts)
        )
        order = np.lexsort((i, u))
        assert (i_s == i[order]).all()
        assert (r_s == r[order]).all()

    def test_nibble_roundtrip(self):
        from pio_tpu.models.als import _encode_ratings, _nibble_pack

        codes = np.array([1, 10, 7, 15, 0, 3, 9], np.uint8)  # odd length
        packed = _nibble_pack(codes)
        assert packed.shape == (4,)
        lo, hi = packed & 0xF, packed >> 4
        inter = np.stack([lo, hi], 1).reshape(-1)[: len(codes)]
        assert (inter == codes).all()
        wire, kind = _encode_ratings(codes.astype(np.float32) * 0.5)
        assert kind == "u4" and (wire == packed).all()
        # beyond the nibble range → u8; off-grid → f16/f32
        assert _encode_ratings(np.array([8.5], np.float32))[1] == "u8"
        assert _encode_ratings(np.array([0.123], np.float32))[1] in (
            "f16", "f32"
        )

    def test_stats_phases(self, synthetic):
        """Profiling mode fills the per-phase breakdown on every path."""
        s = synthetic
        for ctx in (ComputeContext.local(), ComputeContext.create()):
            st = {}
            train_als(ctx, s["u"], s["i"], s["r"], s["U"], s["I"], CFG,
                      stats=st)
            for k in ("pack_s", "wire_bytes", "h2d_s", "device_s",
                      "n_stream", "encoding"):
                assert k in st, (k, st)
            assert st["wire_bytes"] > 0 and st["device_s"] > 0

    def test_entity_counts_not_multiple_of_mesh(self, synthetic):
        # 7 users, 3 items on an 8-device mesh exercises entity padding
        u = np.array([0, 1, 2, 3, 4, 5, 6, 0, 1], np.int32)
        i = np.array([0, 1, 2, 0, 1, 2, 0, 2, 0], np.int32)
        r = np.ones(9, np.float32) * 2.0
        f = train_als(ComputeContext.create(), u, i, r, 7, 3,
                      ALSConfig(rank=2, iterations=4, reg=0.01))
        assert f.user_factors.shape == (7, 2)
        assert f.item_factors.shape == (3, 2)
        assert np.isfinite(f.user_factors).all()


class TestTopN:
    def test_basic(self):
        scores = np.array([0.1, 5.0, 3.0, 4.0])
        idx, vals = top_n(scores, 2)
        assert idx.tolist() == [1, 3]
        assert vals.tolist() == [5.0, 4.0]

    def test_exclude(self):
        scores = np.array([0.1, 5.0, 3.0, 4.0])
        idx, _ = top_n(scores, 2, exclude=np.array([1]))
        assert idx.tolist() == [3, 2]

    def test_n_larger_than_items(self):
        idx, _ = top_n(np.array([1.0, 2.0]), 10)
        assert idx.tolist() == [1, 0]
